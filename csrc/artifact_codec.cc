// Native artifact codec: the host-side hot path of the result envelope.
//
// The reference's output processing (swarm/output_processor.py:46-58,
// 121-136) hashes, base64-encodes and PNG-encodes every generated image in
// Python/PIL at the GPU->host boundary. On a TPU worker pushing multiple
// images per second per chip, that Python encode path becomes the
// serialized host bottleneck — so this framework implements it natively:
// SHA-256, base64, box-filter thumbnailing and PNG (zlib) encoding in C++,
// exposed through a C ABI consumed via ctypes
// (chiaswarm_tpu/native/__init__.py) with a PIL fallback when the shared
// object is unavailable.
//
// Build: g++ -O2 -shared -fPIC artifact_codec.cc -lz -o libartifact.so

#include <cstdint>
#include <cstring>
#include <vector>

#include <zlib.h>

namespace {

// ----------------------------------------------------------- SHA-256

constexpr uint32_t kSha256K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t Rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

void Sha256Block(const uint8_t* p, uint32_t h[8]) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i) {
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  }
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + s1 + ch + kSha256K[i] + w[i];
    uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

// ------------------------------------------------------------- PNG

void PushU32(std::vector<uint8_t>* out, uint32_t v) {
  out->push_back(uint8_t(v >> 24));
  out->push_back(uint8_t(v >> 16));
  out->push_back(uint8_t(v >> 8));
  out->push_back(uint8_t(v));
}

void PushChunk(std::vector<uint8_t>* out, const char type[4],
               const uint8_t* data, size_t n) {
  PushU32(out, uint32_t(n));
  size_t start = out->size();
  out->insert(out->end(), type, type + 4);
  out->insert(out->end(), data, data + n);
  uint32_t crc = crc32(0L, Z_NULL, 0);
  crc = crc32(crc, out->data() + start, uInt(n + 4));
  PushU32(out, crc);
}

}  // namespace

extern "C" {

// 64-hex-char SHA-256 digest + NUL into out[65].
void sha256_hex(const uint8_t* data, uint64_t n, char* out) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t i = 0;
  for (; i + 64 <= n; i += 64) Sha256Block(data + i, h);
  uint8_t tail[128];
  uint64_t rem = n - i;
  std::memcpy(tail, data + i, rem);
  tail[rem] = 0x80;
  uint64_t pad = (rem < 56) ? 64 : 128;
  std::memset(tail + rem + 1, 0, pad - rem - 1 - 8);
  uint64_t bits = n * 8;
  for (int b = 0; b < 8; ++b)
    tail[pad - 1 - b] = uint8_t(bits >> (8 * b));
  Sha256Block(tail, h);
  if (pad == 128) Sha256Block(tail + 64, h);
  static const char* hex = "0123456789abcdef";
  for (int j = 0; j < 8; ++j) {
    for (int b = 0; b < 4; ++b) {
      uint8_t byte = uint8_t(h[j] >> (24 - 8 * b));
      out[j * 8 + b * 2] = hex[byte >> 4];
      out[j * 8 + b * 2 + 1] = hex[byte & 15];
    }
  }
  out[64] = '\0';
}

// base64 encode; out must hold 4*((n+2)/3) bytes. Returns bytes written.
uint64_t b64_encode(const uint8_t* data, uint64_t n, char* out) {
  static const char* tbl =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  uint64_t o = 0, i = 0;
  for (; i + 3 <= n; i += 3) {
    uint32_t v = (uint32_t(data[i]) << 16) | (uint32_t(data[i + 1]) << 8) |
                 data[i + 2];
    out[o++] = tbl[(v >> 18) & 63];
    out[o++] = tbl[(v >> 12) & 63];
    out[o++] = tbl[(v >> 6) & 63];
    out[o++] = tbl[v & 63];
  }
  if (i < n) {
    uint32_t v = uint32_t(data[i]) << 16;
    if (i + 1 < n) v |= uint32_t(data[i + 1]) << 8;
    out[o++] = tbl[(v >> 18) & 63];
    out[o++] = tbl[(v >> 12) & 63];
    out[o++] = (i + 1 < n) ? tbl[(v >> 6) & 63] : '=';
    out[o++] = '=';
  }
  return o;
}

// Box-filter downsample RGB8 (h, w) -> (th, tw). out holds tw*th*3.
void thumbnail_rgb(const uint8_t* rgb, uint32_t w, uint32_t h,
                   uint32_t tw, uint32_t th, uint8_t* out) {
  for (uint32_t ty = 0; ty < th; ++ty) {
    uint32_t y0 = uint64_t(ty) * h / th, y1 = uint64_t(ty + 1) * h / th;
    if (y1 <= y0) y1 = y0 + 1;
    for (uint32_t tx = 0; tx < tw; ++tx) {
      uint32_t x0 = uint64_t(tx) * w / tw, x1 = uint64_t(tx + 1) * w / tw;
      if (x1 <= x0) x1 = x0 + 1;
      uint64_t acc[3] = {0, 0, 0};
      for (uint32_t y = y0; y < y1; ++y)
        for (uint32_t x = x0; x < x1; ++x)
          for (int c = 0; c < 3; ++c)
            acc[c] += rgb[(uint64_t(y) * w + x) * 3 + c];
      uint64_t cnt = uint64_t(y1 - y0) * (x1 - x0);
      for (int c = 0; c < 3; ++c)
        out[(uint64_t(ty) * tw + tx) * 3 + c] = uint8_t(acc[c] / cnt);
    }
  }
}

// PNG-encode RGB8 (h, w). Writes into out (cap bytes); returns bytes
// written, or 0 if cap is too small. Filter type 0 (None) per scanline +
// zlib level 6 — artifact PNGs favor encode speed over ratio.
uint64_t png_encode_rgb(const uint8_t* rgb, uint32_t w, uint32_t h,
                        uint8_t* out, uint64_t cap) {
  std::vector<uint8_t> raw;
  raw.reserve(uint64_t(h) * (uint64_t(w) * 3 + 1));
  for (uint32_t y = 0; y < h; ++y) {
    raw.push_back(0);  // filter: None
    const uint8_t* row = rgb + uint64_t(y) * w * 3;
    raw.insert(raw.end(), row, row + uint64_t(w) * 3);
  }
  uLongf zcap = compressBound(uLong(raw.size()));
  std::vector<uint8_t> z(zcap);
  if (compress2(z.data(), &zcap, raw.data(), uLong(raw.size()), 6) != Z_OK)
    return 0;
  z.resize(zcap);

  std::vector<uint8_t> png;
  static const uint8_t sig[8] = {137, 80, 78, 71, 13, 10, 26, 10};
  png.insert(png.end(), sig, sig + 8);
  uint8_t ihdr[13];
  ihdr[0] = uint8_t(w >> 24); ihdr[1] = uint8_t(w >> 16);
  ihdr[2] = uint8_t(w >> 8);  ihdr[3] = uint8_t(w);
  ihdr[4] = uint8_t(h >> 24); ihdr[5] = uint8_t(h >> 16);
  ihdr[6] = uint8_t(h >> 8);  ihdr[7] = uint8_t(h);
  ihdr[8] = 8;   // bit depth
  ihdr[9] = 2;   // color type: truecolor RGB
  ihdr[10] = 0; ihdr[11] = 0; ihdr[12] = 0;
  PushChunk(&png, "IHDR", ihdr, 13);
  PushChunk(&png, "IDAT", z.data(), z.size());
  PushChunk(&png, "IEND", nullptr, 0);

  if (png.size() > cap) return 0;
  std::memcpy(out, png.data(), png.size());
  return png.size();
}

}  // extern "C"
