"""Image-quality metrics for the step-collapse gates (ISSUE 12).

DeepCache feature reuse and few-step sampling trade compute for image
fidelity, so they ship quality-GATED the way int8 weights shipped
parity-gated (ISSUE 8): the bench and tests/test_fewstep.py compare the
accelerated output against its full-compute reference with PSNR/SSIM
and refuse the trick below threshold (PSNR >= 30 dB, SSIM >= 0.9).

Pure numpy on uint8/float host images — no jax, no scipy, no cv2, so
the gate runs identically on any host. SSIM follows Wang et al. 2004
with a uniform box window (integral-image mean/variance) — the uniform
window is deterministic and dependency-free; it agrees with the
gaussian-window reference implementation to well under the gate's
margin on natural images.
"""

from __future__ import annotations

import numpy as np


def _as_float(img: np.ndarray) -> np.ndarray:
    img = np.asarray(img)
    return img.astype(np.float64)


def psnr(a: np.ndarray, b: np.ndarray, *, data_range: float = 255.0,
         ) -> float:
    """Peak signal-to-noise ratio in dB over the whole array pair.

    Identical inputs return ``inf``. Shapes must match — a silent
    broadcast would gate the wrong pixels."""
    a, b = _as_float(a), _as_float(b)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    mse = float(np.mean((a - b) ** 2))
    if mse == 0.0:
        return float("inf")
    return float(10.0 * np.log10(data_range ** 2 / mse))


def _box_mean(img: np.ndarray, win: int) -> np.ndarray:
    """(H, W) local means over a win x win box for every valid window
    position, via an integral image — O(HW), no dependencies."""
    pad = np.zeros((img.shape[0] + 1, img.shape[1] + 1), np.float64)
    np.cumsum(np.cumsum(img, axis=0), axis=1, out=pad[1:, 1:])
    s = (pad[win:, win:] - pad[:-win, win:]
         - pad[win:, :-win] + pad[:-win, :-win])
    return s / (win * win)


def ssim(a: np.ndarray, b: np.ndarray, *, data_range: float = 255.0,
         win: int = 7) -> float:
    """Mean structural similarity over all channels (uniform window).

    Accepts (H, W), (H, W, C) or (B, H, W, C); channels and batch
    members are scored independently and averaged."""
    a, b = _as_float(a), _as_float(b)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    if a.ndim == 2:
        a, b = a[..., None], b[..., None]
    if a.ndim == 3:
        a, b = a[None], b[None]
    if a.shape[1] < win or a.shape[2] < win:
        raise ValueError(f"images smaller than the {win}x{win} window")
    c1 = (0.01 * data_range) ** 2
    c2 = (0.03 * data_range) ** 2
    scores = []
    for bi in range(a.shape[0]):
        for ch in range(a.shape[-1]):
            x, y = a[bi, :, :, ch], b[bi, :, :, ch]
            mx, my = _box_mean(x, win), _box_mean(y, win)
            mxx = _box_mean(x * x, win) - mx * mx
            myy = _box_mean(y * y, win) - my * my
            mxy = _box_mean(x * y, win) - mx * my
            num = (2 * mx * my + c1) * (2 * mxy + c2)
            den = (mx ** 2 + my ** 2 + c1) * (mxx + myy + c2)
            scores.append(np.mean(num / den))
    return float(np.mean(scores))


def quality_report(test: np.ndarray, reference: np.ndarray, *,
                   psnr_floor: float = 30.0,
                   ssim_floor: float = 0.9) -> dict:
    """The step-collapse quality gate as one stampable dict: PSNR/SSIM
    of ``test`` against ``reference`` plus the pass verdicts at the
    shipped floors (BENCH json stamps this; tests assert ``passed``)."""
    p = psnr(test, reference)
    s = ssim(test, reference)
    return {
        # bit-identical inputs: null, not inf — BENCH json must stay
        # strict-JSON parseable (json.dumps prints inf as bare
        # 'Infinity', which jq/JSON.parse reject)
        "psnr_db": round(p, 2) if np.isfinite(p) else None,
        "ssim": round(s, 4),
        "psnr_floor_db": psnr_floor,
        "ssim_floor": ssim_floor,
        "passed": bool(p >= psnr_floor and s >= ssim_floor),
    }
