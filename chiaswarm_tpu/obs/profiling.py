"""TPU profiler hooks: XLA traces on demand, named device regions.

The metrics registry says *how often* and the span tracer says *where
in the worker* — this module answers *what the chip did*: it wraps
``jax.profiler`` (routed through ``core/compat.py`` so everything
degrades to a no-op when jax or the profiler plugin is absent) into

- :func:`annotate` — ``TraceAnnotation`` regions naming the serving
  hot paths (lane step / lane decode / solo generate) inside an XLA
  trace, so an XProf/Perfetto timeline reads in serving vocabulary
  instead of raw HLO module names; always-on and free outside an
  active capture;
- :func:`capture` — a one-shot, duration-bounded trace capture backing
  the worker's ``/debug/profile?seconds=N`` endpoint (node/worker.py);
  output lands under the directory named by :data:`PROFILE_DIR_ENV`
  (or an explicit ``?dir=``/``out=``);
- :func:`job_profile` — the per-job opt-in trace the executor runs
  when :data:`PROFILE_DIR_ENV` is set.

The profiler is a process-global singleton, so one :data:`_CAPTURE_LOCK`
serializes all of the above: a busy profiler yields an explicit
"busy" result (or an unprofiled job), never a crashed job.

This module is importable without jax (stdlib + lazy compat), like the
rest of ``chiaswarm_tpu/obs``.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Iterator

log = logging.getLogger("chiaswarm.obs.profiling")

#: directory on-demand captures (and the executor's per-job traces)
#: write under; ``/debug/profile`` falls back to it when the request
#: names no explicit directory
PROFILE_DIR_ENV = "CHIASWARM_PROFILE_DIR"

#: ceiling for /debug/profile?seconds=N — a forgotten capture must not
#: trace (and slow) the worker forever
MAX_CAPTURE_S = 120.0

_CAPTURE_LOCK = threading.Lock()


def profiler_available() -> bool:
    """Can this process record an XLA trace at all?"""
    try:
        import jax

        return hasattr(jax, "profiler")
    except Exception:
        return False


@contextlib.contextmanager
def annotate(name: str, **kwargs: Any) -> Iterator[None]:
    """Name a device region inside an XLA trace (no-op when no trace is
    recording, and a full no-op without jax). Cheap enough to stay
    always-on around lane steps and decodes."""
    try:
        from chiaswarm_tpu.core import compat

        annotation = compat.trace_annotation(name, **kwargs)
        annotation.__enter__()
    except Exception:
        # profiling must never fail the job it is observing
        annotation = None
    try:
        yield
    finally:
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:
                pass


def default_profile_dir() -> str:
    return os.environ.get(PROFILE_DIR_ENV, "").strip()


def capture(seconds: float, out: str | None = None) -> dict[str, Any]:
    """Record an XLA trace for ``seconds`` (blocking; run it from a
    thread — node/worker.py uses ``run_in_executor``).

    Returns ``{"status": "ok", "dir": path, "seconds": n}`` or an
    explicit error/busy dict; raises nothing: this backs an HTTP
    endpoint and its failure modes (busy profiler, no backend, bad
    dir) are expected operator-visible states, not crashes.
    """
    seconds = max(0.1, min(float(seconds), MAX_CAPTURE_S))
    out = out or default_profile_dir()
    if not out:
        return {"status": "error",
                "error": f"no capture directory: set {PROFILE_DIR_ENV} "
                         f"or pass ?dir="}
    if not profiler_available():
        return {"status": "error",
                "error": "jax.profiler is unavailable in this process"}
    if not _CAPTURE_LOCK.acquire(blocking=False):
        return {"status": "busy",
                "error": "another profiler capture is already running "
                         "(the profiler is process-global)"}
    try:
        from chiaswarm_tpu.core import compat

        target = os.path.join(
            out, time.strftime("capture-%Y%m%d-%H%M%S"))
        os.makedirs(target, exist_ok=True)
        compat.profiler_start_trace(target)
        try:
            time.sleep(seconds)
        finally:
            compat.profiler_stop_trace()
        log.info("profiler capture (%.1fs) written to %s", seconds, target)
        return {"status": "ok", "dir": target, "seconds": seconds}
    except Exception as exc:
        log.warning("profiler capture failed: %s", exc)
        return {"status": "error", "error": f"{type(exc).__name__}: {exc}"}
    finally:
        _CAPTURE_LOCK.release()


@contextlib.contextmanager
def job_profile(job_id: Any,
                profile_dir: str | None = None) -> Iterator[bool]:
    """Per-job XLA trace when :data:`PROFILE_DIR_ENV` is set — the
    executor's opt-in hook (node/executor.py). Yields True when a trace
    is actually recording. Shares :data:`_CAPTURE_LOCK` with
    :func:`capture`: overlapping jobs (multi-slot workers) and
    on-demand captures skip rather than fight over the process-global
    profiler."""
    profile_dir = (default_profile_dir() if profile_dir is None
                   else profile_dir)
    if not profile_dir:
        yield False
        return
    if not _CAPTURE_LOCK.acquire(blocking=False):
        log.info("job %s not profiled: profiler busy", job_id)
        yield False
        return
    try:
        target = os.path.join(profile_dir, str(job_id or "job"))
        try:
            from chiaswarm_tpu.core import compat

            cm = compat.profiler_trace(target)
            cm.__enter__()
        except Exception as exc:
            log.warning("job %s profile failed to start (%s); job "
                        "continues unprofiled", job_id, exc)
            yield False
            return
        try:
            yield True
        finally:
            try:
                cm.__exit__(None, None, None)
                log.info("job %s profile written to %s", job_id, target)
            except Exception as exc:
                log.warning("job %s profile failed to finalize (%s)",
                            job_id, exc)
    finally:
        _CAPTURE_LOCK.release()
