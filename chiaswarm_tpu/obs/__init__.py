"""swarmscope + swarmlens — the unified observability subsystem.

Five layers, one vocabulary (ISSUE 4 + ISSUE 11):

- ``metrics``   — Prometheus-style :class:`Registry` of counters /
                  gauges / histograms; ``/metrics`` exposition, BENCH
                  snapshots, and the ``/healthz`` read-through view.
- ``trace``     — Dapper-style per-job span trees on ``perf_counter``
                  (poll -> execute -> encode/step/decode -> upload),
                  kept in a bounded ring and exported as
                  Perfetto-loadable JSON at ``/debug/traces``.
- ``profiling`` — ``jax.profiler`` behind ``core/compat.py``:
                  ``TraceAnnotation`` names for the serving hot paths
                  and on-demand XLA captures (``/debug/profile``,
                  ``CHIASWARM_PROFILE_DIR``).
- ``numerics``  — the swarmlens flight recorder (ISSUE 11): named
                  probes compiled INTO jitted programs behind
                  ``CHIASWARM_NUMERICS`` (env off = identity at trace
                  time), per-step per-shard summaries in a bounded
                  ring at ``/debug/numerics``, and the stream format
                  ``tools/divergence_bisect.py`` aligns.
- ``hlocost``   — the static HLO cost model (conv/dot/flash FLOPs, HBM
                  bytes, roofline attainment) shared by
                  ``tools/op_roofline.py`` and the BENCH stamping.

Like ``analysis/``, this package imports without jax, aiohttp, or any
accelerator — host tools, the linter environment, and CI jobs can load
it anywhere. Instrumentation is always-on and allocation-light;
profiler capture and numerics taps are opt-in.
"""

from chiaswarm_tpu.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    render_all,
)
from chiaswarm_tpu.obs.trace import (  # noqa: F401
    TRACE_KEY,
    TRACE_RING,
    JobTrace,
    Span,
    TraceRing,
    activate,
    attach,
    current_span,
    detach,
    job_trace,
    span,
)
from chiaswarm_tpu.obs.profiling import (  # noqa: F401
    PROFILE_DIR_ENV,
    annotate,
    capture,
    job_profile,
    profiler_available,
)
from chiaswarm_tpu.obs.numerics import (  # noqa: F401
    RING,
    TAPS,
    NumericsRing,
    TapRegistry,
    numerics_enabled,
    tap,
)
