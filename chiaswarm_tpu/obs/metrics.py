"""Prometheus-style metrics registry: counters, gauges, histograms.

The worker grew three generations of ad-hoc telemetry — the resilience
counters (PR 2), the stepper lane stats (PR 3), and the seed's bare
``/healthz`` dict. This module is the one vocabulary they all migrate
onto: a :class:`Registry` of named metrics with label support, rendered
in the Prometheus text exposition format at ``/metrics``
(node/worker.py) and snapshot as JSON into BENCH runs (benchmark.py)
and ``/healthz`` (which stays a read-through view for back-compat).

Design constraints, in order:

- **stdlib only** (like ``analysis/``): importable with no jax, no
  aiohttp — the linter, host tools, and ``core/compile_cache.py`` all
  load it.
- **allocation-light on the hot path**: an ``inc()``/``observe()`` is a
  dict lookup + float add under one lock; no per-event objects.
- **hermetic**: :class:`Registry` is a class, not only a module global.
  Each Worker owns its own registry (multiple hermetic workers share a
  test process; their counters must not bleed into each other), while
  process-wide machinery (the compile cache, lane step timing) uses the
  shared :data:`REGISTRY`. ``render_all`` merges both for ``/metrics``.

Counters are monotonic. For sources that already maintain their own
monotonic totals (the stepper's lane stats), a *collector* callback
registered via :meth:`Registry.add_collector` mirrors them in at scrape
time with :meth:`Counter.set_to` — the Prometheus collect-on-scrape
pattern, not a license to decrement.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Iterable, Sequence

log = logging.getLogger("chiaswarm.obs")

#: default histogram buckets (seconds): spans poll blips (~ms) through
#: cold XLA compiles (~minutes). Callers with tighter ranges pass their
#: own.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   600.0)


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Base: one named family holding a value per label-values tuple."""

    typ = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}
        if not self.labelnames:
            # unlabeled series exist from registration, so /metrics shows
            # an explicit 0 instead of omitting the family entirely
            self._values[()] = 0.0

    def _key(self, labels: dict[str, Any]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def series(self) -> dict[tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)

    # ---- exposition ----

    def _series_name(self, suffix: str, key: tuple[str, ...],
                     extra: tuple[tuple[str, str], ...] = ()) -> str:
        pairs = tuple(zip(self.labelnames, key)) + extra
        if not pairs:
            return f"{self.name}{suffix}"
        inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
        return f"{self.name}{suffix}{{{inner}}}"

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.typ}")
        series = self.series()
        for key in sorted(series):
            lines.append(f"{self._series_name('', key)} "
                         f"{_format_value(series[key])}")
        return lines

    def snapshot(self) -> dict[str, Any]:
        return {"type": self.typ, "help": self.help,
                "values": {",".join(k) if k else "": v
                           for k, v in sorted(self.series().items())}}


class Counter(_Metric):
    """Monotonic counter. ``inc`` adds; ``set_to`` mirrors an external
    monotonic total in (collector use only — never goes backward)."""

    typ = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_to(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0),
                                    float(value))


class Gauge(_Metric):
    typ = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative ``le`` buckets + sum/count)."""

    typ = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._totals: dict[tuple[str, ...], int] = {}
        self._values.clear()  # histograms expose bucket/sum/count instead

    def observe(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * len(self.buckets)
                self._sums[key] = 0.0
                self._totals[key] = 0
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            self._sums[key] += value
            self._totals[key] += 1

    def count(self, **labels: Any) -> int:
        with self._lock:
            return self._totals.get(self._key(labels), 0)

    def sum(self, **labels: Any) -> float:
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels: Any) -> float | None:
        """Estimate the q-quantile (q in [0, 1]) from the bucket counts
        by linear interpolation inside the covering bucket — the
        Prometheus ``histogram_quantile`` estimate, computed locally.

        Returns None for an empty series. Mass above the last finite
        bucket clamps to that bound (the estimate cannot exceed what
        the buckets resolve), so pick buckets that cover the tail you
        care about. This is the primitive behind the BENCH
        step-seconds percentiles and the measured hang-budget
        suggestion (serving/guard.py, ISSUE 11)."""
        key = self._key(labels)
        with self._lock:
            # COPY under the lock: a concurrent observe() mutates the
            # bucket list in place, and iterating the live list against
            # a stale total skews the interpolation
            counts = list(self._counts.get(key) or ())
            total = self._totals.get(key, 0)
        if not counts or total <= 0:
            return None
        q = min(max(float(q), 0.0), 1.0)
        rank = q * total
        cum = 0
        for i, n in enumerate(counts):
            if not n:
                continue
            lo = self.buckets[i - 1] if i else 0.0
            hi = self.buckets[i]
            if cum + n >= rank:
                frac = (rank - cum) / n
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += n
        return self.buckets[-1]  # overflow mass: clamp to the last bound

    def percentiles(self, qs: Sequence[float] = (0.5, 0.9, 0.99),
                    **labels: Any) -> dict[str, float] | None:
        """{"p50": ..., "p90": ..., ...} or None when empty."""
        out = {}
        for q in qs:
            v = self.percentile(q, **labels)
            if v is None:
                return None
            out[f"p{str(round(q * 100, 1)).rstrip('0').rstrip('.')}"] = v
        return out

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.typ}")
        with self._lock:
            items = [(k, list(c), self._sums[k], self._totals[k])
                     for k, c in sorted(self._counts.items())]
        for key, counts, total_sum, total in items:
            cum = 0
            for bound, n in zip(self.buckets, counts):
                cum += n
                lines.append(
                    f"{self._series_name('_bucket', key, (('le', _format_value(bound)),))} "
                    f"{cum}")
            lines.append(
                f"{self._series_name('_bucket', key, (('le', '+Inf'),))} "
                f"{total}")
            lines.append(f"{self._series_name('_sum', key)} "
                         f"{_format_value(total_sum)}")
            lines.append(f"{self._series_name('_count', key)} {total}")
        return lines

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "type": self.typ,
                "help": self.help,
                "buckets": list(self.buckets),
                "values": {
                    ",".join(k) if k else "": {
                        "counts": list(c),
                        "sum": self._sums[k],
                        "count": self._totals[k],
                    }
                    for k, c in sorted(self._counts.items())
                },
            }


class Registry:
    """Named metric families + scrape-time collector callbacks.

    ``counter``/``gauge``/``histogram`` are get-or-create: asking twice
    for the same name returns the same object (so modules can declare
    their metrics independently), but re-declaring with a different type
    or label set is a programming error and raises.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}")
                return existing
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a callback run before every render/snapshot — the
        place to mirror externally-maintained state (lane stats, queue
        depths, breaker states) into gauges/counters at scrape time."""
        with self._lock:
            self._collectors.append(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # a broken mirror must never break scrapes
                log.exception("metrics collector failed")

    def _sorted_metrics(self) -> list[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        self.collect()
        lines: list[str] = []
        for metric in self._sorted_metrics():
            lines.extend(metric.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, Any]:
        """JSON-able view of every family — the BENCH ``metrics`` key
        and the programmatic twin of ``render()``."""
        self.collect()
        return {m.name: m.snapshot() for m in self._sorted_metrics()}


def render_all(registries: Iterable[Registry]) -> str:
    """Concatenate several registries' expositions (the worker's own
    registry + the process-global one) into one scrape body."""
    return "".join(r.render() for r in registries)


#: process-global registry: compile-cache activity, lane step timing —
#: state that is genuinely one-per-process. Worker-scoped counters live
#: on the worker's own Registry instance instead (hermetic tests).
REGISTRY = Registry()

#: occupancy-ratio buckets: one per eighth of the lane, matching the
#: pow2 lane widths (a 16-wide lane quantizes occupancy to sixteenths;
#: eighths keep the histogram readable at every width)
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def lane_occupancy_histogram(registry: Registry | None = None) -> Histogram:
    """Per-lane occupancy ratio (active rows / lane width), sampled at
    every lane step by serving/stepper.py and exposed at ``/metrics``.

    THE padding-efficiency signal for lane-width tuning: a lane stepping
    at 0.25 occupancy spends 3/4 of its batched UNet FLOPs on padding
    rows, which the scalar ``padding_waste`` ratio in ``/healthz`` only
    shows as a long-run average — the histogram shows whether waste is a
    steady trickle (width too large for the arrival rate) or admission
    bursts draining out (width fine, arrivals lumpy).

    Labeled by lane WIDTH, not lane id: widths come from the bounded
    pow2 bucket lattice, while lane ids increment for every rebuilt lane
    — id labels on the process-global registry would leak one series
    family per retired lane forever (Prometheus cardinality 101)."""
    return (registry or REGISTRY).histogram(
        "chiaswarm_stepper_lane_occupancy_ratio",
        "active rows / lane width at each lane step, by lane width",
        labelnames=("width",),
        buckets=OCCUPANCY_BUCKETS)

#: resume-step buckets: pow2 over the step-capacity lattice
#: (core/compile_cache.py bucket_steps caps at 128)
RESUME_STEP_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def resume_step_histogram(registry: Registry | None = None) -> Histogram:
    """Step index at which redelivered rows splice back into a lane
    (ISSUE 6), observed by serving/stepper.py at admission.

    THE fleet-invariant proof signal: a redelivered job that resumed
    records step >= 1 here (and in its result's
    ``pipeline_config.stepper.resume_step``); a distribution stuck at
    low steps means leases expire faster than the checkpoint cadence
    (``CHIASWARM_STEPPER_CKPT_EVERY``) can push progress — lengthen the
    lease or tighten the cadence. Unlabeled: lane identity would leak
    unbounded series (same cardinality rule as the occupancy family)."""
    return (registry or REGISTRY).histogram(
        "chiaswarm_stepper_resume_step",
        "step index at which resumed (redelivered) rows spliced into "
        "a lane",
        buckets=RESUME_STEP_BUCKETS)


def lane_resizes_counter(registry: Registry | None = None) -> Counter:
    """Adaptive-width control-loop actions (ISSUE 7): lanes growing or
    shrinking their row file at a step boundary, labeled by direction.

    The closed loop's activity signal: a healthy loop resizes a handful
    of times as traffic regime shifts; a high rate means the controller
    is thrashing (occupancy oscillating around a threshold — raise the
    patience knob or pin ``CHIASWARM_STEPPER_LANE_WIDTH``). Direction
    split matters: all-grow means demand keeps outrunning capacity
    (raise ``CHIASWARM_STEPPER_MAX_WIDTH``), all-shrink means the
    initial width is habitually too large."""
    return (registry or REGISTRY).counter(
        "chiaswarm_stepper_lane_resizes_total",
        "adaptive lane-width resizes at step boundaries, by direction",
        labelnames=("direction",))


def arrival_rate_gauge(registry: Registry | None = None) -> Gauge:
    """The lane scheduler's arrival-rate EWMA (rows/second), the demand
    half of the adaptive-width control signal (occupancy is the supply
    half). Sampled at each control decision; 0 when lanes are idle."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_stepper_arrival_rate",
        "EWMA of lane row arrivals per second (adaptive-width demand "
        "signal)")


def lane_admissions_counter(registry: Registry | None = None) -> Counter:
    """Rows admitted into lanes, by workload (ISSUE 7: lanes serve
    img2img/inpaint/controlnet alongside txt2img). The eligibility-
    breadth proof: a workload stuck at 0 while its jobs flow means it is
    falling back to the per-job path (check LaneReject logs)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_stepper_lane_admissions_total",
        "lane rows admitted, by workload kind",
        labelnames=("workload",))


# ---- step-collapse families (ISSUE 12, swarmturbo) ----
#
# The 15x headline gap is steps x full-UNet; these families measure the
# collapse of that product directly. Incremented by BOTH execution
# paths — the lane driver per dispatch (serving/stepper.py) and the
# solo submit per job (pipelines/diffusion.py) — on the process-global
# REGISTRY, pre-seeded at import by those modules.

#: how one per-row UNet evaluation was served: ``full`` runs the whole
#: network (and refreshes the DeepCache deep-feature cache when reuse
#: is compiled in); ``reuse`` replays the cached deep activation and
#: recomputes only the shallow level-0 blocks
STEPPER_UNET_EVAL_MODES = ("full", "reuse")

#: per-image UNet-eval buckets: pow2 over the step-capacity lattice —
#: a 30-step baseline lands in (16, 32]; the 4-step few-step family in
#: (2, 4]; DeepCache-on rows land wherever their refresh cadence puts
#: the full-eval count
UNET_EVAL_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def unet_evals_counter(registry: Registry | None = None) -> Counter:
    """Per-row UNet evaluations by mode (``full`` vs DeepCache
    ``reuse``). THE step-collapse cost signal: the full-mode rate IS
    the chip-time driver (a reuse eval costs only the shallow level-0
    blocks), so full/(full+reuse) is the fraction of the old per-step
    cost the traffic still pays."""
    return (registry or REGISTRY).counter(
        "chiaswarm_stepper_unet_evals_total",
        "per-row UNet evaluations, by mode (full vs DeepCache reuse)",
        labelnames=("mode",))


def steps_skipped_counter(registry: Registry | None = None) -> Counter:
    """Denoise steps whose deep UNet blocks were skipped via DeepCache
    feature reuse (per row). Zero with ``CHIASWARM_DEEPCACHE`` off or
    no per-job ``reuse_schedule`` — a zero here while reuse jobs flow
    means misaligned lane mates kept forcing full evals (check the
    lane admission mix)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_stepper_steps_skipped_total",
        "denoise steps served from the DeepCache deep-feature cache "
        "(per row)")


def unet_evals_per_image_histogram(
        registry: Registry | None = None) -> Histogram:
    """FULL UNet evaluations each finished image actually paid —
    observed once per row at retirement (lanes) or submit (solo). The
    distribution the ≥4x step-collapse acceptance reads: a 30-step
    baseline observes 30, the lcm 4-step family 4, DeepCache rows
    their refresh count."""
    return (registry or REGISTRY).histogram(
        "chiaswarm_stepper_unet_evals_per_image",
        "full UNet evaluations per finished image",
        buckets=UNET_EVAL_BUCKETS)


# ---- HBM model-residency families (ISSUE 8, serving/residency.py) ----
#
# The residency manager owns the ledger; these helpers only declare the
# families (on the process-global REGISTRY by default — the manager is
# one-per-process like the compile cache; hermetic test managers pass
# their own Registry). The manager pre-seeds every label vocabulary at
# import so dashboards see zeroes from the first scrape (the ISSUE-6
# convention for the lease/resume families).

#: the authoritative per-model state vocabulary (registry + residency,
#: ISSUE 8 satellite: quarantine and residency share one enum)
RESIDENCY_STATES = ("cold", "loading", "resident", "degraded",
                    "evicted", "unavailable", "quarantined")

#: why a resident model was dropped from HBM
RESIDENCY_EVICT_REASONS = ("capacity", "squeeze")

#: how a model load was served (resident admit / degraded load-per-job /
#: background prefetch)
RESIDENCY_LOAD_MODES = ("resident", "per_job", "prefetch")


def residency_bytes_gauge(registry: Registry | None = None) -> Gauge:
    """Bytes of model params the residency ledger holds resident —
    MEASURED from the live trees at load (summed shard .nbytes), not
    estimated. The headroom signal: steady-state near the budget with a
    nonzero eviction rate means the catalog is HBM-bound (quantize, or
    raise CHIASWARM_RESIDENCY_BUDGET)."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_residency_resident_bytes",
        "measured bytes of model params currently resident in HBM")


def residency_budget_gauge(registry: Registry | None = None) -> Gauge:
    return (registry or REGISTRY).gauge(
        "chiaswarm_residency_budget_bytes",
        "HBM byte budget the residency ledger evicts down to")


def residency_peak_gauge(registry: Registry | None = None) -> Gauge:
    """High-water mark of resident + reserved bytes — THE no-double-
    buffer proof: a swap that evicts before loading keeps this at
    most budget + one model (the churn tests assert exactly that)."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_residency_peak_bytes",
        "high-water mark of resident + in-flight reserved bytes")


def residency_models_gauge(registry: Registry | None = None) -> Gauge:
    """Model count per residency state (the /healthz ``models`` enum,
    aggregated). ``degraded`` > 0 is the graceful-degradation rung in
    action: some model serves load-per-job because its measured
    footprint exceeds the budget."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_residency_models",
        "models per residency state (cold/loading/resident/degraded/"
        "evicted/unavailable/quarantined)",
        labelnames=("state",))


def residency_evictions_counter(registry: Registry | None = None) -> Counter:
    """Ledger evictions by reason: ``capacity`` (donation — room made
    for an incoming load) vs ``squeeze`` (the budget itself shrank). A
    high capacity rate with a small catalog means footprints ~ budget:
    expect swap latency on every model switch."""
    return (registry or REGISTRY).counter(
        "chiaswarm_residency_evictions_total",
        "models evicted from HBM residency, by reason",
        labelnames=("reason",))


def residency_loads_counter(registry: Registry | None = None) -> Counter:
    """Model loads by mode. ``per_job`` counting up is the degradation
    rung burning load latency per job — the signal to quantize
    (CHIASWARM_WEIGHTS=int8) or grow the budget; ``prefetch`` counts
    idle-poll warm loads driven by the per-model arrival EWMA."""
    return (registry or REGISTRY).counter(
        "chiaswarm_residency_loads_total",
        "model param-tree loads, by residency mode",
        labelnames=("mode",))


def residency_bounces_counter(registry: Registry | None = None) -> Counter:
    """Jobs refused because the model cannot fit even transiently
    (footprint > hard limit): uploaded as non-fatal
    ``model_unavailable`` so a lease-aware hive redispatches them
    (node/minihive.py REDISPATCH_KINDS)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_residency_bounces_total",
        "jobs bounced model_unavailable: model cannot fit transiently")


def residency_load_seconds_histogram(
        registry: Registry | None = None) -> Histogram:
    """Wall time of one model load (convert/build + measure), by mode —
    with ``swapped="1"`` when the load had to evict first. The swap
    latency the ``model_churn`` bench config stamps into BENCH json."""
    return (registry or REGISTRY).histogram(
        "chiaswarm_residency_load_seconds",
        "model load wall time, by residency mode and whether the load "
        "evicted residents first",
        labelnames=("mode", "swapped"),
        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                 60.0, 120.0, 300.0))


# ---- overload-control families (ISSUE 9, node/overload.py) ----
#
# Declared here like the residency families; the controller is
# per-WORKER (hermetic test workers must not bleed shed counts into
# each other), so these take the worker's registry and the controller
# pre-seeds every label vocabulary at construction.

#: overload controller states (the brownout rung ladder)
OVERLOAD_STATES = ("normal", "brownout")


def overload_state_gauge(registry: Registry | None = None) -> Gauge:
    """Overload-control state: 0 = normal, 1 = brownout (sustained
    shedding tripped the rung — lane admissions are capped per step and
    the shed margin tightens until sheds stop for the cooldown)."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_overload_state",
        "overload control state (0=normal, 1=brownout)")


def overload_shed_counter(registry: Registry | None = None) -> Counter:
    """Jobs shed at admission because the estimator predicted a
    deadline miss, by workload. Sheds upload as non-fatal ``overloaded``
    envelopes a lease-aware hive redispatches (with this worker
    excluded) — a rising rate means offered load exceeds this node's
    capacity; compare against ``chiaswarm_jobs_total{outcome="ok"}`` to
    read the admitted fraction."""
    return (registry or REGISTRY).counter(
        "chiaswarm_overload_shed_total",
        "jobs shed by deadline-aware admission control, by workload",
        labelnames=("workload",))


def overload_backpressure_counter(
        registry: Registry | None = None) -> Counter:
    """Poll-loop waits inserted by queue-depth backpressure: the worker
    predicted its queued backlog alone would outlast the backpressure
    budget and stopped asking for MORE work. Jobs already queued keep
    executing — backpressure throttles intake, shedding handles what
    was already admitted."""
    return (registry or REGISTRY).counter(
        "chiaswarm_overload_backpressure_waits_total",
        "poll-loop waits inserted by queue-depth backpressure")


def overload_predicted_wait_histogram(
        registry: Registry | None = None) -> Histogram:
    """The admission estimator's predicted completion time (queue drain
    + service estimate) sampled at every shed decision. Compare the
    distribution against the deadline knobs: mass past the deadline IS
    the shed rate; mass near it means the margin is doing the work."""
    return (registry or REGISTRY).histogram(
        "chiaswarm_overload_predicted_wait_seconds",
        "admission estimator's predicted completion time at each "
        "shed decision",
        buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                 120.0, 300.0, 600.0, 1800.0))


def overload_admission_cap_gauge(
        registry: Registry | None = None) -> Gauge:
    """Current brownout lane-admission cap (rows per step boundary);
    0 = uncapped (normal state). Pushed into every slot's
    StepScheduler (serving/stepper.py) while brownout holds."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_overload_admission_cap",
        "brownout cap on lane rows admitted per step boundary "
        "(0 = uncapped)")


# ---- swarmguard families (ISSUE 10, serving/guard.py) ----
#
# Declared here like the overload families; the DeviceGuard is
# per-WORKER (hermetic test workers must not bleed health events into
# each other), takes the worker's registry, and pre-seeds every
# enumerable label vocabulary at construction. The ``model`` and
# ``device`` labels are bounded by the catalog / chip count, not by
# time (the occupancy-family cardinality rule).


def guard_hangs_counter(registry: Registry | None = None) -> Counter:
    """Compiled calls the watchdog declared hung, by phase (``lane``
    step dispatch vs ``solo`` denoise). A nonzero rate is THE
    gray-failure signal: the chip wedges without dying — check the
    device health gauge to see whether one chip owns the hangs."""
    return (registry or REGISTRY).counter(
        "chiaswarm_guard_hangs_total",
        "compiled calls declared hung by the step watchdog, by phase",
        labelnames=("phase",))


def guard_condemned_counter(registry: Registry | None = None) -> Counter:
    """Lanes condemned by the watchdog: each one is a lane-rebuild heal
    rung — the condemned lane's rows re-admit to a freshly built lane,
    resuming from their last step-boundary checkpoint."""
    return (registry or REGISTRY).counter(
        "chiaswarm_guard_condemned_lanes_total",
        "lanes condemned by the hang watchdog (rows re-admitted to a "
        "fresh lane)")


def guard_invalid_counter(registry: Registry | None = None) -> Counter:
    """Rows retired with ``invalid_output`` (non-finite latents or a
    poisoned decoded frame), by model. One model owning the count while
    others stay clean points at the checkpoint; every model counting
    together points at the device (watch the health gauge)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_guard_invalid_outputs_total",
        "jobs retired invalid_output instead of uploading a poisoned "
        "image, by model",
        labelnames=("model",))


def guard_device_health_gauge(registry: Registry | None = None) -> Gauge:
    """Per-device health score in [0, 1]: 1 = healthy, decays with the
    consecutive hang/slow-step/invalid-output streak and recovers with
    OK events. The ladder rungs quote their thresholds in streak units;
    the gauge is the operator-facing normalization."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_guard_device_health",
        "per-device health score (1 = healthy; ladder rungs fire as "
        "the sickness streak grows)",
        labelnames=("device",))


def guard_heal_rung_counter(registry: Registry | None = None) -> Counter:
    """Healing-ladder escalations by rung: ``lane_rebuild`` (every
    condemnation), ``cache_flush`` (executable LRU dropped),
    ``device_quarantine`` (mesh shrunk to the healthy chips), and
    ``restart`` (graceful drain + the distinct supervisor exit code)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_guard_heal_rung_total",
        "self-healing ladder escalations, by rung",
        labelnames=("rung",))


def guard_quarantined_gauge(registry: Registry | None = None) -> Gauge:
    return (registry or REGISTRY).gauge(
        "chiaswarm_guard_quarantined_devices",
        "devices currently quarantined out of the serving mesh")


# ---- swarmsight families (ISSUE 13, obs/flight.py) ----


def trace_spans_evicted_counter(
        registry: Registry | None = None) -> Counter:
    """Spans dropped from the bounded trace ring by eviction — the
    signal that a scraper polling ``/debug/traces`` too slowly is
    LOSING trace data, not that there is none. Pair with the endpoint's
    ``?since=<seq>`` cursor: a gap between the scraper's last seq and
    the ring's oldest seq is exactly this eviction window."""
    return (registry or REGISTRY).counter(
        "chiaswarm_trace_spans_evicted_total",
        "spans evicted from the bounded trace ring before any scrape "
        "collected them (use /debug/traces?since= to detect gaps)")


# ---- swarmdurable families (ISSUE 14, node/hivelog.py) ----
#
# Worker-side: the hive-session outage families live on each worker's
# registry (hermetic, like guard/overload). Hive-side journal families
# live on the hive's own registry (node/minihive.py) — /api/stats is
# their scrape, not /metrics.

#: when a dead-letter envelope was replayed back into the upload queue:
#: ``startup`` (the PR-2 path — the worker process restarted) vs
#: ``live`` (ISSUE 14 — the hive healed mid-run and the spool drained
#: without a restart)
DEAD_LETTER_REPLAY_WHEN = ("startup", "live")


def dead_letter_replayed_counter(
        registry: Registry | None = None) -> Counter:
    """Dead-letter envelopes re-queued for upload, split by when: a
    ``live`` count rising during an incident is the ride-through
    working (spooled chip time landing the moment the hive heals); a
    ``startup`` count means the outage outlived the worker process.
    Complements ``chiaswarm_results_replayed_total`` (the undifferen-
    tiated PR-2 total, kept for dashboard compatibility)."""
    return (registry or REGISTRY).counter(
        "chiaswarm_dead_letter_replayed_total",
        "dead-letter results re-queued for upload, by replay moment",
        labelnames=("when",))


def hive_session_state_gauge(registry: Registry | None = None) -> Gauge:
    """The worker's hive-session state: 0 = online, 1 = OUTAGE
    ride-through (leases assumed lost, in-flight work completing,
    results spooling). THE page-the-operator signal for a hive-side
    incident as seen from the fleet's edge — every worker's gauge
    flipping together is a hive outage; one worker alone is a
    partition."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_hive_session_state",
        "worker's hive reachability state (0=online, 1=outage)")


def hive_shard_session_state_gauge(
        registry: Registry | None = None) -> Gauge:
    """The per-shard half of the session signal (swarmfed, ISSUE 17):
    a multiplexed worker holds one HiveSession per hive shard, and this
    family shows exactly WHICH shard's traffic is riding through an
    outage while the rest keep serving. The unlabeled gauge above stays
    the page-the-operator any-shard-down rollup (shard-0-equivalent on
    a single-hive worker)."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_hive_shard_session_state",
        "worker's per-shard hive session (0=online, 1=outage)",
        ("shard",))


# ---- fleet-planner families (swarmplan, ISSUE 19, node/planner.py) ----
#
# The autoscaler's control loop is hive-side state, so the families
# live on the planner's registry (the hive's, usually) — and like the
# residency/overload families every label vocabulary pre-seeds at
# planner construction (plus once at module import for the global
# registry) so a dashboard sees zeros before the first decision.

#: which way a planning tick moved the target
PLANNER_DIRECTIONS = ("up", "down", "hold")

#: why the tick chose that direction — ``demand`` (the smoothed
#: arrival rate moved the capacity target), ``backlog`` (the hive-side
#: queue added a drain term), ``hysteresis`` (inside the deadband),
#: ``cooldown`` (a recent actuation pinned the fleet), ``bounds``
#: (min/max fleet clamp engaged), ``steady`` (target == actual)
PLANNER_REASONS = ("demand", "backlog", "hysteresis", "cooldown",
                   "bounds", "steady")


def planner_target_workers_gauge(registry: Registry | None = None) -> Gauge:
    """The planner's current target fleet size — what the supervisor
    contract (``GET /api/plan``) tells a real deployment to converge
    on. Persistent gap vs the actual gauge below means actuation is
    lagging (slow cold starts: ROADMAP item 5) or the supervisor is
    not consuming the plan."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_planner_target_workers",
        "fleet size the planner wants (the /api/plan target)")


def planner_actual_workers_gauge(registry: Registry | None = None) -> Gauge:
    """Live, reachable workers the planner observed on its last tick
    (the /api/fleet ``workers_live`` view it planned against)."""
    return (registry or REGISTRY).gauge(
        "chiaswarm_planner_actual_workers",
        "live workers observed by the planner's last tick")


def planner_decisions_counter(registry: Registry | None = None) -> Counter:
    """Planning-tick decisions by direction and reason. A high
    ``up``+``down`` churn rate with ``reason="demand"`` means the
    hysteresis band or cooldowns are too tight for the arrival noise;
    mostly ``hold/steady`` is a converged loop."""
    return (registry or REGISTRY).counter(
        "chiaswarm_planner_decisions_total",
        "planning-tick decisions, by direction and reason",
        labelnames=("direction", "reason"))


def planner_placement_moves_counter(
        registry: Registry | None = None) -> Counter:
    """Per-worker model assignments that CHANGED between consecutive
    plans (the placement half of the loop). Each move costs a survivor
    a warm load — a sustained rate here with flat fleet size means the
    demand mix is churning faster than residency can follow."""
    return (registry or REGISTRY).counter(
        "chiaswarm_planner_placement_moves_total",
        "per-worker model placement assignments changed by a new plan")


def planner_worker_hours_counter(
        registry: Registry | None = None) -> Counter:
    """Accumulated worker-hours as the planner observes them (actual
    fleet size x tick interval). THE cost side of the autoscaler's
    headline: BENCH compares this against every static roster in the
    swept set."""
    return (registry or REGISTRY).counter(
        "chiaswarm_planner_worker_hours_total",
        "worker-hours accumulated under the planner's watch")


#: the Prometheus text exposition content type
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
