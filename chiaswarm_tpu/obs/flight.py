"""swarmsight: cross-worker flight records + the fleet observability plane.

Everything observability built so far is strictly per-process: swarmscope
(obs/trace.py) gives each worker span trees, swarmlens (obs/numerics.py)
gives each program numerics — but a job that is shed, redispatched,
killed mid-lane and resumed on a second worker leaves disconnected
fragments in three different trace rings and no single answer to "where
did this job's deadline budget go". This module closes that gap with the
standard Dapper recipe — context propagation plus hive-side assembly:

- **Trace context**: the hive stamps ``trace_ctx`` (one ``trace_id`` per
  job, one ``span_id`` per delivery attempt) into every granted payload
  (:data:`TRACE_CTX_KEY`). The worker JOINs it: its existing
  :class:`~chiaswarm_tpu.obs.trace.JobTrace` becomes that attempt span's
  child, originating locally only when the hive sends none — so against
  a reference hive nothing changes, on the wire or in the trace ring.
- **Span digests**: every result envelope uploaded for a
  context-carrying job rides a compact :func:`span_digest` of the
  worker's span tree (:data:`SPAN_DIGEST_KEY`) — phase boundaries and
  the named pipeline spans, as offsets on the worker's own
  ``perf_counter`` timebase plus a wall anchor. The hive pops the digest
  off the envelope before storing it, so settled results keep their
  historical shape.
- **Flight records**: :class:`FlightRecorder` (bounded, hive-side)
  assembles the authoritative per-job record: submit → grant(attempt,
  worker) → heartbeat checkpoint markers → shed / redispatch / lease
  expiry / redelivery / salvage / abandonment → exactly-once settle,
  each event on the hive clock, with the per-attempt worker digests
  attached. Served at ``GET /api/flight/<job_id>``
  (node/minihive.py); ``tools/job_flight.py`` renders one record as a
  tree, a timeline, or Perfetto JSON spanning workers.
- **Budget attribution**: at settle, :func:`budget_attribution`
  decomposes the job's end-to-end latency into named phases —
  ``hive_queue``, ``admission`` (local queue wait + format + encode),
  ``lane_wait`` (splice wait behind a full lane), ``steps``, ``decode``,
  ``upload``, ``retry`` (chip time burned by non-settling attempts) and
  the ``other`` residue — so a p99 miss points at a phase, not just a
  number. ``loadgen.score_run`` folds these into per-family tables.
- **The fleet plane**: heartbeats push per-worker metric snapshots
  (arrival EWMAs, lane occupancy, chips in service, residency ledger,
  overload state); the hive aggregates them at ``GET /api/fleet`` —
  exactly the observed-state data plane the ROADMAP item-5 autoscaler
  consumes. :class:`RateEwma` is the hive-side observed-arrival
  estimator.

Per-worker clock alignment: a digest's span offsets live on that
worker's ``perf_counter`` epoch, which means nothing hive-side. The
renderers anchor each attempt's offsets at its hive-stamped GRANT time
and report the residual against the hive-stamped SETTLE
(``clock_skew_s``) — two anchors, no clock protocol, accurate to one
poll RTT. Everything here is stdlib-only (the hive, the tools, and the
tests all run without jax).
"""

from __future__ import annotations

import collections
import math
import os
import threading
import uuid
from typing import Any, Iterable

#: wire field the hive stamps into every granted job payload:
#: ``{"trace_id": str, "span_id": str, "attempt": int}``. The worker
#: pops it at poll receipt (node/worker.py) — it never reaches argument
#: formatting or a pipeline callback.
TRACE_CTX_KEY = "trace_ctx"

#: result-envelope field carrying the worker's span digest hive-ward.
#: Attached ONLY when the job carried a hive trace context, so the
#: upload payload against a context-less (reference) hive stays
#: byte-compatible with the pre-swarmsight wire shape (gated by test).
SPAN_DIGEST_KEY = "span_digest"

ENV_FLIGHT_CAPACITY = "CHIASWARM_FLIGHT_RING"

#: per-record event cap: a pathological job (lease churn every beat)
#: must not grow one record without bound; drops are counted, loudly
MAX_EVENTS_PER_FLIGHT = 512

#: per-digest span cap (the digest is a summary, not the full tree)
MAX_DIGEST_SPANS = 64

#: the attribution phase vocabulary, in render order
ATTRIBUTION_PHASES = ("hive_queue", "admission", "lane_wait", "steps",
                      "decode", "upload", "retry", "other")


def new_trace_id() -> str:
    """One id per job lifetime, shared by every attempt's spans."""
    return uuid.uuid4().hex[:16]


def attempt_span_id(trace_id: str, attempt: int) -> str:
    """Deterministic per-attempt span id: stitching needs no registry
    round-trip — the attempt number IS the suffix."""
    return f"{trace_id}.{int(attempt)}"


def _small_meta(meta: dict[str, Any]) -> dict[str, Any]:
    """JSON-safe scalar subset of a span's metadata (digests cross the
    wire; device arrays and long blobs must not)."""
    out: dict[str, Any] = {}
    for key, value in meta.items():
        if isinstance(value, bool) or isinstance(value, (int, float)):
            out[key] = value
        elif isinstance(value, str) and len(value) <= 200:
            out[key] = value
    return out


def span_digest(trace: Any, worker_name: str = "") -> dict[str, Any]:
    """Compact, JSON-safe summary of one JobTrace for the result
    envelope: top-level phases plus the named pipeline spans below them,
    all as offsets from the root start on this worker's perf_counter
    timebase. Built at upload START (the upload phase itself is measured
    hive-side from the grant/settle anchors), so ``duration_s`` covers
    poll receipt -> upload start."""
    root = trace.root
    meta = root.meta
    phases: list[dict[str, Any]] = []
    spans: list[dict[str, Any]] = []
    truncated = False
    for phase in root.children:
        phases.append({
            "name": phase.name,
            "t0_s": round(phase.t0 - root.t0, 6),
            "dur_s": round(phase.duration_s, 6),
        })
        queue: list[tuple[Any, str]] = [(child, phase.name)
                                        for child in phase.children]
        while queue:
            node, phase_name = queue.pop(0)
            if len(spans) >= MAX_DIGEST_SPANS:
                truncated = True
                break
            entry: dict[str, Any] = {
                "name": node.name,
                "phase": phase_name,
                "t0_s": round(node.t0 - root.t0, 6),
                "dur_s": round(node.duration_s, 6),
            }
            small = _small_meta(node.meta)
            if small:
                entry["meta"] = small
            spans.append(entry)
            queue.extend((child, phase_name) for child in node.children)
    digest: dict[str, Any] = {
        "trace_id": str(meta.get("trace_id") or ""),
        "span_id": str(meta.get("span_id") or ""),
        "attempt": int(meta.get("attempt") or 1),
        "worker": str(worker_name or meta.get("worker") or ""),
        "started_at_unix": round(float(trace.started_at_unix), 6),
        "duration_s": round(root.duration_s, 6),
        "phases": phases,
        "spans": spans,
    }
    if truncated:
        digest["spans_truncated"] = True
    for key in ("queued_s", "resume_step"):
        if meta.get(key) is not None:
            try:
                digest[key] = float(meta[key])
            except (TypeError, ValueError):
                pass
    return digest


class RateEwma:
    """Observed event rate (events/second), exponentially weighted over
    ``window_s`` on caller-supplied timestamps — the hive's injectable
    fake clocks work unchanged. The fleet plane's observed-arrival
    estimator (the quantity the item-5 autoscaler plans against)."""

    def __init__(self, window_s: float = 30.0) -> None:
        self.window_s = max(1e-6, float(window_s))
        self._rate = 0.0
        self._last: float | None = None

    def note(self, now: float, n: float = 1.0) -> None:
        if self._last is None:
            self._last = float(now)
            return
        dt = max(1e-6, float(now) - self._last)
        alpha = 1.0 - math.exp(-dt / self.window_s)
        self._rate += alpha * (float(n) / dt - self._rate)
        self._last = float(now)

    def rate(self, now: float) -> float:
        if self._last is None:
            return 0.0
        idle = max(0.0, float(now) - self._last)
        return self._rate * math.exp(-idle / self.window_s)


# ---------------------------------------------------------------------------
# the hive-side recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded per-job flight-record store (hive-side).

    One record per job id, evicting oldest-opened beyond ``capacity``
    (``CHIASWARM_FLIGHT_RING``, default 2048). All timestamps come from
    the caller's clock (the hive's injectable monotonic clock), so the
    whole record lives on ONE timebase; worker digests carry their own
    perf_counter offsets and are aligned at render time on the
    grant/settle anchors."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get(ENV_FLIGHT_CAPACITY, "2048")
                           or 2048)
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._records: collections.OrderedDict[str, dict[str, Any]] = \
            collections.OrderedDict()
        self.evicted = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def job_ids(self) -> list[str]:
        with self._lock:
            return list(self._records)

    def trace_id_of(self, job_id: Any) -> str | None:
        """Existing record's trace id (None when unknown) — the journal
        stamps it into the submit event so recovery keeps one trace per
        job across hive restarts."""
        with self._lock:
            record = self._records.get(str(job_id))
            return None if record is None else record["trace_id"]

    # ---- building ------------------------------------------------------

    def _open_locked(self, job_id: str) -> dict[str, Any]:
        record = self._records.get(job_id)
        if record is None:
            record = {
                "job_id": job_id,
                "trace_id": new_trace_id(),
                "model": "", "workflow": "", "deadline_s": None,
                "submitted_at": None,
                "events": [], "events_dropped": 0,
                "granted": {},      # attempt -> {"t", "worker"}
                "digests": {},      # attempt -> span digest
                "settled": None,
                "attribution": None,
            }
            self._records[job_id] = record
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
                self.evicted += 1
        return record

    def open(self, job_id: Any, job: dict[str, Any] | None, *,
             t: float, trace_id: str | None = None) -> None:
        """Start (or refresh) a record at hive submit. Idempotent: a
        resubmitted id keeps its existing trace and history.
        ``trace_id`` pins the id instead of minting one — journal replay
        (node/minihive.py::MiniHive.recover) restores records under
        their pre-crash trace ids, so a story that spans a hive restart
        stays ONE trace."""
        with self._lock:
            record = self._open_locked(str(job_id))
            if trace_id:
                record["trace_id"] = str(trace_id)
            if record["submitted_at"] is None:
                record["submitted_at"] = float(t)
                self._note_locked(record, t, "submit")
            if isinstance(job, dict):
                record["model"] = record["model"] or str(
                    job.get("model_name") or "")
                record["workflow"] = record["workflow"] or str(
                    job.get("workflow") or "txt2img")
                if record["deadline_s"] is None and job.get("deadline_s"):
                    try:
                        record["deadline_s"] = float(job["deadline_s"])
                    except (TypeError, ValueError):
                        pass

    @staticmethod
    def _note_locked(record: dict[str, Any], t: float, event: str,
                     **fields: Any) -> None:
        if len(record["events"]) >= MAX_EVENTS_PER_FLIGHT:
            record["events_dropped"] += 1
            return
        entry = {"t": round(float(t), 6), "event": str(event)}
        for key, value in fields.items():
            if value is not None:
                entry[key] = value
        record["events"].append(entry)

    def note(self, job_id: Any, event: str, *, t: float,
             **fields: Any) -> None:
        """Append one hive-clock event (lease expiry, redelivery,
        checkpoint marker, shed, salvage, ...)."""
        with self._lock:
            self._note_locked(self._open_locked(str(job_id)), t, event,
                              **fields)

    def grant(self, job_id: Any, *, attempt: int, worker: str, t: float,
              queued_s: float | None = None,
              resume_step: int | None = None,
              epoch: int | None = None) -> dict[str, Any]:
        """Record one delivery and return the wire trace context the
        payload carries (:data:`TRACE_CTX_KEY`). ``epoch`` is the
        journaled hive's grant epoch (swarmdurable): a record whose
        grants carry two different epochs provably spans a hive
        restart."""
        with self._lock:
            record = self._open_locked(str(job_id))
            attempt = int(attempt)
            granted = {"t": round(float(t), 6), "worker": str(worker)}
            if epoch is not None:
                granted["epoch"] = int(epoch)
            record["granted"][attempt] = granted
            self._note_locked(record, t, "grant", attempt=attempt,
                              worker=str(worker), queued_s=queued_s,
                              resume_step=resume_step, epoch=epoch)
            return {"trace_id": record["trace_id"],
                    "span_id": attempt_span_id(record["trace_id"],
                                               attempt),
                    "attempt": attempt}

    def add_digest(self, job_id: Any, digest: Any) -> None:
        """Attach a worker span digest under its attempt (uploads for
        duplicates and redispatched refusals record too — they are part
        of the story)."""
        if not isinstance(digest, dict):
            return
        try:
            attempt = int(digest.get("attempt") or 0)
        except (TypeError, ValueError):
            attempt = 0
        if attempt < 1:
            # a digest that cannot name its attempt cannot be stitched
            # — dropping it beats filing an orphan under attempt 0 that
            # the completeness audit would forever flag
            return
        with self._lock:
            record = self._records.get(str(job_id))
            if record is None:
                return
            record["digests"][attempt] = digest

    def settle(self, job_id: Any, *, t: float, worker: str, outcome: str,
               attempt: int | None = None,
               epoch: int | None = None) -> None:
        """The exactly-once settle closes the record and computes the
        deadline-budget attribution. ``epoch`` stamps which hive epoch
        counted the settle (swarmdurable)."""
        with self._lock:
            record = self._records.get(str(job_id))
            if record is None:
                return
            if record["settled"] is not None:
                return  # exactly once, here too
            if attempt is None:
                attempt = max(record["granted"], default=0)
            record["settled"] = {"t": round(float(t), 6),
                                 "worker": str(worker),
                                 "outcome": str(outcome),
                                 "attempt": int(attempt)}
            if epoch is not None:
                record["settled"]["epoch"] = int(epoch)
            self._note_locked(record, t, "settled", worker=str(worker),
                              outcome=str(outcome), attempt=int(attempt),
                              epoch=epoch)
            record["attribution"] = budget_attribution(record)

    # ---- durability (swarmdurable: compaction snapshots) ---------------

    def dump(self) -> dict[str, Any]:
        """JSON-safe full-state dump for the hive journal's compaction
        snapshot (node/hivelog.py): records in ring order plus the
        eviction counter. Attempt-keyed maps serialize with string keys
        (JSON has no int keys); :meth:`restore` coerces them back."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "evicted": self.evicted,
                "records": [
                    {k: ({str(a): e for a, e in v.items()}
                         if k in ("granted", "digests") else v)
                     for k, v in record.items()}
                    for record in self._records.values()
                ],
            }

    def restore(self, dump: dict[str, Any]) -> None:
        """Rebuild the ring from :meth:`dump` (journal snapshot replay).
        Replaces current contents; capacity stays this instance's own
        (the env knob may legitimately differ across restarts)."""
        if not isinstance(dump, dict):
            return
        with self._lock:
            self._records.clear()
            self.evicted = int(dump.get("evicted") or 0)
            for raw in dump.get("records") or ():
                if not isinstance(raw, dict) or raw.get("job_id") is None:
                    continue
                record = dict(raw)
                for key in ("granted", "digests"):
                    coerced: dict[int, Any] = {}
                    for a, entry in (record.get(key) or {}).items():
                        try:
                            coerced[int(a)] = entry
                        except (TypeError, ValueError):
                            continue
                    record[key] = coerced
                record.setdefault("events", [])
                record.setdefault("events_dropped", 0)
                record.setdefault("settled", None)
                record.setdefault("attribution", None)
                self._records[str(record["job_id"])] = record
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
                self.evicted += 1

    def unsettled_ids(self) -> list[str]:
        """Open (never-settled) records — the set a recovering hive
        marks with its epoch-bump event so a stitched story shows the
        restart between the attempts."""
        with self._lock:
            return [job_id for job_id, record in self._records.items()
                    if record["settled"] is None]

    # ---- reading -------------------------------------------------------

    def get(self, job_id: Any) -> dict[str, Any] | None:
        """JSON view of one record (attempt maps become sorted lists)."""
        with self._lock:
            record = self._records.get(str(job_id))
            if record is None:
                return None
            view = {k: v for k, v in record.items()
                    if k not in ("granted", "digests")}
            view["events"] = list(record["events"])
            view["attempts"] = [
                dict(record["granted"].get(attempt, {}),
                     attempt=attempt,
                     digest=record["digests"].get(attempt))
                for attempt in sorted(set(record["granted"])
                                      | set(record["digests"]))
            ]
            return view

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            settled = sum(1 for r in self._records.values()
                          if r["settled"] is not None)
            return {"records": len(self._records), "settled": settled,
                    "evicted": self.evicted, "capacity": self.capacity}

    def verify(self, job_ids: Iterable[Any], *,
               require_settled: bool = True) -> list[str]:
        """Flight-completeness audit (the nightly soak gate): every id
        has a record, attempt numbers are gapless from 1, every digest
        hangs off a granted attempt, and (by default) the record
        settled. Returns human-readable problems; [] means clean."""
        problems: list[str] = []
        with self._lock:
            for raw in job_ids:
                job_id = str(raw)
                record = self._records.get(job_id)
                if record is None:
                    problems.append(f"{job_id}: no flight record")
                    continue
                attempts = sorted(record["granted"])
                if attempts != list(range(1, len(attempts) + 1)):
                    problems.append(
                        f"{job_id}: attempt gap in grants {attempts}")
                orphans = sorted(set(record["digests"])
                                 - set(record["granted"]))
                if orphans:
                    problems.append(
                        f"{job_id}: orphan span digest(s) for "
                        f"attempt(s) {orphans}")
                if require_settled and record["settled"] is None:
                    problems.append(f"{job_id}: never settled")
                if record["events_dropped"]:
                    problems.append(
                        f"{job_id}: {record['events_dropped']} event(s) "
                        f"dropped at the record cap")
        return problems


# ---------------------------------------------------------------------------
# deadline-budget attribution
# ---------------------------------------------------------------------------


def _digest_phase_split(digest: dict[str, Any] | None
                        ) -> dict[str, float]:
    """Worker-side phase seconds from one span digest: admission (local
    queue wait + format + encode prep), lane_wait (splice wait stamped
    by the lane as ``splice_wait_s``), steps, decode."""
    out = {"admission": 0.0, "lane_wait": 0.0, "steps": 0.0,
           "decode": 0.0}
    if not isinstance(digest, dict):
        return out
    for phase in digest.get("phases") or ():
        if phase.get("name") == "poll":
            out["admission"] += float(phase.get("dur_s") or 0.0)
    for span in digest.get("spans") or ():
        name = span.get("name")
        dur = max(0.0, float(span.get("dur_s") or 0.0))
        if name in ("format", "encode"):
            out["admission"] += dur
        elif name == "step":
            wait = 0.0
            meta = span.get("meta")
            if isinstance(meta, dict):
                try:
                    wait = max(0.0, float(meta.get("splice_wait_s")
                                          or 0.0))
                except (TypeError, ValueError):
                    wait = 0.0
            wait = min(wait, dur)
            out["lane_wait"] += wait
            out["steps"] += dur - wait
        elif name == "decode":
            out["decode"] += dur
    return out


def budget_attribution(record: dict[str, Any]) -> dict[str, Any] | None:
    """Decompose one settled record's end-to-end latency into the
    :data:`ATTRIBUTION_PHASES`. Hive-clock phases (hive_queue, retry,
    upload) come from the event timeline + grant/settle anchors;
    worker-side phases come from the settling attempt's digest. The
    unattributable residue lands in ``other`` — never silently spread
    over the named phases."""
    settled = record.get("settled")
    submitted = record.get("submitted_at")
    if settled is None or submitted is None:
        return None
    t_settle = float(settled["t"])
    final_attempt = int(settled.get("attempt") or 0)
    hive_queue = retry = 0.0
    last_enqueue: float | None = float(submitted)
    open_grant: tuple[int, float] | None = None  # (attempt, t_granted)
    for event in record.get("events") or ():
        kind = event.get("event")
        t = float(event.get("t") or 0.0)
        if kind == "grant":
            if last_enqueue is not None:
                hive_queue += max(0.0, t - last_enqueue)
                last_enqueue = None
            open_grant = (int(event.get("attempt") or 0), t)
        elif kind in ("redispatched", "redelivered", "lease_expired"):
            # an attempt's lease ended without settling HERE: its
            # grant-to-here wall is retry overhead — UNLESS this very
            # attempt later settles the job (a straggler upload
            # salvaging after expiry): its time is productive work the
            # digest already attributes, so booking it as retry would
            # double-count the same interval
            if open_grant is not None:
                attempt, t_granted = open_grant
                if attempt != final_attempt:
                    retry += max(0.0, t - t_granted)
                open_grant = None
            if kind != "lease_expired" and last_enqueue is None:
                last_enqueue = t
            elif kind == "lease_expired":
                last_enqueue = t
    digest = (record.get("digests") or {}).get(final_attempt)
    split = _digest_phase_split(digest)
    upload = 0.0
    grant_final = (record.get("granted") or {}).get(final_attempt)
    if digest is not None and grant_final is not None:
        # the settle anchor: hive-observed attempt wall minus the
        # digest's own (poll receipt -> upload start) duration is the
        # upload leg, network included
        upload = max(0.0, (t_settle - float(grant_final["t"]))
                     - float(digest.get("duration_s") or 0.0))
    total = max(0.0, t_settle - float(submitted))
    phases = {
        "hive_queue": hive_queue,
        "admission": split["admission"],
        "lane_wait": split["lane_wait"],
        "steps": split["steps"],
        "decode": split["decode"],
        "upload": upload,
        "retry": retry,
    }
    phases["other"] = max(0.0, total - sum(phases.values()))
    phases = {k: round(v, 6) for k, v in phases.items()}
    dominant = max(ATTRIBUTION_PHASES, key=lambda p: phases[p]) \
        if total > 0 else None
    return {"total_s": round(total, 6), "phases": phases,
            "dominant_phase": dominant, "attempt": final_attempt,
            "measured": digest is not None}


# ---------------------------------------------------------------------------
# rendering (tools/job_flight.py is a thin CLI over these)
# ---------------------------------------------------------------------------


def _attempt_anchor(record: dict[str, Any],
                    attempt: dict[str, Any]) -> float | None:
    """Hive-clock anchor for one attempt's worker-relative offsets: the
    grant stamp (offsets start at poll receipt ~ one RTT later)."""
    t = attempt.get("t")
    return None if t is None else float(t)


def _attempt_skew(record: dict[str, Any],
                  attempt: dict[str, Any]) -> float | None:
    """Residual between the settle anchor and grant-anchored digest end
    — the cross-clock sanity number the renderers surface."""
    digest = attempt.get("digest")
    settled = record.get("settled")
    if not digest or not settled \
            or settled.get("attempt") != attempt.get("attempt"):
        return None
    anchor = _attempt_anchor(record, attempt)
    if anchor is None:
        return None
    return round(float(settled["t"])
                 - (anchor + float(digest.get("duration_s") or 0.0)), 6)


def flight_to_chrome(record: dict[str, Any]) -> dict[str, Any]:
    """One Perfetto-loadable document for one flight record: the hive
    event timeline as instant events on pid 0, one pid per WORKER with
    one tid per attempt, every attempt's spans anchored at its
    hive-stamped grant. Load the JSON at https://ui.perfetto.dev."""
    base = float(record.get("submitted_at") or 0.0)

    def us(t: float) -> int:
        return max(0, int((float(t) - base) * 1e6))

    events: list[dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "hive"}},
    ]
    for event in record.get("events") or ():
        args = {k: str(v) for k, v in event.items()
                if k not in ("t", "event")}
        events.append({"name": event.get("event", "?"), "ph": "i",
                       "s": "g", "ts": us(event.get("t") or base),
                       "pid": 0, "tid": 0, "args": args})
    worker_pids: dict[str, int] = {}
    for attempt in record.get("attempts") or ():
        digest = attempt.get("digest")
        worker = str(attempt.get("worker")
                     or (digest or {}).get("worker") or "?")
        pid = worker_pids.setdefault(worker, len(worker_pids) + 1)
        if pid == len(worker_pids):  # newly assigned: name the track
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": f"worker {worker}"}})
        anchor = _attempt_anchor(record, attempt)
        if digest is None or anchor is None:
            continue
        tid = int(attempt.get("attempt") or 1)
        skew = _attempt_skew(record, attempt)
        args = {"trace_id": str(record.get("trace_id") or ""),
                "span_id": str(digest.get("span_id") or "")}
        if skew is not None:
            args["clock_skew_s"] = str(skew)
        events.append({
            "name": f"attempt {tid}", "ph": "X", "ts": us(anchor),
            "dur": max(1, int(float(digest.get("duration_s") or 0.0)
                              * 1e6)),
            "pid": pid, "tid": tid, "args": args})
        for entry in list(digest.get("phases") or ()) \
                + list(digest.get("spans") or ()):
            events.append({
                "name": entry.get("name", "?"), "ph": "X",
                "ts": us(anchor + float(entry.get("t0_s") or 0.0)),
                "dur": max(1, int(float(entry.get("dur_s") or 0.0)
                                  * 1e6)),
                "pid": pid, "tid": tid,
                "args": {k: str(v) for k, v in
                         (entry.get("meta") or {}).items()}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _attribution_lines(record: dict[str, Any]) -> list[str]:
    attribution = record.get("attribution")
    if not attribution:
        return ["  (not settled yet — no attribution)"]
    lines = [f"  total {attribution['total_s']:.3f}s   dominant: "
             f"{attribution['dominant_phase']}"]
    total = max(1e-9, float(attribution["total_s"]))
    for phase in ATTRIBUTION_PHASES:
        value = float(attribution["phases"].get(phase, 0.0))
        lines.append(f"  {phase:<11} {value:9.4f}s  "
                     f"{100.0 * value / total:5.1f}%")
    return lines


def render_tree(record: dict[str, Any]) -> str:
    """Human-readable nested view: header, hive events, per-attempt
    span trees, attribution table."""
    lines = [
        f"flight {record.get('job_id')}  trace={record.get('trace_id')}",
        f"  model={record.get('model') or '?'}  "
        f"workflow={record.get('workflow') or '?'}  "
        f"deadline_s={record.get('deadline_s')}",
        "events:",
    ]
    base = float(record.get("submitted_at") or 0.0)
    for event in record.get("events") or ():
        extra = "  ".join(f"{k}={v}" for k, v in event.items()
                          if k not in ("t", "event"))
        lines.append(f"  +{float(event['t']) - base:8.3f}s "
                     f"{event['event']:<14} {extra}")
    for attempt in record.get("attempts") or ():
        n = attempt.get("attempt")
        worker = attempt.get("worker") or "?"
        lines.append(f"attempt {n} on {worker}:")
        digest = attempt.get("digest")
        if not digest:
            lines.append("  (no span digest uploaded)")
            continue
        skew = _attempt_skew(record, attempt)
        if skew is not None:
            lines.append(f"  clock_skew_s={skew}")
        for phase in digest.get("phases") or ():
            lines.append(f"  {phase['name']:<10} "
                         f"+{phase['t0_s']:8.3f}s  "
                         f"{phase['dur_s']:.4f}s")
            for span in digest.get("spans") or ():
                if span.get("phase") == phase["name"]:
                    lines.append(f"    {span['name']:<10} "
                                 f"+{span['t0_s']:8.3f}s  "
                                 f"{span['dur_s']:.4f}s")
    lines.append("budget attribution:")
    lines.extend(_attribution_lines(record))
    return "\n".join(lines)


def render_timeline(record: dict[str, Any]) -> str:
    """One merged hive-clock timeline: hive events and grant-anchored
    worker spans interleaved in time order across workers."""
    base = float(record.get("submitted_at") or 0.0)
    rows: list[tuple[float, str]] = []
    for event in record.get("events") or ():
        extra = "  ".join(f"{k}={v}" for k, v in event.items()
                          if k not in ("t", "event"))
        rows.append((float(event["t"]) - base,
                     f"[hive] {event['event']} {extra}".rstrip()))
    for attempt in record.get("attempts") or ():
        digest = attempt.get("digest")
        anchor = _attempt_anchor(record, attempt)
        if not digest or anchor is None:
            continue
        tag = f"[{digest.get('worker') or '?'}#{attempt.get('attempt')}]"
        for entry in list(digest.get("phases") or ()) \
                + list(digest.get("spans") or ()):
            rows.append((anchor - base + float(entry.get("t0_s") or 0.0),
                         f"{tag} {entry.get('name')} "
                         f"{float(entry.get('dur_s') or 0.0):.4f}s"))
    rows.sort(key=lambda r: r[0])
    lines = [f"timeline {record.get('job_id')} "
             f"trace={record.get('trace_id')}"]
    lines.extend(f"+{t:8.3f}s  {text}" for t, text in rows)
    lines.append("budget attribution:")
    lines.extend(_attribution_lines(record))
    return "\n".join(lines)


__all__ = [
    "ATTRIBUTION_PHASES", "FlightRecorder", "MAX_DIGEST_SPANS",
    "MAX_EVENTS_PER_FLIGHT", "RateEwma", "SPAN_DIGEST_KEY",
    "TRACE_CTX_KEY", "attempt_span_id", "budget_attribution",
    "flight_to_chrome", "new_trace_id", "render_timeline", "render_tree",
    "span_digest",
]
