"""Dapper-style trace spans: where a job's wall-clock actually goes.

One :class:`JobTrace` per hive job, built across the worker's thread
boundaries (poll loop -> slot task -> executor thread -> upload task),
answering the question the ROADMAP's "fast as the hardware allows"
north star keeps asking: poll wait vs host prep vs denoise vs decode vs
upload, per job, with real numbers.

Mechanics:

- Durations come from ``time.perf_counter()`` **only** — wall clock
  (``time.time``) jumps under NTP and is banned for durations by the
  swarmlint R8 ``wallclock-duration`` rule. One wall-clock stamp is
  taken per trace as export *metadata* (when did this happen), never
  subtracted.
- Within one thread, :func:`span` nests via a ``contextvars`` context
  variable: the executor activates a job's trace once at entry
  (:meth:`JobTrace.active`) and every ``span()`` below — pipeline
  encode, lane wait, decode — attaches at the right depth with no
  plumbing.
- Across threads/tasks the handoff is explicit: the trace object rides
  the job dict (``node/worker.py`` attaches it at poll receipt under
  ``TRACE_KEY``; the executor pops it before argument formatting) and
  phases are opened/closed manually (:meth:`JobTrace.phase`).
- Finished traces land in a bounded in-memory :class:`TraceRing`,
  exported as Perfetto/chrome-tracing JSON by ``/debug/traces``
  (node/worker.py) — load the body at https://ui.perfetto.dev.

Everything is stdlib; a ``span()`` outside any active trace times into
a detached throwaway Span, so library code can instrument
unconditionally (allocation-light: one small object per span, none per
lookup).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Iterator

#: key under which a JobTrace rides a job/result dict between worker
#: stages. Executors MUST pop it before kwargs formatting and the
#: worker pops it before JSON-serializing an envelope.
TRACE_KEY = "_obs_trace"

ENV_RING_CAPACITY = "CHIASWARM_TRACE_RING"

_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "chiaswarm_obs_span", default=None)


class Span:
    """One timed region; children nest. Durations on perf_counter."""

    __slots__ = ("name", "meta", "t0", "t1", "children")

    def __init__(self, name: str, meta: dict[str, Any] | None = None,
                 t0: float | None = None) -> None:
        self.name = str(name)
        self.meta = dict(meta or {})
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.t1: float | None = None
        self.children: list[Span] = []

    def child(self, name: str, **meta: Any) -> "Span":
        span = Span(name, meta)
        self.children.append(span)
        return span

    def end(self) -> None:
        """Close this span (idempotent); still-open children close at
        the same instant so a crashed region never exports negative or
        unbounded durations."""
        if self.t1 is None:
            self.t1 = time.perf_counter()
        for child in self.children:
            if child.t1 is None:
                child.t1 = self.t1
                child.end()

    @property
    def open(self) -> bool:
        return self.t1 is None

    @property
    def duration_s(self) -> float:
        end = time.perf_counter() if self.t1 is None else self.t1
        return max(0.0, end - self.t0)

    def find(self, name: str) -> "Span | None":
        """First span named ``name`` in this subtree (tests/debugging)."""
        if self.name == name:
            return self
        for child in self.children:
            hit = child.find(name)
            if hit is not None:
                return hit
        return None

    def to_dict(self) -> dict[str, Any]:
        data: dict[str, Any] = {
            "name": self.name,
            "start_us": int(self.t0 * 1e6),
            "duration_us": int(self.duration_s * 1e6),
        }
        if self.meta:
            data["meta"] = {k: v for k, v in self.meta.items()}
        if self.children:
            data["children"] = [c.to_dict() for c in self.children]
        return data


@contextlib.contextmanager
def span(name: str, **meta: Any) -> Iterator[Span]:
    """Time a region under the currently active span (contextvar).

    With no active trace the span is detached and discarded — safe to
    sprinkle through library code unconditionally."""
    parent = _CURRENT.get()
    current = parent.child(name, **meta) if parent is not None \
        else Span(name, meta)
    token = _CURRENT.set(current)
    try:
        yield current
    finally:
        _CURRENT.reset(token)
        current.end()


def current_span() -> Span | None:
    return _CURRENT.get()


class JobTrace:
    """Span tree for one job, handed explicitly across worker stages.

    Top-level *phases* (poll / execute / upload) are children of the
    root, opened with :meth:`phase` — starting a phase closes the
    previous one, so the manual cross-thread bookkeeping can never leak
    an open span. Library spans attach below whatever phase is open via
    :meth:`active` + :func:`span`.
    """

    def __init__(self, name: str = "job", **meta: Any) -> None:
        self.root = Span(name, meta)
        # wall-clock ANCHOR for humans reading exports ("when was
        # this"); durations never touch it (swarmlint R8)
        self.started_at_unix = time.time()
        self.finished = False
        # monotone ring sequence number, assigned by TraceRing.push():
        # the /debug/traces?since=<seq> cursor key (0 = never pushed)
        self.seq = 0

    @property
    def meta(self) -> dict[str, Any]:
        return self.root.meta

    def phase(self, name: str, **meta: Any) -> Span:
        """Open a new top-level phase, closing any open predecessor."""
        for child in self.root.children:
            if child.open:
                child.end()
        return self.root.child(name, **meta)

    def tail(self) -> Span:
        """Deepest open span — where library spans should attach."""
        node = self.root
        while node.children and node.children[-1].open:
            node = node.children[-1]
        return node

    @contextlib.contextmanager
    def active(self) -> Iterator[Span]:
        """Make this trace the thread/task's ambient span target."""
        token = _CURRENT.set(self.tail())
        try:
            yield self.root
        finally:
            _CURRENT.reset(token)

    def finish(self, ring: "TraceRing | None" = None) -> None:
        """Close the tree and publish it (idempotent)."""
        if self.finished:
            return
        self.finished = True
        self.root.end()
        (ring if ring is not None else TRACE_RING).push(self)

    # ---- export ----

    def to_dict(self) -> dict[str, Any]:
        return {"started_at_unix": round(self.started_at_unix, 6),
                "seq": self.seq,
                "root": self.root.to_dict()}

    def to_chrome_events(self, pid: int = 1,
                         tid: int = 1) -> list[dict[str, Any]]:
        """Chrome-tracing "complete" (ph=X) events, microsecond ts on
        the process perf_counter timebase — Perfetto-loadable."""
        events: list[dict[str, Any]] = []

        def emit(node: Span) -> None:
            event = {
                "name": node.name,
                "ph": "X",
                "ts": int(node.t0 * 1e6),
                "dur": max(1, int(node.duration_s * 1e6)),
                "pid": pid,
                "tid": tid,
            }
            if node.meta:
                event["args"] = {k: str(v) for k, v in node.meta.items()}
            events.append(event)
            for child in node.children:
                emit(child)

        emit(self.root)
        return events


def _span_count(node: Span) -> int:
    return 1 + sum(_span_count(child) for child in node.children)


class TraceRing:
    """Bounded ring of recently finished traces (newest last).

    Every pushed trace gets a monotone ``seq``; evictions are COUNTED
    (``spans_evicted`` feeds ``chiaswarm_trace_spans_evicted_total``)
    and the ``?since=<seq>`` cursor on ``/debug/traces`` lets a scraper
    detect — rather than silently lose — traces the ring dropped
    between scrapes: if ``cursor.oldest_seq > since + 1``, the gap is
    exactly the evicted window."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get(ENV_RING_CAPACITY, "128") or 128)
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._traces: collections.deque[JobTrace] = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self.traces_evicted = 0
        self.spans_evicted = 0

    def push(self, trace: JobTrace) -> None:
        with self._lock:
            self._seq += 1
            trace.seq = self._seq
            if len(self._traces) == self.capacity:
                oldest = self._traces[0]  # deque maxlen drops it below
                self.traces_evicted += 1
                self.spans_evicted += _span_count(oldest.root)
            self._traces.append(trace)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def traces(self, since: int | None = None) -> list[JobTrace]:
        """Ring contents, oldest first; ``since`` keeps only traces
        pushed after that sequence number (the scrape cursor)."""
        with self._lock:
            out = list(self._traces)
        if since is not None:
            out = [t for t in out if t.seq > int(since)]
        return out

    def cursor(self) -> dict[str, Any]:
        """Scraper bookkeeping: pass ``last_seq`` back as ``?since=``;
        a later ``oldest_seq`` > since + 1 means the ring evicted
        traces the scraper never saw (count in ``evicted_spans``)."""
        with self._lock:
            return {
                "last_seq": self._seq,
                "oldest_seq": self._traces[0].seq if self._traces else None,
                "evicted_traces": self.traces_evicted,
                "evicted_spans": self.spans_evicted,
            }

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def to_dicts(self, since: int | None = None) -> list[dict[str, Any]]:
        return [t.to_dict() for t in self.traces(since)]

    def to_chrome(self, since: int | None = None) -> dict[str, Any]:
        """One Perfetto-loadable document; each trace gets its own tid
        so jobs render as separate tracks."""
        events: list[dict[str, Any]] = []
        for tid, trace in enumerate(self.traces(since), start=1):
            events.extend(trace.to_chrome_events(tid=tid))
        return {"traceEvents": events, "displayTimeUnit": "ms"}


#: process-global ring ``/debug/traces`` reads; workers may substitute
#: their own (hermetic tests) via the ``ring=`` parameter on finish().
TRACE_RING = TraceRing()


def job_trace(job: dict[str, Any] | None) -> JobTrace | None:
    """The trace riding ``job`` (or a result envelope), if any."""
    if not isinstance(job, dict):
        return None
    trace = job.get(TRACE_KEY)
    return trace if isinstance(trace, JobTrace) else None


def attach(job: dict[str, Any], trace: JobTrace) -> None:
    job[TRACE_KEY] = trace


def detach(job: dict[str, Any] | None) -> JobTrace | None:
    """Pop the trace off a job/result dict (before kwargs formatting or
    JSON serialization)."""
    if not isinstance(job, dict):
        return None
    trace = job.pop(TRACE_KEY, None)
    return trace if isinstance(trace, JobTrace) else None


@contextlib.contextmanager
def activate(trace: JobTrace | None) -> Iterator[JobTrace | None]:
    """``trace.active()`` that tolerates None (jobs without traces —
    directly-injected test jobs, replayed dead letters)."""
    if trace is None:
        yield None
        return
    with trace.active():
        yield trace
