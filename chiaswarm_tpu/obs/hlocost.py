"""Static HLO cost model + roofline attainment (swarmlens, ISSUE 11).

Extracted from ``tools/op_roofline.py`` (which is now a thin CLI over
this module) so roofline attainment is an importable SIGNAL instead of
a one-off script: ``benchmark.py`` stamps per-config attainment into
BENCH json, tests cost canned HLO fixtures without a TPU, and the CLI
keeps printing the per-fusion table.

Three layers:

- **parsing/costing** — :func:`parse_hlo_text` statically costs every
  fusion / bare conv / dot / flash custom-call in a scheduled-HLO dump:
  conv FLOPs from window/dim_labels/feature_group_count, dot FLOPs from
  contracting dims, flash FLOPs from the folded (B*H, L, D) operands,
  HBM bytes as operands+result touched once. Each entry also records
  its enclosing computation, and :func:`while_body_computations` names
  the computations executed once per loop trip — so a denoise scan's
  per-step work can be folded N times into a whole-program bound.
- **measured attainment** — :func:`collect_op_times` reads per-op
  device durations from a profiler xplane dump (TPU only) and
  :func:`attainment_rows` joins them against the static costs:
  achieved TFLOP/s, both roofline components, percent-of-roofline per
  fusion (``tools/op_roofline.py``'s table).
- **static attainment** — :func:`static_program_report` needs no
  profiler: the program's modeled FLOPs/bytes and its roofline lower
  bound (sum over fusions of max(compute time, memory time)), compared
  against a measured wall time. This is what BENCH stamps per config —
  on CPU hosts the TPU peak numbers make the percentage notional, but
  the schema and the modeled-work numbers are stable across rounds, so
  the r06+ trajectory can track *where the chip time goes*.

Peaks default to TPU v5e (197 bf16 TFLOP/s, 819 GB/s), overridable via
``CHIASWARM_PEAK_TFLOPS`` / ``CHIASWARM_PEAK_GBPS`` or keyword args.
Pure stdlib at import (jax only inside :func:`collect_op_times` /
:class:`ProgramCapture`), like the rest of ``obs/``.
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Callable, Iterable

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
}

_SHAPE_RE = re.compile(r"\b(pred|[su]\d+|bf16|f16|f32|f64)\[([\d,]*)\]")
_NAME_RE = re.compile(r"%([\w.-]+)")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(.+)$")


def default_peaks() -> tuple[float, float]:
    """(peak TFLOP/s, peak GB/s) from env or the TPU v5e defaults."""
    return (float(os.environ.get("CHIASWARM_PEAK_TFLOPS", "197")),
            float(os.environ.get("CHIASWARM_PEAK_GBPS", "819")))


def _shape_dims(dtype_dims: tuple[str, str]):
    dtype, dims = dtype_dims
    return dtype, [int(d) for d in dims.split(",") if d]


def _shape_bytes(dtype: str, dims: list[int]) -> int:
    return math.prod(dims, start=1) * _DTYPE_BYTES.get(dtype, 4)


def build_shape_map(text: str) -> dict[str, tuple[str, list[int]]]:
    """instruction name -> (dtype, dims) of its (first) result shape.

    Scheduled HLO prints operands as bare ``%names`` (no inline shapes),
    so operand shapes must be resolved through the defining instruction.
    """
    shape_map: dict[str, tuple[str, list[int]]] = {}
    for line in text.splitlines():
        d = _DEF_RE.match(line)
        if not d:
            continue
        m = _SHAPE_RE.search(d.group(2))
        if m:
            shape_map[d.group(1)] = _shape_dims(m.groups())
    return shape_map


def operand_shapes(line: str, opcode: str,
                   shape_map) -> list[tuple[str, list[int]]]:
    """(dtype, dims) of each operand of ``opcode`` on ``line`` — inline
    shapes when the printer emitted them, the definition map otherwise."""
    start = line.find(opcode + "(")
    if start < 0:
        return []
    seg = line[start + len(opcode) + 1:]
    # the operand list ends at the first ")" outside {} layout braces and
    # outside nested "(" groups (tuple-typed inline shapes)
    brace = paren = 0
    end = len(seg)
    for i, ch in enumerate(seg):
        if ch == "{":
            brace += 1
        elif ch == "}":
            brace -= 1
        elif brace == 0 and ch == "(":
            paren += 1
        elif brace == 0 and ch == ")":
            if paren:
                paren -= 1
            else:
                end = i
                break
    seg = seg[:end]
    inline = _SHAPE_RE.findall(seg)
    names = _NAME_RE.findall(seg)
    if inline and len(inline) >= len(names):
        return [_shape_dims(s) for s in inline]
    return [shape_map[n] for n in names if n in shape_map]


def conv_flops(line: str, shape_map) -> float:
    """FLOPs of one HLO convolution instruction (per execution):
    2 * out_elems * window_elems * in_features / feature_group_count."""
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if not m:
        return 0.0
    _, out_dims = _shape_dims(m.groups())
    out_elems = math.prod(out_dims, start=1)

    window = re.search(r"window={[^}]*?size=([\dx]+)", line)
    window_elems = 1
    if window:
        for d in window.group(1).split("x"):
            window_elems *= int(d)

    labels = re.search(r"dim_labels=(\S+?)->", line)
    groups = re.search(r"feature_group_count=(\d+)", line)
    group_n = int(groups.group(1)) if groups else 1

    in_features = 1
    operands = operand_shapes(line, "convolution", shape_map)
    if labels and len(operands) >= 2:
        lhs_rhs = labels.group(1).split("_")
        if len(lhs_rhs) == 2:
            rhs_spec = lhs_rhs[1]  # e.g. "01io"
            rhs_dims = operands[1][1]
            i_pos = rhs_spec.find("i")
            if 0 <= i_pos < len(rhs_dims):
                in_features = rhs_dims[i_pos]
    return 2.0 * out_elems * window_elems * in_features / group_n


def dot_flops(line: str, shape_map) -> float:
    """FLOPs of one HLO dot: 2 * out_elems * prod(contracting dims)."""
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if not m:
        return 0.0
    _, out_dims = _shape_dims(m.groups())
    out_elems = math.prod(out_dims, start=1)
    contract = re.search(r"lhs_contracting_dims={([\d,]*)}", line)
    operands = operand_shapes(line, "dot", shape_map)
    k = 1
    if contract and contract.group(1) and operands:
        lhs_dims = operands[0][1]
        for idx in contract.group(1).split(","):
            i = int(idx)
            if i < len(lhs_dims):
                k *= lhs_dims[i]
    return 2.0 * out_elems * k


def flash_flops(line: str, shape_map) -> float:
    """Attention FLOPs of a flash custom call: 2*BH*L*S*D for QK^T plus
    the same for PV — 4*BH*L*S*D. The kernel folds heads into the lead
    dim and pads L/S to its block lattice, so operands are
    (B*H, L_pad, D) (ops/flash_attention.py) — padded work is real
    compute and is costed as such."""
    operands = [dims for _, dims in
                operand_shapes(line, "custom-call", shape_map)
                if len(dims) == 3]
    if len(operands) < 2:
        return 0.0
    bh, l, d = operands[0]
    s = operands[1][1]
    return 4.0 * bh * l * s * d


def io_bytes(line: str, opcode: str, shape_map) -> int:
    """HBM traffic estimate of one instruction: result + operand shapes,
    each touched once."""
    total = 0
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if m:
        total += _shape_bytes(*_shape_dims(m.groups()))
    for dtype, dims in operand_shapes(line, opcode, shape_map):
        total += _shape_bytes(dtype, dims)
    return total


_COMP_HEADER_RE = re.compile(
    r"\s*(?:ENTRY\s+)?%?([\w.-]+)\s+\(.*\)\s*->\s*.+\{\s*$")


def iter_instruction_lines(text: str):
    """Yield (computation name, raw line) for every instruction line in
    an HLO dump ("" at module scope). The one place the computation
    bracketing logic lives — :func:`parse_hlo_text` and the contract
    checker (``analysis/hlocheck.py``) both walk HLO through it."""
    current = None
    for line in text.splitlines():
        header = _COMP_HEADER_RE.match(line)
        if header:
            current = header.group(1)
            continue
        if line.startswith("}"):
            current = None
            continue
        yield (current or ""), line


def called_computations(text: str) -> set[str]:
    """Computation names referenced by ``calls=`` (fused computations).
    Instructions INSIDE them also parse as bare conv/dot rows — fine for
    the measured join (the profiler only emits fusion names) but a
    double count for a static whole-program sum, which must skip them."""
    return {m.group(1)
            for m in re.finditer(r"calls=%?([\w.-]+)", text)}


def while_body_computations(text: str) -> set[str]:
    """Computation names executed once per while-loop trip (body AND
    condition) — the denoise scan's per-step region. Instructions
    costed inside these computations should be folded by the trip
    count when modeling a whole program."""
    bodies: set[str] = set()
    for line in text.splitlines():
        if re.search(r"\bwhile\(", line):
            for field in ("body", "condition"):
                m = re.search(field + r"=%?([\w.-]+)", line)
                if m:
                    bodies.add(m.group(1))
    return bodies


def parse_hlo_text(text: str) -> dict[str, dict]:
    """fusion/conv/dot name -> {flops, bytes, kind, computation} from
    scheduled HLO. ``computation`` is the enclosing computation name
    ("" at module scope) — join against
    :func:`while_body_computations` to find per-loop-trip work."""
    shape_map = build_shape_map(text)

    # computation name -> [total conv+dot flops inside it, kind]
    comp_flops: dict[str, list] = {}
    for current, line in iter_instruction_lines(text):
        if not current:
            continue
        if " convolution(" in line:
            entry = comp_flops.setdefault(current, [0.0, "conv"])
            entry[0] += conv_flops(line, shape_map)
        elif re.search(r"\bdot\(", line):
            entry = comp_flops.setdefault(current, [0.0, "dot"])
            entry[0] += dot_flops(line, shape_map)
            if entry[1] == "conv":
                entry[1] = "mixed"

    fusions: dict[str, dict] = {}
    for comp, line in iter_instruction_lines(text):
        m = re.match(r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*.*?\bfusion\(",
                     line)
        if not m:
            # bare convs/dots outside fusions still deserve a row
            b = re.match(
                r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*.*?\b"
                r"(convolution|dot)\(", line)
            if b:
                op = b.group(2)
                flops = (conv_flops(line, shape_map)
                         if op == "convolution"
                         else dot_flops(line, shape_map))
                fusions[b.group(1)] = {
                    "flops": flops,
                    "bytes": io_bytes(line, op, shape_map),
                    "kind": "conv" if op == "convolution" else "dot",
                    "computation": comp}
            elif "custom-call" in line and "flash_attention" in line:
                c = re.match(r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=", line)
                if c:
                    fusions[c.group(1)] = {
                        "flops": flash_flops(line, shape_map),
                        "bytes": io_bytes(line, "custom-call", shape_map),
                        "kind": "flash",
                        "computation": comp}
            continue
        name = m.group(1)
        called = re.search(r"calls=%?([\w.-]+)", line)
        flops, kind = 0.0, "other"
        if called and called.group(1) in comp_flops:
            flops, kind = comp_flops[called.group(1)]
        # HBM traffic estimate: every operand + the result, touched once
        # (fusions stream operands from HBM exactly once)
        fusions[name] = {"flops": flops,
                         "bytes": io_bytes(line, "fusion", shape_map),
                         "kind": kind,
                         "computation": comp}
    return fusions


# ---------------------------------------------------------------------------
# measured attainment (profiler join — TPU hosts)
# ---------------------------------------------------------------------------


def collect_op_times(xplane_path: str) -> dict[str, dict]:
    """op name -> {total_ps, count} from the TPU device plane."""
    from jax.profiler import ProfileData

    pd = ProfileData.from_file(xplane_path)
    times: dict[str, dict] = {}
    for plane in pd.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for event in line.events:
                stats = dict(event.stats)
                dur = stats.get("device_duration_ps")
                if dur is None:
                    continue
                name = event.name.split(" = ")[0].lstrip("%")
                entry = times.setdefault(
                    name, {"total_ps": 0, "count": 0,
                           "signature": event.name})
                entry["total_ps"] += int(dur)
                entry["count"] += 1
    return times


def is_container_op(name: str) -> bool:
    """A while/conditional event SPANS its body ops, which also appear
    on the same profiler line — counting both would double-book time."""
    return name.split(".")[0] in ("while", "conditional", "call")


def attainment_rows(times: dict[str, dict], costs: dict[str, dict], *,
                    peak_tflops: float, peak_gbps: float) -> list[dict]:
    """Join measured per-op durations against static costs: one row per
    op with achieved TFLOP/s, the binding roofline side, and
    percent-of-roofline, sorted heaviest-first."""
    total_ps = sum(t["total_ps"] for name, t in times.items()
                   if not is_container_op(name))
    rows = []
    for name, t in times.items():
        if is_container_op(name):
            continue
        cost = costs.get(name) or {}
        secs = t["total_ps"] * 1e-12
        flops = cost.get("flops", 0.0) * t["count"]
        bts = cost.get("bytes", 0) * t["count"]
        t_compute = flops / (peak_tflops * 1e12)
        t_bw = bts / (peak_gbps * 1e9)
        t_roof = max(t_compute, t_bw)
        kind = cost.get("kind", "other")
        if kind == "other" and "flash" in name:
            kind = "flash"
        rows.append({
            "name": name, "kind": kind, "count": t["count"],
            "ms": secs * 1e3,
            "gflop": flops / 1e9, "mb": bts / 1e6,
            "tflops": (flops / secs / 1e12) if secs else 0.0,
            "bound": "flops" if t_compute >= t_bw else "hbm",
            "roof_pct": (100.0 * t_roof / secs) if secs else 0.0,
            "share_pct": 100.0 * t["total_ps"] / max(total_ps, 1),
        })
    rows.sort(key=lambda r: -r["ms"])
    return rows


def conv_attainment_summary(rows: list[dict]) -> dict:
    """Time-weighted conv-fusion roofline attainment over the SANELY
    costed rows. A fusion whose static cost model exceeds its measured
    time by >1.2x is MIS-COSTED (e.g. a multi-conv fusion
    double-counted, or a rematerialized op the profiler books
    elsewhere) — folding it into the average would report >100%
    nonsense; it is counted separately instead."""
    conv_rows = [r for r in rows if r["kind"] in ("conv", "mixed")]
    conv_ms = sum(r["ms"] for r in conv_rows)
    sane = [r for r in conv_rows if r["roof_pct"] <= 120.0]
    sane_ms = sum(r["ms"] for r in sane)
    weighted = (sum(r["roof_pct"] * r["ms"] for r in sane)
                / max(sane_ms, 1e-9))
    total_ms = sum(r["ms"] for r in rows)
    return {
        "total_ms": total_ms,
        "conv_ms": conv_ms,
        "conv_share_pct": 100.0 * conv_ms / max(total_ms, 1e-9),
        "weighted_conv_roof_pct": weighted,
        "sane_ms": sane_ms,
        "miscosted_fusions": len(conv_rows) - len(sane),
        "miscosted_ms": conv_ms - sane_ms,
    }


# ---------------------------------------------------------------------------
# static attainment (no profiler — the BENCH stamping)
# ---------------------------------------------------------------------------


def static_program_report(hlo_text: str, *, steps: int = 1,
                          peak_tflops: float | None = None,
                          peak_gbps: float | None = None,
                          achieved_s: float | None = None,
                          top: int = 5) -> dict:
    """Whole-program roofline model from HLO text alone.

    ``steps`` folds instructions inside while-loop bodies (the denoise
    scan executes its body once per step; static HLO prints it once).
    ``achieved_s`` (a measured wall time for one program execution)
    turns the modeled bound into an attainment percentage; without it
    only the modeled quantities are reported."""
    if peak_tflops is None or peak_gbps is None:
        d_tflops, d_gbps = default_peaks()
        peak_tflops = peak_tflops or d_tflops
        peak_gbps = peak_gbps or d_gbps
    costs = parse_hlo_text(hlo_text)
    loop_comps = while_body_computations(hlo_text)
    fused_comps = called_computations(hlo_text)
    total_flops = total_bytes = 0.0
    bound_s = compute_s = memory_s = 0.0
    heaviest: list[dict] = []
    for name, cost in costs.items():
        if cost.get("computation") in fused_comps:
            continue  # costed via the fusion row that calls it
        count = steps if cost.get("computation") in loop_comps else 1
        flops = cost["flops"] * count
        bts = cost["bytes"] * count
        t_c = flops / (peak_tflops * 1e12)
        t_b = bts / (peak_gbps * 1e9)
        total_flops += flops
        total_bytes += bts
        compute_s += t_c
        memory_s += t_b
        bound_s += max(t_c, t_b)
        heaviest.append({
            "name": name, "kind": cost["kind"], "count": count,
            "gflop": round(flops / 1e9, 3), "mb": round(bts / 1e6, 3),
            "bound_ms": round(max(t_c, t_b) * 1e3, 4),
            "bound": "flops" if t_c >= t_b else "hbm",
        })
    heaviest.sort(key=lambda r: -r["bound_ms"])
    report = {
        "modeled_gflop": round(total_flops / 1e9, 3),
        "modeled_gb": round(total_bytes / 1e9, 4),
        "roofline_bound_s": round(bound_s, 9),
        "bound": "flops" if compute_s >= memory_s else "hbm",
        "steps_folded": int(steps),
        "loop_computations": len(loop_comps),
        "costed_ops": len(costs),
        "heaviest": heaviest[:top],
        "peaks": {"tflops": peak_tflops, "gbps": peak_gbps},
    }
    if achieved_s is not None and achieved_s > 0:
        report["achieved_s"] = round(float(achieved_s), 6)
        report["attainment_pct"] = round(
            100.0 * bound_s / float(achieved_s), 2)
    return report


# ---------------------------------------------------------------------------
# program capture (AOT-compile seam for benchmark.py / op_roofline.py)
# ---------------------------------------------------------------------------


def compiled_hlo_text(compiled: Any) -> str:
    """Post-optimization HLO of a jax Compiled object, across backends:
    CPU exposes ``as_text``; the TPU plugin's scheduled HLO comes from
    ``runtime_executable().get_hlo_text()`` (the exact text the chip
    runs, which op_roofline joins against profiler op names)."""
    runtime = getattr(compiled, "runtime_executable", None)
    if callable(runtime):
        try:
            return runtime().get_hlo_text()
        except Exception:
            pass
    return compiled.as_text()


class ProgramCapture:
    """AOT-capturing stand-in for ``toplevel_jit``: patch it into a
    pipeline module so every top-level program the pipeline builds is
    compiled via ``.lower().compile()`` and its executable is kept for
    HLO extraction. Executables are keyed per input-shape signature, so
    a wrapper reused across shapes (stepper lattice programs) recompiles
    per signature exactly like the real jit would.

    Usage::

        cap = ProgramCapture()
        with cap.patching(diffusion_mod):
            pipe(req)                       # compile + run as usual
        hlo = cap.largest_hlo()             # the generate program
    """

    def __init__(self, real_toplevel_jit: Callable | None = None) -> None:
        if real_toplevel_jit is None:
            from chiaswarm_tpu.core.compile_cache import toplevel_jit
            real_toplevel_jit = toplevel_jit
        self._real = real_toplevel_jit
        self.executables: list[Any] = []
        self._mark = 0

    def capturing_toplevel_jit(self, fn, **kwargs):
        jitted = self._real(fn, **kwargs)
        compiled_by_sig: dict[tuple, Any] = {}

        def signature(args):
            return tuple(
                (getattr(a, "shape", None), str(getattr(a, "dtype", "")))
                if hasattr(a, "shape") else type(a).__name__
                for a in args)

        def wrapper(*args):
            sig = signature(args)
            compiled = compiled_by_sig.get(sig)
            if compiled is None:
                compiled = jitted.lower(*args).compile()
                compiled_by_sig[sig] = compiled
                self.executables.append(compiled)
            return compiled(*args)

        return wrapper

    def patching(self, *modules):
        """Context manager: swap each module's ``toplevel_jit`` binding
        for the capturing wrapper (pipelines import the NAME, so the
        module attribute — not compile_cache — is what must change)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            saved = [(m, m.toplevel_jit) for m in modules]
            for m in modules:
                m.toplevel_jit = self.capturing_toplevel_jit
            try:
                yield self
            finally:
                for m, real in saved:
                    m.toplevel_jit = real

        return cm()

    def mark(self) -> list[Any]:
        """Executables captured since the previous mark (per-config
        attribution in a multi-config bench run)."""
        fresh = self.executables[self._mark:]
        self._mark = len(self.executables)
        return fresh

    def largest_hlo(self, executables: Iterable[Any] | None = None) -> str | None:
        """The longest HLO text among captured executables — in a
        pipeline build that is the end-to-end generate program."""
        pool = list(self.executables if executables is None
                    else executables)
        texts = []
        for compiled in pool:
            try:
                texts.append(compiled_hlo_text(compiled))
            except Exception:
                continue
        return max(texts, key=len) if texts else None
