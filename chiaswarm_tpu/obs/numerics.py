"""swarmlens numerics flight recorder: named probes INSIDE compiled programs.

swarmscope (obs/metrics.py, obs/trace.py) sees job phases and lane
stats; a sharded denoise step is still a black box between dispatch and
result — which is exactly where the GSPMD divergence family (ROADMAP
item 1) has hidden for five rounds. This module puts the instrument
taps inside the jitted programs themselves:

- :func:`tap` is a **trace-time identity** unless ``CHIASWARM_NUMERICS``
  enables the probe: with the env unset the value is returned untouched,
  the lowered HLO is byte-identical to an untapped program, no callback
  exists, and the compile-cache counters cannot move (the invariance
  gate, tests/test_obs.py). With the probe enabled, a handful of
  device-side reductions (L2 norm, mean, absmax, non-finite count, a
  bitwise content checksum) ride an ``io_callback`` into the bounded
  in-process :class:`NumericsRing` — a few floats per probe per step,
  never the tensor itself.
- Probes carry a ``step`` (traced loop index) and a ``shard`` (traced
  ``axis_index`` inside ``shard_map``; -1 = the global value of a
  GSPMD program, which jax gathers before the callback), so two runs of
  a program pair can be aligned record-for-record and bisected to the
  FIRST divergent (step, probe, shard) — ``tools/divergence_bisect.py``.
- Host-side code that already holds a transferred array (the lane
  checkpoint boundary, serving/stepper.py) records through
  :func:`record_host` with the SAME summary math, so device-tapped and
  host-tapped streams are directly comparable.

Enablement (read at TRACE time — flipping it poisons no cached
executable because ``core/compile_cache.py`` folds the live fingerprint
into every static cache key while enabled):

- ``CHIASWARM_NUMERICS`` unset/empty  -> all taps are identity (default)
- ``CHIASWARM_NUMERICS=1``            -> every probe records
- ``CHIASWARM_NUMERICS=diffusion,ring`` -> only probes whose name starts
  with one of the comma-separated prefixes
- ``CHIASWARM_NUMERICS_RING``         -> ring capacity (default 8192)

The ring is served at ``/debug/numerics`` (node/worker.py) and dumps to
a JSONL run file via :func:`dump`. Like the rest of ``obs/``, this
module imports without jax; jax is touched only inside an enabled tap.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Iterable

ENV_ENABLE = "CHIASWARM_NUMERICS"
ENV_RING = "CHIASWARM_NUMERICS_RING"

_DEFAULT_CAPACITY = 8192

#: the summary fields every record carries, in comparison order — the
#: bisect driver tolerance-compares the floats and equality-compares
#: ``nonfinite``/``checksum``
SUMMARY_FIELDS = ("l2", "mean", "absmax", "nonfinite", "checksum")


#: values that mean OFF — an operator writing ``CHIASWARM_NUMERICS=0``
#: must get a disabled recorder, not a fingerprinted cache-key churn
#: with zero matching probes
_OFF_VALUES = frozenset({"", "0", "off", "false", "no", "none"})


def _raw() -> str:
    raw = os.environ.get(ENV_ENABLE, "").strip()
    return "" if raw.lower() in _OFF_VALUES else raw


def enabled() -> bool:
    """True when ANY probe is enabled (the trace-time master switch).
    ``0``/``off``/``false``/``no`` count as unset."""
    return bool(_raw())


def fingerprint() -> str:
    """The raw enablement value, folded into compile-cache keys while
    taps are on so an env flip retraces instead of reusing a tap-less
    (or differently-tapped) executable."""
    return _raw()


#: package-level export alias (``from chiaswarm_tpu.obs import
#: numerics_enabled`` — "enabled" alone is too generic a name there)
def numerics_enabled() -> bool:
    return enabled()


def enabled_for(probe: str) -> bool:
    """Prefix filter, BIDIRECTIONAL so family guards compose with
    per-probe filters: token ``attn`` enables ``attn.q``; token
    ``attn.q`` also satisfies the family guard ``enabled_for("attn")``
    (the call site traces its taps in, and each tap then filters
    itself — so ``CHIASWARM_NUMERICS=attn.q`` records exactly q)."""
    raw = _raw()
    if not raw:
        return False
    if raw.lower() in ("1", "true", "on", "all"):
        return True
    return any(probe.startswith(tok) or tok.startswith(probe)
               for tok in (t.strip() for t in raw.split(",")) if tok)


class NumericsRing:
    """Bounded ring of per-probe summary records (oldest evicted).

    Thread-safe: records arrive from jax callback threads, lane driver
    threads, and the solo executor concurrently. Each record is a plain
    dict (JSON-able end to end: /debug/numerics, dump files, the bisect
    report)."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            try:
                capacity = int(os.environ.get(ENV_RING, "") or
                               _DEFAULT_CAPACITY)
            except ValueError:
                capacity = _DEFAULT_CAPACITY
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._records: collections.deque[dict] = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self.total = 0      # records ever appended
        self.evicted = 0    # records pushed out by the bound

    def record(self, probe: str, *, step: int = -1, shard: int = -1,
               l2: float = 0.0, mean: float = 0.0, absmax: float = 0.0,
               nonfinite: int = 0, checksum: int = 0, size: int = 0,
               note: str | None = None) -> dict:
        rec = {
            "probe": str(probe), "step": int(step), "shard": int(shard),
            "l2": float(l2), "mean": float(mean), "absmax": float(absmax),
            "nonfinite": int(nonfinite), "checksum": int(checksum),
            "size": int(size), "t": time.time(),
        }
        if note is not None:
            rec["note"] = str(note)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._records) == self.capacity:
                self.evicted += 1
            self._records.append(rec)
            self.total += 1
        return rec

    def snapshot(self, probe_prefix: str | None = None,
                 limit: int | None = None) -> list[dict]:
        with self._lock:
            records = list(self._records)
        if probe_prefix:
            records = [r for r in records
                       if r["probe"].startswith(probe_prefix)]
        if limit is not None and limit >= 0:
            # records[-0:] is the WHOLE list — limit=0 must mean none
            records = records[-limit:] if limit else []
        return records

    def drain(self) -> list[dict]:
        """Snapshot AND clear atomically (the bisect driver's per-run
        capture primitive)."""
        with self._lock:
            records = list(self._records)
            self._records.clear()
        return records

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "depth": len(self._records),
                    "total": self.total, "evicted": self.evicted}


class TapRegistry:
    """Named probe points + the ring their summaries land in.

    ``traced_probes`` counts how many times each probe was compiled into
    a program (trace-time, not per-step) — the /debug/numerics header
    that tells an operator which taps exist in the currently-resident
    executables."""

    def __init__(self, ring: NumericsRing | None = None) -> None:
        self.ring = ring if ring is not None else NumericsRing()
        self._lock = threading.Lock()
        self._traced: dict[str, int] = {}
        self._trace_seq: dict[str, int] = {}

    # -- bookkeeping -------------------------------------------------------

    def note_traced(self, probe: str) -> None:
        with self._lock:
            self._traced[probe] = self._traced.get(probe, 0) + 1

    def traced_probes(self) -> dict[str, int]:
        with self._lock:
            return dict(self._traced)

    def trace_seq(self, name: str) -> int:
        """TRACE-time sequence number per counter name — the call-site
        index shared-structure probes use (ops/attention.py): two twin
        programs trace the same modules in the same order, so index N
        aligns across runs. The bisect driver resets these between its
        paired runs (:meth:`reset_trace_seq`) so both twins count from
        zero."""
        with self._lock:
            n = self._trace_seq.get(name, 0)
            self._trace_seq[name] = n + 1
            return n

    def reset_trace_seq(self) -> None:
        with self._lock:
            self._trace_seq.clear()

    # -- the traced tap ----------------------------------------------------

    def tap(self, probe: str, value: Any, *, step: Any = None,
            shard: Any = None, note: str | None = None) -> Any:
        """Summarize ``value`` into the ring — IDENTITY unless ``probe``
        is enabled at trace time.

        ``step``/``shard`` may be traced scalars (a scan's loop index,
        ``lax.axis_index`` inside shard_map) or plain ints; None means
        -1 (unstepped / global). Returns ``value`` unchanged either
        way, so call sites read as pass-throughs."""
        if not enabled_for(probe):
            return value

        import jax
        import jax.numpy as jnp

        from chiaswarm_tpu.core.compat import io_callback

        self.note_traced(probe)
        x = jnp.asarray(value)
        size = int(x.size)
        xf = x.astype(jnp.float32)
        finite = jnp.isfinite(xf)
        xz = jnp.where(finite, xf, 0.0)
        summary = jnp.stack([
            jnp.sqrt(jnp.sum(xz * xz)),
            jnp.sum(xz) / max(size, 1),
            jnp.max(jnp.abs(xz)) if size else jnp.float32(0.0),
        ])
        nonfinite = jnp.sum(~finite, dtype=jnp.int32)
        # bitwise content checksum of the f32 view: integer addition is
        # associative, so the reduction is order-insensitive (bit-exact
        # across shardings) — equality here means "same f32 content"
        checksum = jnp.sum(
            jax.lax.bitcast_convert_type(xz, jnp.uint32),
            dtype=jnp.uint32)
        step_arr = jnp.int32(-1 if step is None else step)
        shard_arr = jnp.int32(-1 if shard is None else shard)

        def _record(step_v, shard_v, summary_v, nonfinite_v, checksum_v):
            # host side of the io_callback: the incoming values are tiny
            # (3 floats + 2 ints); the conversions below never touch the
            # tapped tensor  # swarmlens: allow-host-sync
            s = [float(v) for v in summary_v]
            self.ring.record(
                probe, step=int(step_v), shard=int(shard_v),
                l2=s[0], mean=s[1], absmax=s[2],
                nonfinite=int(nonfinite_v), checksum=int(checksum_v),
                size=size, note=note)

        io_callback(_record, None, step_arr, shard_arr, summary,
                    nonfinite, checksum, ordered=False)
        return value

    # -- the host-side twin ------------------------------------------------

    def record_host(self, probe: str, array: Any, *, step: int = -1,
                    shard: int = -1, note: str | None = None) -> dict | None:
        """Summarize a host-resident array with the SAME math as the
        device tap (f32 view, non-finites zeroed out of the moments), so
        host-tapped streams align against device-tapped ones."""
        if not enabled_for(probe):
            return None
        import numpy as np

        x = np.asarray(array)
        size = int(x.size)
        xf = x.astype(np.float32)
        finite = np.isfinite(xf)
        xz = np.where(finite, xf, np.float32(0.0))
        checksum = int(np.sum(xz.view(np.uint32), dtype=np.uint64)
                       & 0xFFFFFFFF)
        return self.ring.record(
            probe, step=step, shard=shard,
            l2=float(np.sqrt(np.sum(xz.astype(np.float64) ** 2))),
            mean=float(np.sum(xz, dtype=np.float64) / max(size, 1)),
            absmax=float(np.max(np.abs(xz))) if size else 0.0,
            nonfinite=int(np.sum(~finite)), checksum=checksum,
            size=size, note=note)


#: process-global recorder: the serving taps, /debug/numerics, and the
#: bisect driver all share it (one program, one stream)
RING = NumericsRing()
TAPS = TapRegistry(RING)


def tap(probe: str, value: Any, *, step: Any = None, shard: Any = None,
        note: str | None = None) -> Any:
    """Module-level convenience over the global :data:`TAPS` registry —
    the spelling the serving taps use."""
    return TAPS.tap(probe, value, step=step, shard=shard, note=note)


def record_host(probe: str, array: Any, *, step: int = -1, shard: int = -1,
                note: str | None = None) -> dict | None:
    return TAPS.record_host(probe, array, step=step, shard=shard, note=note)


def flush() -> None:
    """Best-effort barrier for in-flight unordered callbacks: records
    from a finished computation may still be draining through the jax
    callback machinery when the output future resolves. The bisect
    driver calls this between runs so stream A cannot bleed into
    stream B."""
    try:
        import jax

        barrier = getattr(jax, "effects_barrier", None)
        if barrier is not None:
            barrier()
            return
    except Exception:
        pass
    time.sleep(0.05)  # no barrier on this jax: give the drain a beat


def dump(path: str, records: Iterable[dict] | None = None) -> int:
    """Write records (default: the live ring) to a JSONL run file.
    Returns the record count."""
    records = list(RING.snapshot() if records is None else records)
    with open(path, "w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return len(records)


def load_dump(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


def debug_payload(probe_prefix: str | None = None,
                  limit: int | None = None) -> dict:
    """The ``/debug/numerics`` response body: enablement, ring stats,
    trace-time probe census, and the (filtered) records."""
    return {
        "enabled": enabled(),
        "filter": fingerprint(),
        "ring": RING.stats(),
        "traced_probes": TAPS.traced_probes(),
        "records": RING.snapshot(probe_prefix, limit),
    }
