"""swarmguard: gray-failure detection and the self-healing ladder.

The fleet survives clean deaths (PR-6 leases + checkpoint/resume),
crashes and OOMs (the PR-2 ladder), and overload (PR-9 shedding) — but a
worker that *degrades without dying* was invisible until this module: a
wedged compiled step holds a lane's rows hostage until the per-row
deadline, a NaN-poisoned trajectory uploads garbage images that settle
as "completed", and a sick device drags every lane on it. This is the
classic gray-failure gap of serving systems; the node must detect its
own sickness and heal in place, not just die loudly. Three mechanisms:

- **In-flight step watchdog**: a monitor thread (:class:`Watchdog`)
  arms a wall-clock budget per compiled call — ``k x`` the lane
  scheduler's step-seconds EWMA, clamped between floor and ceiling
  knobs — around lane step dispatches (serving/stepper.py) and solo
  denoise phases (node/executor.py ``watch_solo``). A call that
  outlives its budget is declared HUNG: the lane is condemned
  (:meth:`~chiaswarm_tpu.serving.stepper.Lane.condemn`) and its rows
  are re-admitted to a freshly built lane, resuming from the last
  step-boundary checkpoint; a hung solo phase raises :class:`StepHung`
  (classified ``transient``) once the call returns, so the PR-2 ladder
  re-runs it.
- **Per-row output validation**: a finite-check on the lane latents
  rides the existing checkpoint-boundary device->host transfer, and
  :func:`screen_images` screens decoded frames for NaN/Inf and
  constant (black) frames. A poisoned row retires with a structured
  non-fatal ``invalid_output`` envelope — a
  :data:`~chiaswarm_tpu.node.resilience.REDISPATCH_KINDS` member and
  breaker fodder — instead of uploading garbage, and never takes its
  lane peers down.
- **Device-health scorer + healing ladder** (:class:`DeviceGuard`):
  consecutive hangs / slow steps / invalid outputs per device feed a
  health score; rungs escalate lane-rebuild (intrinsic to every
  condemnation) -> executable-cache flush
  (``core/compile_cache.py::CompileCache.flush_executables``) ->
  device quarantine (the worker shrinks the slot mesh to the healthy
  chips and re-advertises capacity on /healthz) -> self-restart
  request (graceful PR-2 drain with :data:`GUARD_RESTART_EXIT_CODE`
  so supervisors distinguish "restart me" from a crash).

Chaos seams (deterministic, like the PR-2/PR-3 harnesses):

- ``CHIASWARM_CHAOS_WEDGE_STEP="N:S"``   sleep S seconds inside lane
  step N's armed window — the wedged-compiled-call stand-in (one shot
  process-wide; the first lane to reach step N consumes it)
- ``CHIASWARM_CHAOS_SLOW_STEP="M"``      stretch every lane step to
  ~M x its own wall time (the sick-but-alive device)
- ``CHIASWARM_CHAOS_NAN_STEP="T:R"``     poison lane row R with NaN
  after step T (one shot) — proves the validation rung

Watchdog/validation knobs (env, like the stepper's):

- ``CHIASWARM_GUARD=0``               disable watchdog + validation
- ``CHIASWARM_GUARD_HANG_FACTOR``     budget = factor x step EWMA (20)
- ``CHIASWARM_GUARD_HANG_FLOOR_S``    budget floor, seconds (30)
- ``CHIASWARM_GUARD_HANG_CEIL_S``     budget ceiling — also the cold
  budget while no EWMA exists, so a first-call compile is never
  condemned (600)
- ``CHIASWARM_GUARD_SLOW_FACTOR``     a step slower than factor x the
  EWMA counts as a slow-step health event (4)

Ladder thresholds are worker settings (``guard_*``, node/settings.py);
the rung state surfaces as ``chiaswarm_guard_*`` metric families
(obs/metrics.py) and the ``/healthz`` ``guard`` key.

Stdlib + numpy only — importable without jax, like node/resilience.py,
so the chaos suite and unit tests load it anywhere.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import logging
import os
import threading
import time
from typing import Any, Callable, Iterable

import numpy as np

from chiaswarm_tpu.obs import metrics as obs_metrics

log = logging.getLogger("chiaswarm.guard")

#: exit code a guard-requested self-restart leaves behind (after the
#: graceful PR-2 drain): supervisors restart-on-73 instead of paging
GUARD_RESTART_EXIT_CODE = 73

ENV_ENABLE = "CHIASWARM_GUARD"
ENV_HANG_FACTOR = "CHIASWARM_GUARD_HANG_FACTOR"
ENV_HANG_FLOOR = "CHIASWARM_GUARD_HANG_FLOOR_S"
ENV_HANG_CEIL = "CHIASWARM_GUARD_HANG_CEIL_S"
ENV_SLOW_FACTOR = "CHIASWARM_GUARD_SLOW_FACTOR"

ENV_CHAOS_WEDGE = "CHIASWARM_CHAOS_WEDGE_STEP"
ENV_CHAOS_SLOW = "CHIASWARM_CHAOS_SLOW_STEP"
ENV_CHAOS_NAN = "CHIASWARM_CHAOS_NAN_STEP"


# ---------------------------------------------------------------------------
# failure vocabulary
# ---------------------------------------------------------------------------


class StepHung(RuntimeError):
    """A watched solo phase outlived its hang budget. Raised AFTER the
    wedged call finally returns (a blocked thread cannot be interrupted;
    one that never returns is the PR-2 deadline envelope's job) and
    classified ``transient`` so the ladder re-runs the job."""


class LaneHung(RuntimeError):
    """A condemned lane failed this job's rows. ``resume`` carries the
    last in-memory step-boundary checkpoint (the PR-6 lane state shape)
    or None; the executor re-admits the job to a freshly built lane,
    resuming at the checkpointed step when one exists."""

    def __init__(self, message: str,
                 resume: dict[str, Any] | None = None) -> None:
        super().__init__(message)
        self.resume = resume


class InvalidOutput(RuntimeError):
    """A row's trajectory is numerically poisoned (non-finite latents,
    NaN/Inf or constant decoded frames). The job retires with a
    non-fatal ``invalid_output`` envelope — never an uploaded garbage
    image — and a lease-aware hive redispatches it elsewhere."""


def watchdog_enabled() -> bool:
    """The guard (watchdog + output validation) is ON by default;
    ``CHIASWARM_GUARD=0`` opts the node out entirely."""
    return os.environ.get(ENV_ENABLE, "").strip().lower() not in (
        "0", "false", "off", "no")


def validation_enabled() -> bool:
    return watchdog_enabled()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def hang_budget_s(step_ewma: float) -> float:
    """Wall-clock budget for one compiled lane step: ``factor x`` the
    scheduler's step-seconds EWMA, clamped to [floor, ceiling]. With no
    EWMA yet (the lane's first call — which COMPILES) the ceiling is
    the budget, so a legitimate cold compile is never condemned."""
    factor = _env_float(ENV_HANG_FACTOR, 20.0)
    floor = _env_float(ENV_HANG_FLOOR, 30.0)
    ceil = max(floor, _env_float(ENV_HANG_CEIL, 600.0))
    if step_ewma <= 0.0:
        return ceil
    return min(ceil, max(floor, factor * float(step_ewma)))


def solo_hang_budget_s(step_ewma: float, steps: int) -> float | None:
    """Budget for a whole solo denoise phase (``steps`` x the lane step
    EWMA x factor). None — never armed — when there is no EWMA evidence
    or no step count: a cold solo path must not false-positive on its
    own compile."""
    if step_ewma <= 0.0 or int(steps or 0) <= 0:
        return None
    factor = _env_float(ENV_HANG_FACTOR, 20.0)
    floor = _env_float(ENV_HANG_FLOOR, 30.0)
    ceil = max(floor, _env_float(ENV_HANG_CEIL, 600.0))
    return min(ceil, max(floor, factor * float(step_ewma) * int(steps)))


def slow_factor() -> float:
    return max(1.0, _env_float(ENV_SLOW_FACTOR, 4.0))


#: minimum step-seconds observations before a hang-budget suggestion is
#: called MEASURED — below this the histogram is warm-up noise
SUGGEST_MIN_SAMPLES = 32


def suggest_hang_budget(histogram: Any = None, *,
                        min_samples: int = SUGGEST_MIN_SAMPLES) -> dict:
    """MEASURED watchdog-knob suggestion from the live step-seconds
    histogram (swarmlens, ISSUE 11) — closes the PR-10 carry-over that
    factor 20 / floor 30 s / ceiling 600 s are priors, not measurements.

    Derivation (documented so operators can audit the numbers):

    - ``factor``  = 4x the measured p99/p50 dispersion, clamped to
      [4, 20] — the budget tracks the EWMA, so the factor only needs to
      absorb step-to-step variance plus headroom, not absolute scale.
    - ``floor_s`` = 20x p99, at least 1 s — guards the budget when the
      EWMA is tiny (fast lanes), so scheduler jitter cannot condemn.
    - ``ceil_s``  = 200x p99 bounded to [60 s, the configured ceiling]
      — the worst legitimate warm step; cold COMPILES are exempt from
      this bound by construction (the watchdog gives un-warmed
      dispatches the ceiling alone, so the ceiling need not cover
      compile time, only pathological-but-alive steps).

    Returns ``{"measured": False, "samples": n}`` until ``min_samples``
    observations exist; /healthz, the loadgen report, and BENCH all
    stamp this payload, so a real TPU deployment reads its knobs off
    its own histogram.
    """
    if histogram is None:
        from chiaswarm_tpu.obs.metrics import REGISTRY

        histogram = REGISTRY.get("chiaswarm_stepper_step_seconds")
    current = {
        "factor": _env_float(ENV_HANG_FACTOR, 20.0),
        "floor_s": _env_float(ENV_HANG_FLOOR, 30.0),
        "ceil_s": max(_env_float(ENV_HANG_FLOOR, 30.0),
                      _env_float(ENV_HANG_CEIL, 600.0)),
    }
    samples = histogram.count() if histogram is not None else 0
    if histogram is None or samples < min_samples:
        return {"measured": False, "samples": int(samples),
                "min_samples": int(min_samples), "current": current}
    p50 = histogram.percentile(0.5)
    p99 = histogram.percentile(0.99)
    if not p50 or not p99:
        return {"measured": False, "samples": int(samples),
                "min_samples": int(min_samples), "current": current}
    dispersion = max(1.0, p99 / p50)
    factor = min(20.0, max(4.0, 4.0 * dispersion))
    floor_s = max(1.0, 20.0 * p99)
    ceil_s = min(current["ceil_s"], max(60.0, 200.0 * p99))
    return {
        "measured": True,
        "samples": int(samples),
        "p50_s": round(p50, 6),
        "p99_s": round(p99, 6),
        "suggested": {
            "factor": round(factor, 2),
            "floor_s": round(floor_s, 3),
            "ceil_s": round(max(ceil_s, floor_s), 3),
        },
        "current": current,
    }


# ---------------------------------------------------------------------------
# the watchdog monitor thread
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Armed:
    deadline: float
    on_hang: Callable[[], None]
    tag: str
    fired: bool = False


class Watchdog:
    """One monitor thread declaring in-flight compiled calls hung.

    ``arm(budget, on_hang)`` registers a deadline; ``disarm(ticket)``
    withdraws it and reports whether it fired. Fire-vs-disarm races
    resolve under the watchdog lock: a disarmed ticket can never fire
    afterwards, and a fired one reports ``True`` to its disarmer. The
    ``on_hang`` callback runs in the MONITOR thread and must never
    block on the device — the wedged dispatch is exactly what it
    cannot wait on."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._armed: dict[int, _Armed] = {}
        self._ids = itertools.count(1)
        self._thread: threading.Thread | None = None

    def arm(self, budget_s: float, on_hang: Callable[[], None],
            tag: str = "") -> int:
        ticket = next(self._ids)
        entry = _Armed(time.monotonic() + float(budget_s), on_hang, tag)
        with self._cond:
            self._armed[ticket] = entry
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._monitor, name="swarmguard-watchdog",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        return ticket

    def disarm(self, ticket: int) -> bool:
        """Withdraw ``ticket``; True when it already fired (the caller
        was declared hung while it was away)."""
        with self._cond:
            entry = self._armed.pop(ticket, None)
        return bool(entry is not None and entry.fired)

    def _monitor(self) -> None:
        while True:
            with self._cond:
                now = time.monotonic()
                due = [e for e in self._armed.values()
                       if not e.fired and e.deadline <= now]
                for entry in due:
                    entry.fired = True
                pending = [e.deadline for e in self._armed.values()
                           if not e.fired]
                timeout = (min(pending) - now) if pending else 60.0
            for entry in due:
                log.error("watchdog: %s exceeded its hang budget; "
                          "declaring it hung", entry.tag or "a call")
                try:
                    entry.on_hang()
                except Exception:  # a broken heal hook must not kill
                    log.exception("watchdog on_hang callback failed "
                                  "for %s", entry.tag)
            with self._cond:
                self._cond.wait(timeout=max(0.005, min(timeout, 60.0)))


#: process-wide watchdog (lane drivers + solo phases share the monitor)
WATCHDOG = Watchdog()

# executable-cache flush epoch: the cache_flush heal rung bumps this
# (node/worker.py), and every lane treats its next dispatch as COLD —
# budgeted at the ceiling — because that dispatch recompiles. Without
# it the flush rung would manufacture its own "hangs" out of the very
# recompiles it caused and self-amplify up the ladder.
_FLUSH_LOCK = threading.Lock()
_FLUSH_EPOCH = 0


def flush_epoch() -> int:
    with _FLUSH_LOCK:
        return _FLUSH_EPOCH


def note_cache_flush() -> None:
    """Record that the executable cache was flushed (the heal rung):
    in-flight lanes re-enter their cold-budget window."""
    global _FLUSH_EPOCH
    with _FLUSH_LOCK:
        _FLUSH_EPOCH += 1


def _slot_devices(slot: Any) -> list[str]:
    """Device labels of one mesh slot (stub slots report nothing)."""
    mesh = getattr(slot, "mesh", None)
    if mesh is None:
        return []
    try:
        return [str(d.id) for d in mesh.devices.flatten()]
    except Exception:  # exotic mesh stubs
        return []


@contextlib.contextmanager
def watch_solo(slot: Any, steps: Any, key: Any = None):
    """Arm the watchdog around one solo denoise phase
    (node/executor.py::_execute). Budget = steps x the slot scheduler's
    step EWMA x factor; with no EWMA evidence the phase runs unwatched
    (cold compiles must never be declared hung). On fire: the device
    health ledger hears a solo hang, and :class:`StepHung` raises once
    the wedged call returns — classified transient, so the PR-2 ladder
    re-runs the job.

    ``key`` identifies the solo program variant (the executor passes
    (model, height, width)): solo executables are per-(model, shape)
    compile-cache entries, so the FIRST watched call per key — which
    may be that program's multi-minute compile — runs under the
    ceiling budget, and only later calls of the same key get the tight
    steps-x-EWMA budget. The warm-key set resets on every cache-flush
    heal rung (the flush drops the solo executables too)."""
    stepper = getattr(slot, "_stepper", None)
    if not watchdog_enabled() or stepper is None:
        yield
        return
    try:
        ewma = float(stepper.step_ewma())
        n_steps = int(steps or 0)
    except (AttributeError, TypeError, ValueError):
        yield
        return
    budget = solo_hang_budget_s(ewma, n_steps)
    if budget is None:
        yield
        return
    epoch = flush_epoch()
    state = getattr(slot, "_guard_solo_warm", None)
    warm_keys = (state[1] if isinstance(state, tuple)
                 and state[0] == epoch else set())
    if key not in warm_keys:
        floor = _env_float(ENV_HANG_FLOOR, 30.0)
        budget = max(floor, _env_float(ENV_HANG_CEIL, 600.0))
    guard = getattr(slot, "_guard", None)

    def on_hang() -> None:
        if guard is not None:
            guard.note_hang(_slot_devices(slot), phase="solo")

    ticket = WATCHDOG.arm(budget, on_hang, tag="solo-denoise")
    fired = False
    try:
        yield
    finally:
        fired = WATCHDOG.disarm(ticket)
    if fired:
        raise StepHung(
            f"solo denoise exceeded its {budget:.1f}s hang budget "
            f"(declared hung; retrying through the ladder)")
    try:
        warm_keys.add(key)
        slot._guard_solo_warm = (epoch, warm_keys)
    except (AttributeError, TypeError):  # exotic slot stubs
        pass


# ---------------------------------------------------------------------------
# output validation
# ---------------------------------------------------------------------------


def screen_images(images: Any, *, context: str = "decode") -> None:
    """Post-decode screen: raise :class:`InvalidOutput` when decoded
    frames are numerically poisoned — non-finite values (float stages)
    or a CONSTANT frame (a NaN trajectory casts to a flat/black frame
    in uint8; a legitimate generation is never exactly constant). Runs
    on the host copy the result path already holds, so it costs one
    pass over pixels and no extra transfer."""
    if not validation_enabled():
        return
    arr = np.asarray(images)
    if arr.size == 0:
        return
    if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
        raise InvalidOutput(
            f"non-finite pixel values after {context}; refusing to "
            f"upload a poisoned image")
    # ndim >= 4 is a (B, H, W, C) batch; anything smaller is ONE image
    # (the OutputProcessor convention) — iterating an (H, W, C) image
    # as H "frames" would flag any legitimate solid border row
    frames = arr if arr.ndim >= 4 else arr[None]
    for i, frame in enumerate(frames):
        flat = np.asarray(frame)
        if flat.size and flat.max() == flat.min():
            raise InvalidOutput(
                f"frame {i} is constant (value {flat.flat[0]!r}) after "
                f"{context} — a poisoned trajectory, not an image")


# ---------------------------------------------------------------------------
# chaos seams
# ---------------------------------------------------------------------------

_CHAOS_LOCK = threading.Lock()
_CHAOS_CONSUMED: set[str] = set()


def consume_chaos(kind: str) -> bool:
    """One-shot chaos gate: the first caller for ``kind`` wins, so a
    scripted wedge/NaN fires in exactly one lane process-wide no matter
    how many lanes reach the trigger step."""
    with _CHAOS_LOCK:
        if kind in _CHAOS_CONSUMED:
            return False
        _CHAOS_CONSUMED.add(kind)
        return True


def reset_chaos() -> None:
    """Re-arm the one-shot chaos seams (tests)."""
    with _CHAOS_LOCK:
        _CHAOS_CONSUMED.clear()


@dataclasses.dataclass(frozen=True)
class LaneChaos:
    """Parsed lane chaos plan. Lanes re-read the env at every dispatch
    (serving/stepper.py) and count trigger steps RELATIVE to the step
    at which the plan first appeared — so a test can warm lanes first,
    then arm a wedge/NaN that fires a deterministic number of steps
    later, on fresh and reused lanes alike."""

    wedge_step: int | None = None
    wedge_s: float = 0.0
    slow_mult: float = 1.0
    nan_step: int | None = None
    nan_row: int = 0

    @classmethod
    def from_env(cls) -> "LaneChaos":
        def pair(name: str) -> tuple[int, float] | None:
            raw = os.environ.get(name, "").strip()
            if not raw or ":" not in raw:
                return None
            a, b = raw.split(":", 1)
            try:
                return int(a), float(b)
            except ValueError:
                return None

        wedge = pair(ENV_CHAOS_WEDGE)
        nan = pair(ENV_CHAOS_NAN)
        return cls(
            wedge_step=None if wedge is None else wedge[0],
            wedge_s=0.0 if wedge is None else wedge[1],
            slow_mult=max(1.0, _env_float(ENV_CHAOS_SLOW, 1.0)),
            nan_step=None if nan is None else nan[0],
            nan_row=0 if nan is None else int(nan[1]),
        )

    @property
    def armed(self) -> bool:
        return (self.wedge_step is not None or self.nan_step is not None
                or self.slow_mult > 1.0)

    def wedge_at(self, step: int) -> float:
        """Seconds to wedge inside lane step ``step`` (0 = no wedge)."""
        if self.wedge_step is None or step != self.wedge_step:
            return 0.0
        return self.wedge_s if consume_chaos("wedge") else 0.0

    def nan_wants(self, step: int) -> int | None:
        """Row the NaN seam WANTS to poison at (or after) lane step
        ``step`` — the lane consumes the one-shot only once the row is
        actually ELIGIBLE (active and mid-trajectory): a seam spent on
        a padding row or a row about to retire would prove nothing."""
        if self.nan_step is None or step < self.nan_step:
            return None
        return self.nan_row

    def slow_extra_s(self, step_s: float) -> float:
        """Extra sleep stretching this step to ~slow_mult x its time."""
        if self.slow_mult <= 1.0:
            return 0.0
        return max(0.0, float(step_s) * (self.slow_mult - 1.0))


# ---------------------------------------------------------------------------
# device health + the healing ladder
# ---------------------------------------------------------------------------

#: heal rung vocabulary (escalation order; ``lane_rebuild`` is counted
#: on every condemnation — it IS the condemnation — the later rungs
#: queue worker-side actions)
HEAL_RUNGS = ("lane_rebuild", "cache_flush", "device_quarantine",
              "restart")

#: hang phases the counter labels by
HANG_PHASES = ("lane", "solo")

#: streak weight per event kind: a hang is stronger evidence of a sick
#: device than one slow step or one poisoned row
EVENT_WEIGHTS = {"hang": 2, "invalid_output": 1, "slow_step": 1}


@dataclasses.dataclass(frozen=True)
class HealAction:
    """One queued ladder action for the worker to apply."""

    rung: str
    device: str
    reason: str


class DeviceGuard:
    """Per-device health ledger + the healing-ladder policy.

    Events (hangs, slow steps, invalid outputs) grow a per-device
    sickness STREAK — weighted, consecutive: any OK event shrinks it —
    and the health gauge derives from the streak
    (``1 - streak / restart_after``, floored at 0). Crossing a rung
    threshold queues exactly one :class:`HealAction` per rung per
    sickness episode; the worker applies them from its poll loop
    (node/worker.py::_apply_heal_rungs) and the episode's rungs re-arm
    once the device recovers to streak 0.

    Thread-safe on an injectable clock; hermetic per worker (metrics
    land on the worker's registry, like the overload controller)."""

    def __init__(self, *, enabled: bool = True,
                 cache_flush_after: int = 3,
                 quarantine_after: int = 5,
                 restart_after: int = 7,
                 clock: Callable[[], float] = time.monotonic,
                 metrics_registry: Any = None) -> None:
        self.enabled = bool(enabled)
        self.cache_flush_after = max(1, int(cache_flush_after))
        self.quarantine_after = max(self.cache_flush_after,
                                    int(quarantine_after))
        self.restart_after = max(self.quarantine_after, int(restart_after))
        self._clock = clock
        self._lock = threading.Lock()
        self._streak: dict[str, int] = {}
        #: rung index (into HEAL_RUNGS) already queued this episode
        self._rung_done: dict[str, int] = {}
        self._actions: collections.deque[HealAction] = collections.deque()
        self.quarantined: set[str] = set()
        self.restart_requested = False
        self.hangs_total = 0
        self.invalid_total = 0
        self.slow_total = 0
        self.condemned_lanes = 0
        reg = metrics_registry
        self._m_hangs = obs_metrics.guard_hangs_counter(reg)
        self._m_condemned = obs_metrics.guard_condemned_counter(reg)
        self._m_invalid = obs_metrics.guard_invalid_counter(reg)
        self._m_health = obs_metrics.guard_device_health_gauge(reg)
        self._m_rungs = obs_metrics.guard_heal_rung_counter(reg)
        self._m_quarantined = obs_metrics.guard_quarantined_gauge(reg)
        # pre-seed every enumerable vocabulary so the families render
        # zeroes from the FIRST scrape (the ISSUE-6 convention)
        for phase in HANG_PHASES:
            self._m_hangs.inc(0, phase=phase)
        for rung in HEAL_RUNGS:
            self._m_rungs.inc(0, rung=rung)
        self._m_condemned.inc(0)
        self._m_quarantined.set(0)

    # ---- event intake ----

    def seed_devices(self, devices: Iterable[str]) -> None:
        """Register the devices this worker serves so their health
        gauges render 1.0 before any event lands."""
        with self._lock:
            for device in devices:
                self._streak.setdefault(str(device), 0)
        self._publish_health()

    def note_hang(self, devices: Iterable[str], phase: str = "lane") -> None:
        with self._lock:
            self.hangs_total += 1
        self._m_hangs.inc(phase=phase if phase in HANG_PHASES else "lane")
        self._note_bad(devices, "hang")

    def note_condemned(self) -> None:
        with self._lock:
            self.condemned_lanes += 1
        self._m_condemned.inc()
        self._m_rungs.inc(rung="lane_rebuild")

    def note_invalid_output(self, devices: Iterable[str],
                            model: str = "") -> None:
        with self._lock:
            self.invalid_total += 1
        self._m_invalid.inc(model=str(model or "unknown"))
        self._note_bad(devices, "invalid_output")

    def note_slow_step(self, devices: Iterable[str]) -> None:
        with self._lock:
            self.slow_total += 1
        self._note_bad(devices, "slow_step")

    def note_ok(self, devices: Iterable[str]) -> None:
        """A healthy step/job on these devices: the sickness streak
        decays (one weight unit per OK), and a device that reaches 0
        re-arms its ladder for the next episode."""
        with self._lock:
            for device in (str(d) for d in devices):
                streak = max(0, self._streak.get(device, 0) - 1)
                self._streak[device] = streak
                if streak == 0:
                    self._rung_done.pop(device, None)
        self._publish_health()

    def _note_bad(self, devices: Iterable[str], kind: str) -> None:
        weight = EVENT_WEIGHTS.get(kind, 1)
        queued: list[HealAction] = []
        with self._lock:
            for device in (str(d) for d in devices):
                streak = self._streak.get(device, 0) + weight
                self._streak[device] = streak
                if not self.enabled:
                    continue
                done = self._rung_done.get(device, 0)
                for rung_idx, (rung, threshold) in enumerate((
                        ("cache_flush", self.cache_flush_after),
                        ("device_quarantine", self.quarantine_after),
                        ("restart", self.restart_after)), start=1):
                    if streak >= threshold and done < rung_idx:
                        # event attribution is SLOT-granular (every
                        # device of a slot hears every event), so all
                        # its chips cross each threshold together:
                        # queue each rung ONCE per call — and
                        # quarantine amputates at most one chip per
                        # process; if sickness continues, the next
                        # rung (restart) is the honest answer, not
                        # shrinking a healthy mesh chip by chip
                        repeat = any(a.rung == rung for a in queued)
                        if rung == "device_quarantine" and (
                                repeat or self.quarantined):
                            done = rung_idx
                            continue
                        if repeat:
                            done = rung_idx
                            continue
                        reason = (f"device {device} sickness streak "
                                  f"{streak} >= {threshold} ({kind})")
                        queued.append(HealAction(rung, device, reason))
                        done = rung_idx
                        if rung == "device_quarantine":
                            self.quarantined.add(device)
                        elif rung == "restart":
                            self.restart_requested = True
                self._rung_done[device] = done
            for action in queued:
                self._actions.append(action)
        for action in queued:
            self._m_rungs.inc(rung=action.rung)
            log.error("guard ladder: %s queued (%s)", action.rung,
                      action.reason)
        self._m_quarantined.set(len(self.quarantined))
        self._publish_health()

    def _publish_health(self) -> None:
        with self._lock:
            scores = {device: max(0.0, 1.0 - streak / self.restart_after)
                      for device, streak in self._streak.items()}
        for device, score in scores.items():
            self._m_health.set(round(score, 4), device=device)

    def health_scores(self) -> dict[str, float]:
        with self._lock:
            return {device: round(
                max(0.0, 1.0 - streak / self.restart_after), 4)
                for device, streak in sorted(self._streak.items())}

    # ---- the worker drains queued actions ----

    def take_actions(self) -> list[HealAction]:
        with self._lock:
            actions = list(self._actions)
            self._actions.clear()
        return actions

    def snapshot(self) -> dict[str, Any]:
        """The /healthz ``guard`` key (node/worker.py)."""
        with self._lock:
            streaks = dict(sorted(self._streak.items()))
            return {
                "enabled": self.enabled,
                "hangs": self.hangs_total,
                "condemned_lanes": self.condemned_lanes,
                "invalid_outputs": self.invalid_total,
                "slow_steps": self.slow_total,
                "streaks": streaks,
                "health": {d: round(max(0.0, 1.0 - s / self.restart_after),
                                    4) for d, s in streaks.items()},
                "quarantined": sorted(self.quarantined),
                "restart_requested": self.restart_requested,
                "rungs": {"cache_flush_after": self.cache_flush_after,
                          "quarantine_after": self.quarantine_after,
                          "restart_after": self.restart_after},
            }
