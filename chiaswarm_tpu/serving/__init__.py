"""Serving-side schedulers (no reference analog).

``stepper`` implements continuous step-level batching: jobs join and
leave a resident batched denoise loop at step boundaries instead of
queueing behind whole solo programs. ``residency`` owns the HBM model
ledger (measured footprints, eviction, prefetch, degradation rungs).
``guard`` is the gray-failure layer (ISSUE 10): the in-flight step
watchdog, per-row output validation, and the per-device self-healing
ladder.
"""
