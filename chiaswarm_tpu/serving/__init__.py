"""Serving-side schedulers (no reference analog).

``stepper`` implements continuous step-level batching: jobs join and
leave a resident batched denoise loop at step boundaries instead of
queueing behind whole solo programs.
"""
