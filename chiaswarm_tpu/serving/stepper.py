"""Continuous step-level batching: the UNet step is the scheduling quantum.

Burst coalescing (node/executor.py::synchronous_do_work_batch) only merges
jobs that arrive in the SAME poll with identical static params — a job that
arrives one poll later waits behind a full solo program. This module applies
iteration-level admission (Orca-style continuous batching, popularized for
LLM serving by vLLM) to diffusion: one resident batched denoise program per
(model, bucketed-shape, steps-capacity, sampler) **lane** executes ONE step
per call over a fixed lane width of rows; incoming jobs splice into free
row slots at the next step boundary, finished rows retire early and their
VAE decode + host transfer overlap the ongoing UNet steps.

Per-row traced state (latents, carry keys, step index, START index,
sigma/timestep tables, guidance, multistep history, inpaint mask/known
stacks, ControlNet hint embeddings, active mask) makes rows at different
progress — and with different step counts and WORKLOADS — coexist in one
program; the per-row math is a ``vmap`` of the solo sampler step, so
every row walks exactly its solo trajectory (the numerical-equivalence
gate, tests/test_stepper.py). Since ISSUE 7 lanes are the ENGINE, not
the experiment: the default is ON (``CHIASWARM_STEPPER=0`` opts out and
restores the burst/solo routing), and eligibility spans txt2img,
img2img (per-row denoise start indices), inpaint (per-row mask + clean
latents, reprojected by the shared sampler helper) and ControlNet
(bundle-keyed lanes; per-row hint embeddings + conditioning scales).
Admission never compiles: the lane executables (encode / row-init /
control-embed / step / decode, pipelines/diffusion.py ``stepper_*_fn``)
are keyed by buckets alone.

Lane capacity is a CLOSED LOOP (ISSUE 7c): instead of a fixed width,
each lane carries a :class:`LaneWidthController` that follows the
scheduler's arrival-rate EWMA (fed by submissions) plus the worker
poll loop's short-lived row hints, and the lane's occupancy EWMA — the
same signal the
``chiaswarm_stepper_lane_occupancy_ratio`` histogram exports. Lanes
grow when pending rows cannot fit (or occupancy stays high while
arrivals continue) and shrink when occupancy stays low, ONLY at step
boundaries, and only onto the pow2 width lattice the compile cache
already buckets by — so a resize reuses (or compiles once, bounded) a
lattice program, and admission itself still never compiles.

Fault containment composes with the PR-2 machinery: a failed lane fails
every resident row's future — the executor falls back to the per-job path
(where the OOM ladder splits and retries), so the chaos zero-loss
invariant (every job -> exactly one envelope or dead-letter) holds; rows
carry their own in-lane deadline; an OOM'd lane additionally halves the
lane width it will rebuild with. ``drain``/``shutdown`` retire lanes
cleanly on worker stop.

Fleet durability (ISSUE 6): when the owning worker attaches a
checkpoint spool to the slot (``slot._checkpoint_spool``,
node/worker.py), each lane snapshots every resident job's per-row state
— latents, carry PRNG keys, multistep history, step index — at step
boundaries, every ``CHIASWARM_STEPPER_CKPT_EVERY`` steps. The worker's
heartbeat pushes the latest snapshot to a lease-aware hive
(node/minihive.py); a job redelivered after this worker dies arrives
with a ``resume`` payload and splices into a lane at step k through the
SAME mid-flight admission path fresh jobs use — restored rows walk the
identical solo trajectory from step k because keys/latents/history are
bit-exact.

Knobs (operator guide: README "Continuous batching" and "Fleet
operations"):

- ``CHIASWARM_STEPPER=0``  opt OUT of lane routing (default on)
- ``CHIASWARM_STEPPER_LANE_WIDTH``  PIN rows per lane (disables the
  adaptive controller; unset = adaptive width over the pow2 lattice)
- ``CHIASWARM_STEPPER_ADAPTIVE=0``  disable adaptive width without
  pinning (lanes stay at their initial width)
- ``CHIASWARM_STEPPER_MIN_WIDTH`` / ``_MAX_WIDTH``  adaptive bounds
  (defaults: 1 and 4x the slot-saturation heuristic, pow2-bucketed)
- ``CHIASWARM_STEPPER_ROW_DEADLINE_S``  per-row in-lane deadline (600)
- ``CHIASWARM_STEPPER_IDLE_S``  idle grace before a lane retires (15)
- ``CHIASWARM_STEPPER_CKPT_EVERY``  steps between lane checkpoints
  (default 8; 0 disables — each snapshot costs one device->host copy
  of the lane state)
- ``CHIASWARM_STEPPER_STEP_DELAY_S``  artificial per-step delay
  (chaos/test seam: stretches lane wall time so fleet faults can land
  deterministically mid-lane; keep 0 in production)

Gray-failure guard (ISSUE 10, serving/guard.py): every step dispatch
runs under the watchdog's hang budget (k x the step EWMA) — a wedged
call condemns the lane from the monitor thread and its rows re-admit
to a freshly built lane, resuming from the last step-boundary
checkpoint; the checkpoint transfer doubles as a per-row finite-check,
so a NaN-poisoned row retires ``invalid_output`` without touching its
peers. ``CHIASWARM_GUARD*`` knobs and the ``CHIASWARM_CHAOS_*`` seams
(scripted wedge / slow-step / NaN) are documented there.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import logging
import os
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from chiaswarm_tpu.obs import numerics as _numerics
from chiaswarm_tpu.obs.metrics import (
    REGISTRY,
    STEPPER_UNET_EVAL_MODES,
    arrival_rate_gauge,
    lane_admissions_counter,
    lane_occupancy_histogram,
    lane_resizes_counter,
    resume_step_histogram,
    steps_skipped_counter,
    unet_evals_counter,
    unet_evals_per_image_histogram,
)
from chiaswarm_tpu.obs.profiling import annotate
from chiaswarm_tpu.obs.trace import span

# swarmguard (ISSUE 10): the in-flight step watchdog, per-row output
# validation, and the chaos seams that prove them deterministically
from chiaswarm_tpu.serving import guard as _guard
from chiaswarm_tpu.serving.guard import InvalidOutput, LaneHung

# the rows/second EWMA the width controllers read is the SAME demand
# primitive the residency manager ranks prefetch candidates with — one
# implementation, shared (ISSUE 8 reuses the ISSUE-7c pattern)
from chiaswarm_tpu.serving.residency import ArrivalEwma as _ArrivalEwma

log = logging.getLogger("chiaswarm.stepper")

# per-step latency distribution under mixed admission — THE signal lane
# width and deadline tuning read (ISSUE 4). Process-global registry: the
# lane drivers are detached threads without a worker handle; /metrics
# serves this registry alongside the worker's own. The timer wraps the
# dispatch INCLUDING the depth-2 window throttle, so in steady state it
# converges on the true device step latency, not the async-submit cost.
_STEP_SECONDS = REGISTRY.histogram(
    "chiaswarm_stepper_step_seconds",
    "lane step wall time (dispatch + pipelined-window backpressure)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0))
_LANE_ADMIT_SECONDS = REGISTRY.histogram(
    "chiaswarm_stepper_admission_seconds",
    "submit-side admission prep (tokenize + encode + row init)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0))
# per-lane occupancy ratio at each step (obs/metrics.py ISSUE-5 tie-in):
# distribution over time, where /healthz's lane_occupancy is only the
# lifetime average
_LANE_OCCUPANCY = lane_occupancy_histogram()
# resume telemetry (ISSUE 6): which step redelivered rows splice back in
# at — the fleet-level proof that redelivery resumes instead of
# restarting (obs/metrics.py documents the tuning story)
_RESUME_STEP = resume_step_histogram()
_CKPT_SECONDS = REGISTRY.histogram(
    "chiaswarm_stepper_checkpoint_seconds",
    "wall time of one lane checkpoint snapshot (device->host + spool)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
# adaptive-width control loop (ISSUE 7c): resize actions, the demand
# EWMA, and per-workload admission breadth — declared in obs/metrics.py
_LANE_RESIZES = lane_resizes_counter()
_ARRIVAL_RATE = arrival_rate_gauge()
_LANE_ADMISSIONS = lane_admissions_counter()
# step-collapse families (ISSUE 12): per-row UNet evals by mode, deep-
# blocks-skipped steps, and the per-image full-eval histogram — shared
# with the solo path (pipelines/diffusion.py increments the same
# process-global families per submitted job)
_UNET_EVALS = unet_evals_counter()
_STEPS_SKIPPED = steps_skipped_counter()
_EVALS_PER_IMAGE = unet_evals_per_image_histogram()

ENV_ENABLE = "CHIASWARM_STEPPER"
ENV_LANE_WIDTH = "CHIASWARM_STEPPER_LANE_WIDTH"
ENV_ADAPTIVE = "CHIASWARM_STEPPER_ADAPTIVE"
ENV_MIN_WIDTH = "CHIASWARM_STEPPER_MIN_WIDTH"
ENV_MAX_WIDTH = "CHIASWARM_STEPPER_MAX_WIDTH"
ENV_ROW_DEADLINE = "CHIASWARM_STEPPER_ROW_DEADLINE_S"
ENV_IDLE_S = "CHIASWARM_STEPPER_IDLE_S"
ENV_SHARD_ROWS = "CHIASWARM_STEPPER_SHARD_ROWS"
ENV_CKPT_EVERY = "CHIASWARM_STEPPER_CKPT_EVERY"
ENV_STEP_DELAY = "CHIASWARM_STEPPER_STEP_DELAY_S"

#: lane workload kinds (the ``workload`` label vocabulary)
WORKLOADS = ("txt2img", "img2img", "inpaint", "controlnet")

# pre-seed every label vocabulary at import so the control-loop families
# render zeroes from the FIRST /metrics scrape (dashboards need the
# zeroes — the ISSUE-6 convention for the lease/resume families)
_ARRIVAL_RATE.set(0.0)
for _direction in ("grow", "shrink"):
    _LANE_RESIZES.inc(0, direction=_direction)
for _workload in WORKLOADS:
    _LANE_ADMISSIONS.inc(0, workload=_workload)
for _mode in STEPPER_UNET_EVAL_MODES:
    _UNET_EVALS.inc(0, mode=_mode)
_STEPS_SKIPPED.inc(0)


# ---- resume-state packing ------------------------------------------------
#
# Checkpoints must survive JSON serialization end to end: spool file ->
# heartbeat body -> hive store -> redelivered job payload. Arrays ride
# as base64 raw bytes + dtype/shape — exact (bit-for-bit, no float
# round-trip through decimal), compact enough for latent-sized state.


def pack_array(arr: Any) -> dict[str, Any]:
    a = np.ascontiguousarray(np.asarray(arr))
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def unpack_array(spec: dict[str, Any]) -> np.ndarray:
    a = np.frombuffer(base64.b64decode(spec["b64"]),
                      dtype=np.dtype(str(spec["dtype"])))
    return a.reshape([int(s) for s in spec["shape"]]).copy()


class ResumeReject(RuntimeError):
    """The resume payload does not match this job (wrong shape/steps,
    corrupt arrays): the job restarts from step 0 — losing progress is
    acceptable, resuming onto the WRONG trajectory is not."""


def stepper_enabled() -> bool:
    """Continuous batching is the DEFAULT engine (ISSUE 7): eligible
    diffusion jobs ride lanes unless the operator opts out with
    ``CHIASWARM_STEPPER=0``, which restores the pre-lane burst/solo
    routing end to end (the per-job fallback path is unchanged either
    way)."""
    return os.environ.get(ENV_ENABLE, "").strip().lower() not in (
        "0", "false", "off", "no")


def adaptive_enabled() -> bool:
    """Adaptive lane width is on by default; a pinned
    ``CHIASWARM_STEPPER_LANE_WIDTH`` or ``CHIASWARM_STEPPER_ADAPTIVE=0``
    turns the controller off (lanes then keep their creation width)."""
    if os.environ.get(ENV_LANE_WIDTH, "").strip():
        return False
    return os.environ.get(ENV_ADAPTIVE, "").strip().lower() not in (
        "0", "false", "off", "no")


class LaneReject(RuntimeError):
    """The job cannot ride a lane (too many rows, steps beyond the
    capacity lattice, ...) — run it through the ordinary path."""


class LaneDeadline(TimeoutError):
    """A row exceeded its in-lane deadline and was retired unfinished."""


class LaneRetired(RuntimeError):
    """The lane shut down (drain/stop/fault) before the row completed."""


@dataclasses.dataclass(eq=False)  # identity semantics: membership checks
class _RowJob:                    # must never compare device/numpy fields
    """One job's rows plus everything admission needs. Prepared in the
    SUBMITTING thread (tokenize/encode/init dispatch happen there) so the
    driver stays a pure step pump."""

    job_id: Any
    n_rows: int
    steps: int
    guidance: float
    sigmas: np.ndarray          # (steps+1,) this job's ladder
    timesteps: np.ndarray       # (steps,)
    ctx_u: Any                  # (n, L, D) device
    ctx_c: Any
    pooled_u: Any               # (n, P) device or None (non-XL)
    pooled_c: Any
    keys0: Any                  # (n, ...) carry keys after the init split
    x0: Any                     # (n, lh, lw, C) initial latents
    deadline: float             # absolute time.monotonic() cutoff
    future: Future = dataclasses.field(default_factory=Future)
    admitted_at_step: int = -1
    slots: list[int] = dataclasses.field(default_factory=list)
    # splice-wait telemetry (swarmsight, ISSUE 13): submit vs admit on
    # perf_counter, surfaced as ``splice_wait_s`` in the lane info so
    # the flight record's budget attribution can separate "waited
    # behind a full lane" from "was stepping"
    submitted_t: float = dataclasses.field(
        default_factory=time.perf_counter)
    admitted_t: float = 0.0
    # redelivered-job resume (ISSUE 6): rows splice in at step
    # ``resume_step`` with restored latents/keys and the multistep
    # history ``old0`` instead of freshly drawn noise at step 0
    resume_step: int = 0
    old0: Any = None
    # workload row state (ISSUE 7b): img2img rows start partway down the
    # ladder; inpaint rows carry their latent-grid mask + clean source
    # latents; ControlNet rows carry the pre-embedded hint + scale
    workload: str = "txt2img"
    start_step: int = 0
    known0: Any = None          # (n, lh, lw, C) clean init latents
    mask0: Any = None           # (n, lh, lw, 1) latent mask, 1=regenerate
    cond0: Any = None           # (n, lh, lw, C0) pre-embedded hint
    cscale: float = 1.0         # ControlNet conditioning scale
    # DeepCache step-level reuse (ISSUE 12): the canonical per-job
    # schedule plus resume state — cached deep activations (uncond/cond
    # halves), cache validity, and the skipped-steps tally so a resumed
    # row's per-image eval accounting stays whole-trajectory
    reuse_schedule: tuple[int, ...] = ()
    cache_u0: Any = None        # (n, lh, lw, C1) restored deep cache
    cache_c0: Any = None
    cache_ok0: bool = False
    skipped0: int = 0

    @property
    def idx0(self) -> int:
        """Ladder index a freshly admitted row begins at: the recorded
        resume step for redelivered rows, else the workload's start
        index (0 for txt2img/inpaint, strength-derived for img2img)."""
        return self.resume_step if self.resume_step > 0 else self.start_step


class LaneWidthController:
    """Closed-loop lane capacity (ISSUE 7c): width follows demand.

    Two signals, one actuator. Demand is the scheduler's arrival-rate
    EWMA (rows/sec, fed by submissions and the worker's poll hints);
    supply is the lane's occupancy EWMA — the per-step ratio the
    ``chiaswarm_stepper_lane_occupancy_ratio`` histogram exports.
    Decisions land ONLY at step boundaries (the driver calls
    :meth:`decide` between dispatches — a lane mid-step is untouchable
    by construction) and only onto the pow2 width lattice, so the
    program set stays bounded by the compile-cache buckets:

    - **grow under burst**: pending rows that cannot fit the free slots
      resize immediately to the bucket that holds them; sustained
      occupancy >= ``grow_at`` with arrivals still flowing doubles the
      width ahead of the queue.
    - **shrink under trickle**: occupancy <= ``shrink_at`` for
      ``patience`` consecutive boundaries with nothing pending halves
      the width — padding rows are batched UNet FLOPs burned, the
      exact waste BENCH r05's 0.33 padding ratio measures.
    - bounds are clamped per decision, so an OOM width-limit recorded
      by the scheduler (``note_oom`` halving) is respected even when it
      arrives between boundaries.

    Pure host arithmetic on an injected clock — unit-testable without
    lanes (tests/test_stepper.py::TestLaneWidthController)."""

    def __init__(self, *, min_width: int = 1, max_width: int = 128,
                 alpha: float = 0.25, grow_at: float = 0.75,
                 shrink_at: float = 0.25, patience: int = 6,
                 rate_window_s: float = 10.0) -> None:
        # defaults are the swarmload harness sweep winner (ISSUE 9:
        # node/loadgen.py::sweep_lane_gains, seed "swarmload" — grow
        # earlier at 0.75 occupancy, hold width until 0.25): the table
        # rides every BENCH json under configs.load_harness, and
        # tests/test_loadgen.py pins defaults == winner
        # (pre-sweep statics were grow_at=0.875, shrink_at=0.375)
        self.min_width = max(1, int(min_width))
        self.max_width = max(self.min_width, int(max_width))
        self.alpha = float(alpha)
        self.grow_at = float(grow_at)
        self.shrink_at = float(shrink_at)
        self.patience = max(1, int(patience))
        self.rate_window_s = float(rate_window_s)
        self.occ_ewma = 0.0
        # EWMA-driven moves need ``patience`` boundaries of evidence
        # from birth too — only the pending-cannot-fit burst reaction
        # is allowed to act immediately
        self._boundaries_since_resize = 0

    def decide(self, width: int, occupied: int, pending_rows: int,
               rate: float, *, max_width: int | None = None) -> int:
        """Target width for the NEXT step, given current occupancy,
        rows waiting at the gate, and the arrival-rate EWMA. Returns
        ``width`` unchanged when the loop holds steady."""
        from chiaswarm_tpu.core.compile_cache import bucket_batch

        hi = self.max_width if max_width is None else max(1, min(
            self.max_width, int(max_width)))
        lo = min(self.min_width, hi)
        self.occ_ewma += self.alpha * (occupied / max(1, width)
                                       - self.occ_ewma)
        self._boundaries_since_resize += 1
        target = width
        need = occupied + pending_rows
        if need > width:
            # burst reaction: pending rows must not queue behind a full
            # lane when a wider lattice program can hold them now
            target = bucket_batch(min(need, hi))
        elif self._boundaries_since_resize >= self.patience:
            if (self.occ_ewma >= self.grow_at and rate > 0.0
                    and width * 2 <= hi):
                target = width * 2
            elif (self.occ_ewma <= self.shrink_at and pending_rows == 0
                    and occupied <= width // 2 and width > lo):
                target = width // 2
        target = max(lo, min(hi, bucket_batch(max(1, target))))
        target = max(target, bucket_batch(max(1, occupied)))
        if target != width:
            self._boundaries_since_resize = 0
            # re-seed the EWMA at the post-resize ratio so one resize
            # does not immediately argue for the next
            self.occ_ewma = occupied / max(1, target)
        return target




class Lane:
    """One resident batched denoise loop: a fixed-width row file through
    one compiled step program, driven by a dedicated thread."""

    _ids = iter(range(1, 1 << 62))

    def __init__(self, sched: "StepScheduler", key: tuple, pipe,
                 *, width: int, height: int, width_px: int,
                 steps_cap: int, sampler, control: Any = None,
                 width_bounds: tuple[int, int] | None = None,
                 reuse: bool = False) -> None:
        self._sched = sched
        self.key = key
        self.pipe = pipe
        self.width = int(width)
        self.height = int(height)
        self.width_px = int(width_px)
        self.steps_cap = int(steps_cap)
        self.sampler = sampler
        # ControlNet lanes are keyed by bundle: every row shares the
        # branch params; hint embeddings + scales stay per row
        self.ctrl = control
        # DeepCache lanes (ISSUE 12) compile the reuse branch in and
        # carry per-row deep-feature caches; keyed separately so plain
        # lanes keep the pre-reuse program
        self.reuse = bool(reuse)
        self.lane_id = next(Lane._ids)
        self._cond = threading.Condition()
        self._pending: collections.deque[_RowJob] = collections.deque()
        self._rows: list[_RowJob | None] = [None] * self.width
        self._stop = False
        self._retired = False
        # eviction→retire (ISSUE 9 satellite): the residency ledger
        # evicted this lane's model — retire the moment the row file
        # drains (idle lanes retire on the next driver wakeup) instead
        # of waiting out the idle grace, so HBM actually frees at
        # eviction
        self._retire_asap = False
        self.steps_executed = 0
        # adaptive capacity (ISSUE 7c): decisions land at step
        # boundaries only; bounds come from the scheduler's policy and
        # are re-clamped per decision by the OOM width limits
        self._adaptive = adaptive_enabled()
        lo, hi = width_bounds if width_bounds else (self.width, self.width)
        self._ctl = LaneWidthController(min_width=lo, max_width=hi)
        # host mirrors of the slow-changing per-row inputs (rebuilt on
        # device only when admission/retirement changes them)
        self._h_start = np.zeros(self.width, np.int32)
        self._h_idx = np.zeros(self.width, np.int32)
        self._h_sig = np.ones((self.width, self.steps_cap + 1), np.float32)
        self._h_ts = np.zeros((self.width, self.steps_cap), np.float32)
        self._h_guid = np.ones(self.width, np.float32)
        self._h_active = np.zeros(self.width, bool)
        self._h_mask_on = np.zeros(self.width, bool)
        self._h_cscale = np.ones(self.width, np.float32)
        # DeepCache row state (reuse lanes only; kept allocated either
        # way so the resize remap stays uniform): which ladder steps
        # each row's schedule wants reused, whether its cache is valid
        # (a full step ran since admission), and its skipped tally
        self._h_reuse = np.zeros((self.width, self.steps_cap), bool)
        self._h_cache_ok = np.zeros(self.width, bool)
        self._h_skipped = np.zeros(self.width, np.int64)
        self._dev = None  # device state dict, allocated at first admission
        self._mesh = None
        self._deferred_counts: list[dict] = []
        self._window: collections.deque = collections.deque()
        # step-boundary resume snapshots (ISSUE 6): only when the owning
        # worker attached its checkpoint spool to the slot
        self._spool = getattr(getattr(sched, "slot", None),
                              "_checkpoint_spool", None)
        self._ckpt_every = int(
            os.environ.get(ENV_CKPT_EVERY, "8") or 8)
        self._step_delay = float(
            os.environ.get(ENV_STEP_DELAY, "0") or 0)
        # swarmguard (ISSUE 10): the watchdog condemns a wedged lane
        # from the MONITOR thread; resume state for the re-admission
        # comes from this in-memory twin of the spool checkpoint (kept
        # even without a spool — condemnation must not depend on the
        # fleet heartbeat being on), and the chaos plan scripts
        # wedge/slow/NaN faults deterministically
        self._condemned = False
        self._ckpt_mem: dict[int, dict[str, Any]] = {}
        # chaos plan is re-read per dispatch; triggers count steps
        # relative to when the CURRENT plan first appeared on THIS
        # lane (a changed plan re-bases, so sequentially-armed seams
        # each get their own step window)
        self._chaos_base: int | None = None
        self._chaos_seen: _guard.LaneChaos | None = None
        # widths whose step program has completed a dispatch in THIS
        # lane: a dispatch at a new width (fresh lane, resize) may
        # COMPILE, so it runs under the watchdog's ceiling budget, not
        # the steady-state EWMA budget; a cache-flush heal rung bumps
        # the epoch and re-colds every lane (serving/guard.py)
        self._warm_widths: set[int] = set()
        self._flush_epoch = _guard.flush_epoch()
        # retired rows whose async decode is still in flight: the future
        # resolves only once the images are RESIDENT (same cross-thread
        # hazard as admission — the consumer must never read an array
        # another thread is still computing)
        self._handoff: collections.deque = collections.deque()
        self._thread = threading.Thread(
            target=self._drive, name=f"stepper-lane-{self.lane_id}",
            daemon=True)
        self._thread.start()

    # ---- submission side ----

    def try_enqueue(self, job: _RowJob) -> bool:
        with self._cond:
            if self._stop or self._retired:
                return False
            self._pending.append(job)
            self._cond.notify_all()
            return True

    def busy(self) -> bool:
        with self._cond:
            return (bool(self._pending) or bool(self._handoff)
                    or any(r is not None for r in self._rows))

    def occupancy(self) -> tuple[int, int]:
        with self._cond:
            return sum(r is not None for r in self._rows), self.width

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()

    def request_retire(self) -> None:
        """Retire as soon as the row file drains (resident rows finish,
        pending rows admitted and finished) — the eviction hook. Unlike
        :meth:`stop` this never fails resident rows: their params are
        still live on device until they release them."""
        with self._cond:
            self._retire_asap = True
            self._cond.notify_all()

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)

    def condemn(self, reason: str) -> None:
        """Declare this lane HUNG (swarmguard watchdog, ISSUE 10).

        Runs in the watchdog MONITOR thread while the driver is blocked
        inside the wedged dispatch, so it must never touch the device:
        it retires the lane (submitters open a fresh one), clears the
        row file, and fails every job's future with :class:`LaneHung`
        carrying the last in-memory step-boundary checkpoint — the
        executor re-admits those rows to a freshly built lane resuming
        at step k (node/executor.py::_stepper_collect). The wedged
        driver thread notices on return and exits without touching the
        row file it no longer owns."""
        with self._cond:
            if self._retired or self._condemned:
                return
            self._condemned = True
            # retire BEFORE failing over, like _fail_all: a racing
            # submit must see a dead lane and open a fresh one
            self._retired = True
            jobs = {id(j): j for j in self._rows if j is not None}
            pending = [j for j in self._pending]
            self._pending.clear()
            for s in range(self.width):
                self._rows[s] = None
            self._h_active[:] = False
            resumes = {jid: self._ckpt_mem.get(jid) for jid in jobs}
            handoff = list(self._handoff)
            self._handoff.clear()
            self._cond.notify_all()
        # outside the lane lock: _lane_done/_count take sched._lock,
        # which submitters hold while waiting on this lane's cond —
        # nesting them here would invert the order and deadlock
        self._sched._lane_done(self)
        rows_hung = 0
        for jid, job in jobs.items():
            rows_hung += job.n_rows
            if not job.future.done():
                job.future.set_exception(LaneHung(
                    f"lane {self.lane_id} condemned: {reason}",
                    resume=resumes.get(jid)))
        for job in pending:
            rows_hung += job.n_rows
            if not job.future.done():
                job.future.set_exception(LaneHung(
                    f"lane {self.lane_id} condemned with the job still "
                    f"pending: {reason}"))
        for job, _pending_imgs, _info in handoff:
            # the retired rows' decode was dispatched onto the wedged
            # device — waiting on it HERE would wedge the watchdog too;
            # the job re-runs instead (chip time lost, rows never are)
            if not job.future.done():
                job.future.set_exception(LaneHung(
                    f"lane {self.lane_id} condemned with the decode "
                    f"in flight: {reason}"))
        self._sched._count(lanes_condemned=1, rows_hung=rows_hung)
        device_guard = getattr(getattr(self._sched, "slot", None),
                               "_guard", None)
        if device_guard is not None:
            device_guard.note_hang(
                _guard._slot_devices(self._sched.slot), phase="lane")
            device_guard.note_condemned()
        log.error("lane %d CONDEMNED (%s): %d row(s) failed over with "
                  "resume state for a fresh lane", self.lane_id, reason,
                  rows_hung)

    # ---- driver ----

    def _drive(self) -> None:
        idle_s = float(os.environ.get(ENV_IDLE_S, "15") or 15)
        idle_since: float | None = None
        try:
            while True:
                # scheduler-side control signals, read OUTSIDE the lane
                # lock (sched._lock nests inside submitters holding it
                # while they wait on this lane's cond — taking it under
                # self._cond would invert the order and deadlock)
                width_limit = self._sched.width_limit_for(self.key)
                rate, hint_rows = self._sched.demand_signal()
                admit_cap = self._sched.admission_cap()
                with self._cond:
                    while True:
                        if self._stop:
                            raise LaneRetired("lane stopped")
                        self._resize_locked(width_limit, rate, hint_rows)
                        self._admit_locked(admit_cap)
                        if self._h_active.any():
                            idle_since = None
                            break
                        if self._retire_asap and not self._pending:
                            # eviction retire: the model left the HBM
                            # ledger and the row file is drained — free
                            # the device state NOW, not after the idle
                            # grace (handoffs were flushed blocking
                            # before the loop came back around)
                            self._retired = True
                            self._deferred_counts.append(
                                dict(lanes_evict_retired=1))
                            return
                        now = time.monotonic()
                        if idle_since is None:
                            idle_since = now
                        elif now - idle_since >= idle_s:
                            if self._pending:
                                # a job the lane can never admit (e.g.
                                # wider than a width-limited lane) must
                                # bounce, not leak an unresolved future
                                raise LaneRetired(
                                    "lane retired with unadmittable "
                                    "pending rows")
                            self._retired = True
                            return
                        # woken by try_enqueue/stop notify; the timeout
                        # only bounds the idle grace itself
                        self._cond.wait(
                            timeout=max(0.05, idle_s - (now - idle_since)))
                self._flush_counts()
                self._sched._maybe_fault(self)
                self._dispatch_step()
                self._retire_rows()
                self._maybe_checkpoint()
                self._flush_handoff(block=not self._h_active.any())
        except BaseException as exc:  # noqa: BLE001 — containment seam
            self._fail_all(exc)
        finally:
            with self._cond:
                self._retired = True
            self._flush_counts()
            self._sched._lane_done(self)

    def _flush_counts(self) -> None:
        while self._deferred_counts:
            self._sched._count(**self._deferred_counts.pop(0))

    def _alloc_dev(self, job: _RowJob) -> None:
        import jax.numpy as jnp

        from chiaswarm_tpu.pipelines.diffusion import _params_mesh

        # data parallelism: when the params live on a dp x tp mesh, lane
        # rows ride the 'data' axis — same GSPMD seeding the solo path
        # uses for its token inputs (pipelines/diffusion.py submit). A
        # solo job on a dp slot wastes (dp-1)/dp of the chips; a full
        # lane keeps every data row busy. OPT-IN for now: on the pinned
        # jax build the row-sharded step program diverges numerically
        # from its unsharded twin (same failure smell as the seq-parallel
        # divergence in ROADMAP) — enable once that is debugged.
        self._mesh = None
        if os.environ.get(ENV_SHARD_ROWS, "").strip().lower() in (
                "1", "true", "on", "yes"):
            mesh = _params_mesh(self.pipe.c.params)
            if mesh is not None and self.width % mesh.shape["data"] == 0:
                self._mesh = mesh
        lh, lw = self.pipe._latent_hw(self.height, self.width_px)
        ch = self.pipe.c.family.vae.latent_channels
        zero_row = jnp.zeros((self.width, lh, lw, ch), jnp.float32)
        keys = jnp.stack([job.keys0[0]] * self.width)
        placeholder = jnp.zeros((1,), jnp.float32)
        self._dev = {
            "x": zero_row,
            "keys": keys,
            "idx": jnp.zeros(self.width, jnp.int32),
            "old": zero_row,
            "ctx_u": jnp.zeros((self.width,) + job.ctx_u.shape[1:],
                               job.ctx_u.dtype),
            "ctx_c": jnp.zeros((self.width,) + job.ctx_c.shape[1:],
                               job.ctx_c.dtype),
            "pooled_u": (placeholder if job.pooled_u is None else
                         jnp.zeros((self.width,) + job.pooled_u.shape[1:],
                                   job.pooled_u.dtype)),
            "pooled_c": (placeholder if job.pooled_c is None else
                         jnp.zeros((self.width,) + job.pooled_c.shape[1:],
                                   job.pooled_c.dtype)),
            # image-mode row state (ISSUE 7b): clean source latents +
            # latent mask for inpaint rows; mask=1 everywhere keeps
            # non-inpaint rows untouched if the selection ever engages
            "known": zero_row,
            "mask": jnp.ones((self.width, lh, lw, 1), jnp.float32),
            # pre-embedded ControlNet hint rows (control lanes only; a
            # placeholder rides through the no-control step signature)
            "cond": (placeholder if job.cond0 is None else
                     jnp.zeros((self.width,) + job.cond0.shape[1:],
                               job.cond0.dtype)),
        }
        if self.reuse:
            # per-row cached deep activations (uncond/cond halves) —
            # the DeepCache carry the step program refreshes on full
            # steps and replays on reuse steps
            c1 = self.pipe.c.family.unet.block_out_channels[1]
            cache_row = jnp.zeros((self.width, lh, lw, c1),
                                  self.pipe.c.unet.dtype)
            self._dev["cache_u"] = cache_row
            self._dev["cache_c"] = cache_row
        self._sync_tables()

    def _place_rows(self) -> None:
        """Pin every lane-width array onto the mesh's data axis (no-op on
        single-chip slots). Re-applied after admission scatters, whose
        outputs may lose the row sharding."""
        if self._mesh is None:
            return
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        for key, arr in self._dev.items():
            if getattr(arr, "ndim", 0) < 1 or arr.shape[0] != self.width:
                continue  # non-XL pooled placeholders
            spec = P(*(("data",) + (None,) * (arr.ndim - 1)))
            self._dev[key] = jax.device_put(
                arr, NamedSharding(self._mesh, spec))

    def _sync_tables(self) -> None:
        """Rebuild the device copies of the host-mirrored per-row inputs.

        MUST transfer COPIES: jax dispatch is async, and handing it a
        live numpy buffer that admission/retirement later mutates in
        place lets the device read the FUTURE value — the step then e.g.
        sees a row as inactive and silently skips it (observed: a
        one-step job decoding its un-stepped init latents)."""
        import jax.numpy as jnp

        dev = self._dev
        dev["start"] = jnp.asarray(self._h_start.copy())
        dev["sig"] = jnp.asarray(self._h_sig.copy())
        dev["ts"] = jnp.asarray(self._h_ts.copy())
        dev["guid"] = jnp.asarray(self._h_guid.copy())
        dev["active"] = jnp.asarray(self._h_active.copy())
        dev["mask_on"] = jnp.asarray(self._h_mask_on.copy())
        dev["cscale"] = jnp.asarray(self._h_cscale.copy())

    def _admit_locked(self, cap: int | None = None) -> None:
        """Splice pending jobs into free row slots — the step boundary is
        wherever the driver is between dispatches. ``cap`` is the
        brownout rung (node/overload.py via the scheduler): at most that
        many rows splice in per boundary, so resident rows finish ahead
        of fresh admissions under sustained overload. The first pending
        job always admits when slots allow — the cap throttles breadth,
        it must never wedge a job wider than itself."""
        import jax.numpy as jnp

        admitted_rows = 0
        free = [s for s in range(self.width) if self._rows[s] is None]
        while self._pending and self._pending[0].n_rows <= len(free):
            if (cap is not None and admitted_rows > 0
                    and admitted_rows + self._pending[0].n_rows > cap):
                break
            job = self._pending.popleft()
            if job.future.cancelled():
                continue
            # cross-thread handoff sync: the job's arrays were dispatched
            # from the SUBMITTING thread (encode/init overlap earlier lane
            # steps); admit the row only once they are resident. Usually a
            # no-op by now — and this container's jax build corrupts
            # results when a program consumes another thread's still-
            # compiling outputs, so the barrier is correctness, not style.
            for arr in (job.x0, job.keys0, job.ctx_u, job.ctx_c,
                        job.pooled_u, job.pooled_c, job.old0,
                        job.known0, job.mask0, job.cond0,
                        job.cache_u0, job.cache_c0):
                if arr is not None:
                    arr.block_until_ready()
            slots, free = free[:job.n_rows], free[job.n_rows:]
            admitted_rows += job.n_rows
            if self._dev is None:
                self._alloc_dev(job)
            mid_flight = bool(self._h_active.any())
            sel = np.asarray(slots)
            dev = self._dev
            dev["x"] = dev["x"].at[sel].set(job.x0)
            dev["keys"] = dev["keys"].at[sel].set(job.keys0)
            # a resumed row restores its multistep history and rejoins
            # at step k; a fresh row starts clean at its workload's
            # start index — both through the one admission path (the
            # step program never knows the difference)
            dev["old"] = dev["old"].at[sel].set(
                jnp.zeros_like(job.x0) if job.old0 is None else job.old0)
            dev["idx"] = dev["idx"].at[sel].set(job.idx0)
            dev["ctx_u"] = dev["ctx_u"].at[sel].set(job.ctx_u)
            dev["ctx_c"] = dev["ctx_c"].at[sel].set(job.ctx_c)
            if job.pooled_u is not None:
                dev["pooled_u"] = dev["pooled_u"].at[sel].set(job.pooled_u)
                dev["pooled_c"] = dev["pooled_c"].at[sel].set(job.pooled_c)
            dev["known"] = dev["known"].at[sel].set(
                jnp.zeros_like(job.x0) if job.known0 is None
                else job.known0)
            dev["mask"] = dev["mask"].at[sel].set(
                jnp.ones_like(dev["mask"][sel]) if job.mask0 is None
                else job.mask0)
            if job.cond0 is not None:
                dev["cond"] = dev["cond"].at[sel].set(job.cond0)
            if self.reuse:
                # a fresh row starts cache-invalid (its first step runs
                # the full network); a resumed row restores its cache +
                # validity + skipped tally exactly as checkpointed
                dev["cache_u"] = dev["cache_u"].at[sel].set(
                    0.0 if job.cache_u0 is None else job.cache_u0)
                dev["cache_c"] = dev["cache_c"].at[sel].set(
                    0.0 if job.cache_c0 is None else job.cache_c0)
                self._h_reuse[sel, :] = False
                for step_j in job.reuse_schedule:
                    if 0 <= int(step_j) < self.steps_cap:
                        self._h_reuse[sel, int(step_j)] = True
                self._h_cache_ok[sel] = bool(job.cache_ok0)
                self._h_skipped[sel] = int(job.skipped0)
            self._h_idx[sel] = job.idx0
            self._h_start[sel] = job.start_step
            self._h_mask_on[sel] = job.mask0 is not None
            self._h_cscale[sel] = job.cscale
            self._h_sig[sel, :] = 0.0
            self._h_sig[sel, : job.steps + 1] = job.sigmas
            self._h_ts[sel, :] = 0.0
            self._h_ts[sel, : job.steps] = job.timesteps
            self._h_guid[sel] = job.guidance
            self._h_active[sel] = True
            self._sync_tables()
            self._place_rows()
            for s in slots:
                self._rows[s] = job
            job.slots = slots
            job.admitted_at_step = self.steps_executed
            job.admitted_t = time.perf_counter()
            # workload-labeled admission breadth (metric-local lock
            # only — safe under self._cond)
            _LANE_ADMISSIONS.inc(job.n_rows, workload=job.workload)
            # deferred: _admit_locked runs under self._cond while
            # submitters hold sched._lock and wait on self._cond —
            # taking sched._lock (inside _count) HERE would deadlock
            self._deferred_counts.append(dict(
                rows_admitted=job.n_rows,
                rows_admitted_midflight=(job.n_rows if mid_flight
                                         else 0),
                rows_resumed=(job.n_rows if job.resume_step > 0 else 0),
                **{f"rows_admitted_{job.workload}": job.n_rows}))
            if job.resume_step > 0:
                _RESUME_STEP.observe(job.resume_step)
                log.info("job %s resumed at step %d/%d (%d row(s))",
                         job.job_id, job.resume_step, job.steps,
                         job.n_rows)

    def _resize_locked(self, width_limit: int | None, rate: float,
                       hint_rows: int) -> None:
        """Adaptive capacity, applied ONLY here — between dispatches, so
        a step in flight never sees its row file change under it. Runs
        under ``self._cond`` (mutates ``_rows``/host mirrors submitters
        read); the scheduler-side signals were prefetched lock-free by
        the driver. Sharded-row lanes skip (their width must divide the
        mesh data axis; ROADMAP item 2)."""
        if not self._adaptive or self._mesh is not None:
            return
        occupied = sum(r is not None for r in self._rows)
        pending = sum(j.n_rows for j in self._pending
                      if not j.future.cancelled())
        target = self._ctl.decide(self.width, occupied,
                                  pending + max(0, hint_rows), rate,
                                  max_width=width_limit)
        if target == self.width:
            return
        self._apply_resize_locked(target)

    def _apply_resize_locked(self, new_width: int) -> None:
        """Rebuild the row file at ``new_width``: resident rows compact
        onto the first slots (their device state gathered across), host
        mirrors re-seed, and the next dispatch fetches the lattice
        program for the new batch — a cache hit after the first resize
        to any given width."""
        import jax.numpy as jnp

        old_width, self.width = self.width, int(new_width)
        occupied = [(s, self._rows[s]) for s in range(old_width)
                    if self._rows[s] is not None]
        grow = self.width > old_width
        log.info("lane %d %s %d -> %d rows (%d resident)", self.lane_id,
                 "grows" if grow else "shrinks", old_width, self.width,
                 len(occupied))
        _LANE_RESIZES.inc(direction="grow" if grow else "shrink")
        self._deferred_counts.append(dict(lane_resizes=1))
        old_h = (self._h_start, self._h_idx, self._h_sig, self._h_ts,
                 self._h_guid, self._h_active, self._h_mask_on,
                 self._h_cscale, self._h_reuse, self._h_cache_ok,
                 self._h_skipped)
        self._h_start = np.zeros(self.width, np.int32)
        self._h_idx = np.zeros(self.width, np.int32)
        self._h_sig = np.ones((self.width, self.steps_cap + 1), np.float32)
        self._h_ts = np.zeros((self.width, self.steps_cap), np.float32)
        self._h_guid = np.ones(self.width, np.float32)
        self._h_active = np.zeros(self.width, bool)
        self._h_mask_on = np.zeros(self.width, bool)
        self._h_cscale = np.ones(self.width, np.float32)
        self._h_reuse = np.zeros((self.width, self.steps_cap), bool)
        self._h_cache_ok = np.zeros(self.width, bool)
        self._h_skipped = np.zeros(self.width, np.int64)
        new_mirrors = (self._h_start, self._h_idx, self._h_sig, self._h_ts,
                       self._h_guid, self._h_active, self._h_mask_on,
                       self._h_cscale, self._h_reuse, self._h_cache_ok,
                       self._h_skipped)
        for new_s, (old_s, _) in enumerate(occupied):
            for old_m, new_m in zip(old_h, new_mirrors):
                new_m[new_s] = old_m[old_s]
        self._rows = [None] * self.width
        for new_s, (_, job) in enumerate(occupied):
            self._rows[new_s] = job
        for job in {id(j): j for _, j in occupied}.values():
            job.slots = [s for s, (_, j) in enumerate(occupied) if j is job]
        if self._dev is not None:
            sel = jnp.asarray([old_s for old_s, _ in occupied]
                              or [0])[: len(occupied) or None]

            def remap(name, arr):
                # non-XL pooled / no-control placeholders are exactly
                # the 1-D (1,) arrays under these keys: pass them
                # through BY NAME — shape alone misreads them as row
                # state when old_width == 1, and padding a placeholder
                # would change a traced input shape (a recompile no
                # fresh lane ever pays)
                if name in ("pooled_u", "pooled_c", "cond") and \
                        getattr(arr, "ndim", 0) == 1:
                    return arr
                if occupied:
                    taken = jnp.take(arr, sel, axis=0)
                else:
                    taken = arr[:0]
                pad_n = self.width - int(taken.shape[0])
                if pad_n <= 0:
                    return taken
                pad = jnp.zeros((pad_n,) + tuple(arr.shape[1:]), arr.dtype)
                return jnp.concatenate([taken, pad], axis=0)

            self._dev = {k: remap(k, v) for k, v in self._dev.items()}
            self._sync_tables()
            self._place_rows()

    def _dispatch_step(self) -> None:
        dev = self._dev
        fn = self.pipe.stepper_step_fn(
            batch=self.width, height=self.height, width=self.width_px,
            steps_cap=self.steps_cap, sampler=self.sampler,
            has_control=self.ctrl is not None, reuse=self.reuse)
        import jax.numpy as jnp

        # DeepCache step decision (ISSUE 12), made HOST-side from the
        # mirrors this driver owns: skip the deep blocks only when EVERY
        # active row's schedule wants reuse at its current step AND
        # holds a valid cache. The flag rides as a traced scalar, so
        # the decision never recompiles; misaligned lane mates degrade
        # the step to a full eval — more compute, never wrong math.
        reuse_now = False
        if self.reuse and self._h_active.any():
            step_of = np.minimum(self._h_idx, self.steps_cap - 1)
            wants = self._h_reuse[np.arange(self.width), step_of]
            reuse_now = bool(np.all(
                ~self._h_active | (wants & self._h_cache_ok)))

        ctrl_params = (self.ctrl.params if self.ctrl is not None
                       else {"zero": jnp.zeros((1,), jnp.float32)})
        this_step = self.steps_executed + 1
        # chaos plan (swarmguard seams): env re-read each dispatch, and
        # trigger steps count from the dispatch that first SAW the plan
        # — deterministic on fresh and warm (reused) lanes alike
        chaos = _guard.LaneChaos.from_env()
        if not chaos.armed:
            self._chaos_base = self._chaos_seen = None
        elif chaos != self._chaos_seen:
            self._chaos_base = self.steps_executed
            self._chaos_seen = chaos
        chaos_step = (this_step - self._chaos_base
                      if self._chaos_base is not None else 0)
        # swarmguard (ISSUE 10): arm the hang watchdog around the whole
        # dispatch INCLUDING the depth-2 window drain — that drain is
        # where a wedged device actually blocks this thread. Budget is
        # k x the scheduler's step EWMA, EXCEPT when this dispatch may
        # compile — the lane's first dispatch at this width, or the
        # first after a cache-flush heal rung — which runs under the
        # generous ceiling instead: a compile is not a gray failure,
        # and condemning one would feed the very ladder that caused it.
        # If the watchdog fires while we are away, the lane was
        # condemned from the MONITOR thread (rows already failed over
        # with their resume state) — this thread just exits without
        # touching the dead row file.
        epoch = _guard.flush_epoch()
        if epoch != self._flush_epoch:
            self._flush_epoch = epoch
            self._warm_widths.clear()
        budget = self._sched.hang_budget()
        if budget is not None and self.width not in self._warm_widths:
            budget = _guard.hang_budget_s(0.0)  # the cold ceiling
        ticket = None
        if budget is not None:
            ticket = _guard.WATCHDOG.arm(
                budget, lambda: self.condemn(
                    f"step {this_step} exceeded its {budget:.1f}s hang "
                    f"budget"),
                tag=f"lane-{self.lane_id}-step-{this_step}")
        t0 = time.perf_counter()
        fired = False
        try:
            with annotate("swarm.lane.step"):
                base_args = (
                    self.pipe.c.params,
                    dev["ctx_u"], dev["ctx_c"], dev["pooled_u"],
                    dev["pooled_c"],
                    dev["x"], dev["keys"], dev["idx"],
                    dev["start"], dev["sig"], dev["ts"], dev["guid"],
                    dev["old"], dev["active"],
                    dev["known"], dev["mask"], dev["mask_on"],
                    ctrl_params, dev["cond"], dev["cscale"],
                )
                if self.reuse:
                    (dev["x"], dev["keys"], dev["idx"], dev["old"],
                     dev["cache_u"], dev["cache_c"]) = fn(
                        *base_args, dev["cache_u"], dev["cache_c"],
                        jnp.asarray(reuse_now))
                else:
                    dev["x"], dev["keys"], dev["idx"], dev["old"] = fn(
                        *base_args)
            wedge_s = chaos.wedge_at(chaos_step)
            if wedge_s > 0:  # scripted wedged-compiled-call stand-in
                log.warning("chaos: wedging lane %d step %d for %.1fs",
                            self.lane_id, this_step, wedge_s)
                time.sleep(wedge_s)
            # throttle: keep at most two dispatched steps in flight
            # (the depth-2 philosophy of core/chip_pool.py) so the
            # async queue cannot run away from the device — and
            # execution errors surface here, inside the containment
            # try of the driver loop
            self._window.append(dev["x"])
            if len(self._window) > 2:
                self._window.popleft().block_until_ready()
        finally:
            if ticket is not None:
                fired = _guard.WATCHDOG.disarm(ticket)
        if fired:
            # the watchdog declared this step hung. condemn() usually
            # already ran in the monitor thread — but the monitor marks
            # ``fired`` BEFORE invoking the callback, so a dispatch
            # returning in that window could reach _fail_all first and
            # strand the rows with a resume-less LaneRetired. Condemn
            # from HERE too (idempotent): whichever thread wins, every
            # job fails over as LaneHung with its checkpoint, and the
            # hang reaches the device-health ledger exactly once.
            self.condemn(
                f"step {this_step} exceeded its hang budget")
            raise LaneRetired(
                f"lane {self.lane_id} condemned by the hang watchdog "
                f"at step {this_step}")
        self._warm_widths.add(self.width)  # this width's program ran
        nan_row = chaos.nan_wants(chaos_step)
        if nan_row is not None:  # scripted trajectory poisoning —
            # consume the one-shot only when the target row is ACTIVE
            # with at least one more step boundary before retirement,
            # so the poison is deterministically caught by the
            # checkpoint-boundary finite-check (a seam spent on a
            # padding row or a retiring row was the fleet-gate flake)
            row = min(max(0, int(nan_row)), self.width - 1)
            victim = self._rows[row]
            if (victim is not None and self._h_active[row]
                    and int(self._h_idx[row]) + 1 < victim.steps
                    and _guard.consume_chaos("nan")):
                log.warning("chaos: poisoning lane %d row %d with NaN "
                            "after step %d", self.lane_id, row,
                            this_step)
                dev["x"] = dev["x"].at[row].set(jnp.nan)
        active = int(self._h_active.sum())
        if self.reuse and reuse_now:
            # this dispatch replayed the deep cache: every active row
            # skipped its deep blocks — the step-collapse tally the
            # per-image eval accounting and /metrics families read
            self._h_skipped[self._h_active] += 1
            _UNET_EVALS.inc(active, mode="reuse")
            _STEPS_SKIPPED.inc(active)
            self._sched._count(steps_reused=1, row_steps_reused=active)
        else:
            if self.reuse:
                # a full step refreshed every active row's cache
                self._h_cache_ok[self._h_active] = True
            _UNET_EVALS.inc(active, mode="full")
        self._h_idx[self._h_active] += 1
        self.steps_executed += 1
        self._sched._count(steps_executed=1, row_steps_active=active,
                           row_steps_padded=self.width - active)
        _LANE_OCCUPANCY.observe(active / self.width, width=str(self.width))
        if self._step_delay > 0:  # chaos seam: stretch lane wall time
            time.sleep(self._step_delay)
        step_s = time.perf_counter() - t0
        slow_extra = chaos.slow_extra_s(step_s)
        if slow_extra > 0:  # chaos: the sick-but-alive device
            time.sleep(slow_extra)
            step_s += slow_extra
        _STEP_SECONDS.observe(step_s)
        # the overload estimator's lane-path signal (node/overload.py):
        # job steps x this EWMA floors the predicted service time —
        # and the guard's slow-step health signal AND hang budget read
        # the same EWMA. The lane's FIRST dispatch compiles (seconds to
        # minutes); feeding it would poison the EWMA and inflate the
        # watchdog's hang budget k-fold for many steps — a real wedge
        # would then sail under the budget. Skip it: the watchdog
        # already covers the cold window with the ceiling budget.
        ewma_before = self._sched.step_ewma()
        if self.steps_executed > 1:
            self._sched.note_step_seconds(step_s)
        device_guard = getattr(getattr(self._sched, "slot", None),
                               "_guard", None)
        if device_guard is not None:
            devices = _guard._slot_devices(self._sched.slot)
            if ewma_before > 0 and step_s > _guard.slow_factor() * \
                    ewma_before:
                self._sched._count(steps_slow=1)
                device_guard.note_slow_step(devices)
            else:
                device_guard.note_ok(devices)

    def _retire_rows(self) -> None:
        """Retire finished rows (decode dispatched async — it overlaps the
        next steps) and expire rows past their deadline."""
        from chiaswarm_tpu.core.compile_cache import bucket_batch
        from chiaswarm_tpu.pipelines.diffusion import PendingImages

        import jax.numpy as jnp

        now = time.monotonic()
        done: list[_RowJob] = []
        expired: list[_RowJob] = []
        for s, job in enumerate(self._rows):
            if job is None or not self._h_active[s]:
                continue
            if self._h_idx[s] >= job.steps and job not in done:
                done.append(job)
            elif now > job.deadline and job not in expired \
                    and self._h_idx[s] < job.steps:
                expired.append(job)
        changed = False
        for job in done:
            sel = np.asarray(job.slots)
            rows_x = jnp.take(self._dev["x"], jnp.asarray(sel), axis=0)
            bucket = bucket_batch(job.n_rows)
            if job.n_rows < bucket:
                rows_x = jnp.concatenate(
                    [rows_x, jnp.repeat(rows_x[-1:],
                                        bucket - job.n_rows, axis=0)])
            decode = self.pipe.stepper_decode_fn(
                batch=bucket, height=self.height, width=self.width_px)
            with annotate("swarm.lane.decode"):
                images = decode(self.pipe.c.params, rows_x)
            pending = PendingImages(
                device_images=images,
                compiled_hw=(self.height, self.width_px),
                requested_hw=(self.height, self.width_px),
                requested_batch=job.n_rows)
            info = {
                "lane": self.lane_id,
                "lane_width": self.width,
                "admitted_at_step": job.admitted_at_step,
                "steps_executed": self.steps_executed,
                # the fleet-invariant proof point: >0 means this job was
                # redelivered and resumed mid-trajectory, not restarted
                "resume_step": job.resume_step,
                # time the rows waited for a free slot before their
                # first step (flight-record lane_wait attribution)
                "splice_wait_s": round(
                    max(0.0, job.admitted_t - job.submitted_t), 6)
                if job.admitted_t else 0.0,
            }
            # per-image UNet-eval accounting (ISSUE 12): full evals this
            # row actually paid over its WHOLE trajectory (the skipped
            # tally survives resume), observed once per row
            skipped = (int(self._h_skipped[job.slots[0]])
                       if self.reuse and job.slots else 0)
            evals = (job.steps - job.start_step) - skipped
            for _ in range(job.n_rows):
                _EVALS_PER_IMAGE.observe(evals)
            if self.reuse:
                info["unet_evals"] = evals
                info["steps_skipped"] = skipped
            # handoff BEFORE releasing the slots: busy() reports
            # "_pending or _handoff or _rows", so releasing first opens
            # a window where a draining caller sees an idle lane while
            # this job's future is still unresolved — drain() returning
            # True with the future pending was the at-seed stepper
            # flake on loaded single-core hosts
            self._handoff.append((job, pending, info))
            self._release_rows(job)
            changed = True
            self._sched._count(rows_completed=job.n_rows)
        for job in expired:
            # ordering discipline: the caller wakes on set_exception,
            # so everything it may read must land first (the expired
            # count) and the slots must stop counting toward busy()
            # only after the future resolves (the drain() gap above)
            self._sched._count(rows_expired=job.n_rows)
            if not job.future.done():
                job.future.set_exception(LaneDeadline(
                    f"row(s) of job {job.job_id} exceeded the in-lane "
                    f"deadline"))
            self._release_rows(job)
            changed = True
        if changed:
            with self._cond:
                self._cond.notify_all()

    def _maybe_checkpoint(self) -> None:
        """Snapshot every resident job's per-row state at this step
        boundary (every ``_ckpt_every`` steps) — to the worker's
        checkpoint spool when one is attached (fleet heartbeats, ISSUE
        6) and ALWAYS to the in-memory twin the guard's condemnation
        path resumes from (ISSUE 10). The snapshot is exact resume
        state: latents, carry PRNG keys, and multistep history at step
        k — restored rows continue on the bit-identical solo
        trajectory.

        The guard's per-row finite-check rides the SAME device->host
        transfer: a job whose latents went non-finite is poisoned — it
        retires with :class:`InvalidOutput` (no checkpoint, no decode,
        no upload) while its lane peers keep stepping. Runs in the
        driver thread, so the reads only stall THIS lane's pipeline (by
        one window drain), never the submitters."""
        validate = _guard.validation_enabled()
        want_ckpt = self._spool is not None or _guard.watchdog_enabled()
        # swarmlens (ISSUE 11): numerics probing rides the SAME
        # checkpoint-boundary device->host transfer — enabling the
        # lane_row probe forces the transfer even with durability and
        # the watchdog off (set CHIASWARM_STEPPER_CKPT_EVERY=1 for
        # per-step resolution when bisecting)
        numerics_on = _numerics.enabled_for("lane_row")
        if (self._ckpt_every <= 0 or self._dev is None
                or not (validate or want_ckpt or numerics_on)):
            return
        if self.steps_executed % self._ckpt_every:
            return
        jobs = {id(j): j for j in self._rows if j is not None}
        if not jobs:
            return
        t0 = time.perf_counter()
        # one transfer for the whole lane, sliced per job below
        x = np.asarray(self._dev["x"])
        keys = old = cache_u = cache_c = None
        written = 0
        poisoned: list[_RowJob] = []
        for job in jobs.values():
            sel = list(job.slots)
            if numerics_on:
                # per-row lane-state summaries, recorded BEFORE the
                # finite screen so a poisoned row's NaN step is on the
                # record; slot index doubles as the shard id, so a
                # sharded lane aligns row-for-row with its unsharded
                # twin in the bisect streams
                for s in sel:
                    _numerics.record_host(
                        "lane_row", x[s], step=int(self._h_idx[s]),
                        shard=s, note=str(job.job_id))
            if validate and not np.isfinite(x[sel]).all():
                poisoned.append(job)
                continue
            step = int(self._h_idx[sel[0]])
            if step <= job.start_step or step >= job.steps:
                continue  # nothing to resume yet / rows about to retire
            if not want_ckpt:
                continue
            if keys is None:
                keys = np.asarray(self._dev["keys"])
                old = np.asarray(self._dev["old"])
            state = {
                "version": 1, "kind": "lane",
                "step": step, "steps": int(job.steps),
                "rows": int(job.n_rows),
                "height": int(self.height), "width": int(self.width_px),
                "guidance": float(job.guidance),
                # workload identity (ISSUE 7b): a resumed img2img row
                # must rejoin the SAME truncated ladder; mask/known/hint
                # state re-derives from the redelivered job's own inputs
                "workload": str(job.workload),
                "start": int(job.start_step),
                "x": pack_array(x[sel]),
                "keys": pack_array(keys[sel]),
                "old": pack_array(old[sel]),
            }
            if self.reuse:
                # DeepCache resume state (ISSUE 12): the deep-feature
                # caches + validity + skipped tally ride the snapshot,
                # so a redelivered row replays the EXACT remaining
                # reuse decisions — bit-identical to the uninterrupted
                # run. The schedule itself is recorded for validation:
                # a tampered schedule must restart clean, never finish
                # a different trajectory under this job's identity.
                if cache_u is None:
                    cache_u = np.asarray(self._dev["cache_u"])
                    cache_c = np.asarray(self._dev["cache_c"])
                state.update({
                    "reuse_schedule": [int(j) for j in
                                       job.reuse_schedule],
                    "cache_u": pack_array(cache_u[sel]),
                    "cache_c": pack_array(cache_c[sel]),
                    "cache_ok": bool(self._h_cache_ok[sel[0]]),
                    "skipped": int(self._h_skipped[sel[0]]),
                })
            self._ckpt_mem[id(job)] = state
            if self._spool is None:
                continue
            try:
                self._spool.save(job.job_id, state)
                written += 1
            except OSError as exc:  # durability never fails the lane
                log.warning("checkpoint for job %s failed: %s",
                            job.job_id, exc)
        for job in poisoned:
            self._poison_rows(job)
        if written:
            self._sched._count(checkpoints_written=written)
            _CKPT_SECONDS.observe(time.perf_counter() - t0)

    def _poison_rows(self, job: _RowJob) -> None:
        """Retire ONE job's rows as numerically poisoned (swarmguard,
        ISSUE 10): non-finite latents never decode, never upload, and
        never take the lane's other jobs down — the job's future fails
        with :class:`InvalidOutput`, which the executor envelopes as a
        non-fatal ``invalid_output`` (REDISPATCH_KINDS member: a
        lease-aware hive re-runs it on a different node)."""
        step = int(self._h_idx[job.slots[0]]) if job.slots else 0
        self._release_rows(job)
        self._sched._count(rows_invalid=job.n_rows)
        if not job.future.done():
            job.future.set_exception(InvalidOutput(
                f"job {job.job_id}: non-finite latents at step {step} — "
                f"row(s) retired without decoding"))
        log.error("lane %d: job %s poisoned (non-finite latents at step "
                  "%d); %d row(s) retired invalid_output, peers keep "
                  "stepping", self.lane_id, job.job_id, step, job.n_rows)
        with self._cond:
            self._cond.notify_all()

    def _flush_handoff(self, block: bool) -> None:
        """Resolve retired rows whose decoded images are resident. With
        ``block=False`` (rows still stepping) in-flight decodes stay
        queued — the overlap — and resolve at a later boundary; with
        ``block=True`` (lane idle) the driver waits them out."""
        while self._handoff:
            job, pending, info = self._handoff[0]
            images = pending.device_images
            ready = True
            if not block:
                is_ready = getattr(images, "is_ready", None)
                ready = bool(is_ready()) if callable(is_ready) else False
            if not ready:
                return
            images.block_until_ready()
            self._handoff.popleft()
            if not job.future.cancelled():
                job.future.set_result((pending, info))

    def _release_rows(self, job: _RowJob) -> None:
        for s in job.slots:
            self._rows[s] = None
            self._h_active[s] = False
        self._ckpt_mem.pop(id(job), None)
        if self._dev is not None:
            self._sync_tables()

    def _fail_all(self, exc: BaseException) -> None:
        err = exc if isinstance(exc, Exception) else LaneRetired(str(exc))
        # retired rows with in-flight decodes: their chip time is already
        # spent — deliver if the decode survives, fail otherwise
        while self._handoff:
            job, pending, info = self._handoff.popleft()
            try:
                pending.device_images.block_until_ready()
                if not job.future.done():
                    job.future.set_result((pending, info))
            except Exception:
                if not job.future.done():
                    job.future.set_exception(err)
        with self._cond:
            # retire BEFORE draining: a submit racing this failure must
            # see a dead lane (and open a fresh one), not append a job
            # whose future nobody will ever resolve
            self._retired = True
            jobs = {id(j): j for j in self._rows if j is not None}
            jobs.update({id(j): j for j in self._pending})
            self._pending.clear()
            for s in range(self.width):
                self._rows[s] = None
            self._h_active[:] = False
        failed_rows = 0
        for job in jobs.values():
            failed_rows += job.n_rows
            if not job.future.done():
                job.future.set_exception(err)
        if jobs:
            # remember (key, width) BEFORE collectors wake: note_oom may
            # run after _lane_done has already deregistered this lane
            self._sched._note_lane_failure(self.key, self.width)
            self._sched._count(rows_failed=failed_rows, lanes_failed=1)
            log.warning("lane %d failed (%s): %d row(s) bounced to the "
                        "per-job path", self.lane_id, err, failed_rows)
        self._dev = None
        self._window.clear()


class StepScheduler:
    """Owns the lanes of one slot; thread-safe submit/stats/drain."""

    def __init__(self, slot: Any = None) -> None:
        self.slot = slot
        self._lock = threading.Lock()
        self._lanes: dict[tuple, Lane] = {}
        self._width_limits: dict[tuple, int] = {}
        self._stats = collections.Counter()
        self._fault: list[tuple[int, BaseException]] = []
        self._total_steps = 0
        self._last_oom_incident = -1
        # (key -> width) of recently failed lanes: note_oom must still
        # find the lane that just died even after _lane_done removed it
        self._failed_lane_hints: dict[tuple, int] = {}
        # adaptive-width demand signal (ISSUE 7c): submissions feed the
        # rows/sec EWMA; the worker's poll loop leaves a short-lived
        # rows hint so lanes can grow BEFORE the formatted submissions
        # land — the poll-loop / step-boundary merge
        self._arrivals = _ArrivalEwma()
        self._poll_hint_rows = 0
        self._poll_hint_t = float("-inf")
        # overload control (ISSUE 9): the per-step lane-admission cap
        # the worker pushes while brownout holds, and the step-latency
        # EWMA the admission estimator floors its predictions with
        self._admission_cap: int | None = None
        self._step_ewma = 0.0
        _register_for_exit(self)

    # ---- policy ----

    def lane_width(self, height: int, width: int) -> int:
        """Pinned width (``CHIASWARM_STEPPER_LANE_WIDTH``) or the static
        slot-saturation heuristic: data width x the measured per-chip
        profitable batch, pow2-bucketed. With the adaptive controller on
        this is only the anchor for :meth:`width_bounds`."""
        env = os.environ.get(ENV_LANE_WIDTH, "").strip()
        if env:
            width_rows = int(env)
        else:
            from chiaswarm_tpu.core.compile_cache import bucket_batch
            from chiaswarm_tpu.node.executor import single_chip_rows

            data_width = max(1, int(getattr(self.slot, "data_width", 1)))
            per_device = single_chip_rows({"height": height, "width": width})
            width_rows = bucket_batch(max(2, data_width * per_device))
        return max(1, width_rows)

    def width_bounds(self, height: int, width: int) -> tuple[int, int]:
        """(min, max) lane width for the adaptive controller. Defaults:
        1 to 4x the saturation heuristic (pow2, capped at the batch
        lattice maximum) — wide enough that the closed loop, not a
        static guess, decides how much padding a traffic mix pays.
        Pinned width collapses the range to a point."""
        from chiaswarm_tpu.core.compile_cache import bucket_batch

        if not adaptive_enabled():
            pinned = self.lane_width(height, width)
            return pinned, pinned
        env_min = os.environ.get(ENV_MIN_WIDTH, "").strip()
        env_max = os.environ.get(ENV_MAX_WIDTH, "").strip()
        lo = max(1, int(env_min)) if env_min else 1
        if env_max:
            hi = bucket_batch(min(128, max(1, int(env_max))))
        else:
            hi = bucket_batch(min(128, 4 * self.lane_width(height, width)))
        return min(lo, hi), max(lo, hi)

    def initial_width(self, rows: int, height: int, width: int) -> int:
        """A fresh lane opens just big enough for its first job (plus
        headroom for one more) and lets the controller follow demand
        from there — idle-start lanes must not pay a saturation-sized
        padding bill while traffic ramps."""
        from chiaswarm_tpu.core.compile_cache import bucket_batch

        lo, hi = self.width_bounds(height, width)
        if not adaptive_enabled():
            return hi
        return max(lo, min(hi, bucket_batch(max(2, int(rows)))))

    def row_deadline_s(self) -> float:
        return float(os.environ.get(ENV_ROW_DEADLINE, "600") or 600)

    # ---- demand signal (adaptive width, ISSUE 7c) ----

    def _note_arrival(self, rows: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._arrivals.note(int(rows), now)
            # the hinted rows have (partly) landed as real submissions:
            # burn the hint down so lanes never count the same rows as
            # both pending AND hinted (which would over-grow the width)
            self._poll_hint_rows = max(0, self._poll_hint_rows - int(rows))
            rate = self._arrivals.rate(now)
        _ARRIVAL_RATE.set(rate)

    def note_poll(self, jobs: int, now: float | None = None) -> None:
        """Worker poll hook: a poll just returned ``jobs`` jobs, so that
        many rows are about to be formatted and submitted. Lanes read
        the hint at their next step boundary and can grow BEFORE the
        submissions land — the queue never waits out a full lane."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._poll_hint_rows = max(0, int(jobs))
            self._poll_hint_t = now

    def demand_signal(self, now: float | None = None) -> tuple[float, int]:
        """(arrival-rate EWMA rows/sec, fresh poll-hint rows) — read by
        lane drivers lock-free relative to the lanes (only the scheduler
        lock is taken, never a lane's)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rate = self._arrivals.rate(now)
            hint = (self._poll_hint_rows
                    if now - self._poll_hint_t <= 2.0 else 0)
        _ARRIVAL_RATE.set(rate)
        return rate, hint

    def width_limit_for(self, key: tuple) -> int | None:
        """The OOM-halving width cap for ``key`` (note_oom), read by the
        lane driver before each boundary so limits recorded mid-flight
        clamp the very next resize decision."""
        with self._lock:
            return self._width_limits.get(key)

    # ---- overload control (ISSUE 9, node/overload.py) ----

    def set_admission_cap(self, rows: int | None) -> None:
        """Brownout rung: cap lane rows admitted per step boundary
        (None/0 = uncapped). Pushed by the worker on every poll and
        every shed while its overload controller holds brownout."""
        with self._lock:
            self._admission_cap = (None if not rows or int(rows) <= 0
                                   else int(rows))

    def admission_cap(self) -> int | None:
        with self._lock:
            return self._admission_cap

    def note_step_seconds(self, seconds: float) -> None:
        """Lane drivers feed each step's wall time; the EWMA rides
        ``stats()`` so the worker's admission estimator can floor a
        lane job's predicted service at steps x step-latency."""
        with self._lock:
            self._step_ewma = (float(seconds) if self._step_ewma <= 0.0
                               else self._step_ewma + 0.25 * (
                                   float(seconds) - self._step_ewma))

    def step_ewma(self) -> float:
        """The step-seconds EWMA (0.0 while cold) — shared by the
        overload estimator's lane floor and the guard's hang-budget and
        slow-step signals (serving/guard.py)."""
        with self._lock:
            return self._step_ewma

    def hang_budget(self) -> float | None:
        """Wall-clock budget the watchdog arms around one lane step
        dispatch (swarmguard, ISSUE 10): k x the step EWMA between the
        floor/ceiling knobs; the ceiling alone while cold, so a lane's
        first (compiling) call is never condemned. None = watchdog off
        (``CHIASWARM_GUARD=0``)."""
        from chiaswarm_tpu.serving.guard import (
            hang_budget_s,
            watchdog_enabled,
        )

        if not watchdog_enabled():
            return None
        with self._lock:
            ewma = self._step_ewma
        return hang_budget_s(ewma)

    def retire_lanes_for_owner(self, owner_id: int) -> int:
        """Eviction→lane-retire (ISSUE 9 satellite, ROADMAP item 4c
        residue): ask every lane built on the components object with
        ``id == owner_id`` to retire as soon as its rows drain — idle
        lanes free their device state on the next driver wakeup instead
        of after the idle grace. Returns the number of lanes asked."""
        with self._lock:
            lanes = [lane for key, lane in self._lanes.items()
                     if key and key[0] == owner_id]
        for lane in lanes:
            lane.request_retire()
        return len(lanes)

    # ---- submission ----

    def submit_request(self, pipe, *, prompt: str, negative_prompt: str = "",
                       steps: int = 30, guidance_scale: float = 7.5,
                       height: int | None = None, width: int | None = None,
                       rows: int = 1, seed: int = 0,
                       scheduler: str | None = None,
                       deadline_s: float | None = None,
                       job_id: Any = None,
                       resume: dict[str, Any] | None = None,
                       init_image: Any = None, strength: float = 0.8,
                       mask: Any = None,
                       controlnet: Any = None, control_image: Any = None,
                       control_scale: float = 1.0,
                       reuse_schedule: Any = None) -> Future:
        """Prepare a job's rows (tokenize, encode, ladder, initial noise
        — plus, per workload: init-latent VAE encode, latent-mask
        quantization, ControlNet hint embedding) and hand them to the
        matching lane. Returns a Future resolving to ``(PendingImages,
        lane_info)``; raises :class:`LaneReject` when the job cannot
        ride a lane.

        Workloads (ISSUE 7b): ``init_image`` makes the rows img2img —
        ``strength`` maps to a per-row denoise START index exactly as
        the solo program quantizes it; ``mask`` (with ``init_image``)
        makes them inpaint — the latent-grid mask + clean source
        latents ride as row state and the step program re-projects the
        kept region per step; ``controlnet`` (a ControlNetBundle, with
        ``control_image``) routes to a bundle-keyed control lane with
        the pre-embedded hint + ``control_scale`` per row.

        ``resume`` (a lane checkpoint from a redelivered job) replaces
        the fresh-noise prologue with the snapshotted latents, keys, and
        multistep history, splicing the rows in at the recorded step. An
        invalid/corrupt payload is rejected LOUDLY and the job restarts
        at step 0 — progress is expendable, trajectory integrity is
        not."""
        import jax
        import jax.numpy as jnp

        from chiaswarm_tpu.core.compile_cache import (
            bucket_batch,
            bucket_image_size,
            bucket_steps,
        )
        from chiaswarm_tpu.core.rng import key_for_seed
        from chiaswarm_tpu.pipelines.diffusion import (
            _resize_batch,
            latent_mask,
        )
        from chiaswarm_tpu.schedulers import make_sampling_schedule, resolve

        from chiaswarm_tpu.schedulers.sampling import FEWSTEP_KINDS

        fam = pipe.c.family
        if fam.kind != "sd" or fam.image_conditioned:
            raise LaneReject(f"family {fam.name!r} does not ride lanes")
        sampler = resolve(scheduler, prediction_type=fam.prediction_type)
        if float(guidance_scale) <= 1.0 and sampler.kind not in \
                FEWSTEP_KINDS:
            # few-step kinds are guidance-embedded (ISSUE 12): their
            # CFG-free mode rides lanes — the per-row combine selects
            # the pure conditional prediction for guidance <= 1 rows
            raise LaneReject("guidance <= 1 runs the solo (no-CFG) program")
        if mask is not None and init_image is None:
            raise LaneReject("inpainting requires an init image")
        if controlnet is not None and (control_image is None
                                       or mask is not None
                                       or init_image is not None):
            raise LaneReject("controlnet lanes take exactly a "
                             "conditioning image")
        height, width = bucket_image_size(int(height or fam.default_size),
                                          int(width or fam.default_size))
        steps = max(1, int(steps))
        try:
            cap = bucket_steps(steps)
        except ValueError as exc:
            raise LaneReject(str(exc)) from exc
        rows = max(1, int(rows))
        workload = ("controlnet" if controlnet is not None else
                    "inpaint" if mask is not None else
                    "img2img" if init_image is not None else "txt2img")
        # img2img strength -> start index: the solo program's exact
        # quantization (the shared helper), so a lane row executes the
        # identical truncated ladder
        start_step = 0
        if workload == "img2img":
            from chiaswarm_tpu.pipelines.diffusion import (
                img2img_start_index,
            )

            start_step = img2img_start_index(steps, strength)
        bounds_lo, bounds_hi = self.width_bounds(height, width)
        if rows > bounds_hi:
            raise LaneReject(
                f"{rows} rows exceed the lane width cap {bounds_hi}")
        # DeepCache (ISSUE 12): the per-job schedule engages only behind
        # the env switch and never alongside the ControlNet branch —
        # schedule-carrying jobs ride reuse-keyed lanes whose program
        # compiles the cache branch in; everything else keeps the plain
        # lane program untouched
        reuse: tuple[int, ...] = ()
        if reuse_schedule:
            from chiaswarm_tpu.pipelines.diffusion import (
                deepcache_enabled,
                normalize_reuse_schedule,
            )

            if deepcache_enabled() and controlnet is None:
                try:
                    reuse = normalize_reuse_schedule(
                        steps, reuse_schedule, start_step)
                except ValueError as exc:
                    # the solo path raises the canonical user error
                    raise LaneReject(str(exc)) from exc
        key = (id(pipe.c), height, width, cap, sampler,
               None if controlnet is None else id(controlnet),
               bool(reuse))
        lane_rows = self.initial_width(rows, height, width)
        limit = self._width_limits.get(key)
        if limit is not None and limit < lane_rows:
            lane_rows = max(rows, limit)
        self._note_arrival(rows)

        sched = make_sampling_schedule(pipe.noise_schedule, steps, sampler)
        sig = np.asarray(sched.sigmas, np.float32)
        ts = np.asarray(sched.timesteps, np.float32)

        resume_step = 0
        restored = None
        if resume is not None:
            try:
                resume_step, restored = self._validate_resume(
                    pipe, resume, steps=steps, rows=rows,
                    height=height, width=width,
                    guidance=float(guidance_scale),
                    start=start_step, workload=workload,
                    reuse_schedule=reuse)
            except ResumeReject as exc:
                log.error("resume state for job %s rejected (%s); "
                          "restarting at step 0", job_id, exc)
                self._count(resumes_rejected=1)
                resume_step, restored = 0, None

        t_prep = time.perf_counter()
        with span("encode", rows=rows, steps=steps), \
                annotate("swarm.lane.encode"):
            eb = bucket_batch(rows)
            ids = [jnp.asarray(i)
                   for i in pipe._tokenize([prompt or ""] * eb)]
            neg = [jnp.asarray(i) for i in
                   pipe._tokenize([negative_prompt or ""] * eb)]
            ctx_u, ctx_c, pooled_u, pooled_c = pipe.stepper_encode_fn(
                batch=eb)(pipe.c.params, ids, neg)
            # workload row state: init latents encoded with the job's
            # OWN seed through the same batch-1 executable the solo run
            # uses (bitwise solo equality by construction); masks
            # quantize through the shared latent_mask helper; hints
            # pre-embed once per job (the solo hoisting, kept)
            init_rows = mask_rows = cond_rows = None
            if init_image is not None:
                init = np.asarray(init_image)
                if init.shape[:2] != (height, width):
                    init = _resize_batch(init, height, width)
                z = pipe.encode_init_image(init, height, width, int(seed))
                init_rows = jnp.repeat(z, rows, axis=0)
            if mask is not None:
                lh, lw = pipe._latent_hw(height, width)
                m = latent_mask(np.asarray(mask, np.float32), lh, lw,
                                fam.vae.downscale)
                mask_rows = jnp.repeat(
                    jnp.asarray(m)[None, :, :, None], rows, axis=0)
            if controlnet is not None:
                cond = np.asarray(control_image)
                as_u8 = cond.dtype == np.uint8
                if cond.shape[:2] != (height, width):
                    cond = _resize_batch(cond, height, width)
                cond = np.asarray(cond, np.float32)
                if as_u8 or cond.max() > 1.0:
                    cond = cond / 255.0
                emb = pipe.stepper_control_embed_fn(
                    height=height, width=width)(
                        controlnet.params["embed"],
                        jnp.asarray(np.clip(cond, 0.0, 1.0))[None])
                cond_rows = jnp.repeat(emb, rows, axis=0)
            cache_u0 = cache_c0 = None
            cache_ok0 = False
            skipped0 = 0
            if restored is not None:
                # redelivered rows: the context re-encodes (it is a pure
                # function of the prompt), but latents/keys/history come
                # back exactly as the dead worker checkpointed them
                carry_rows = jnp.asarray(restored["keys"])
                x0_rows = jnp.asarray(restored["x"])
                old_rows = jnp.asarray(restored["old"])
                if reuse and "cache_u" in restored:
                    # DeepCache resume: the deep caches + validity +
                    # skipped tally splice back in, so the remaining
                    # reuse decisions replay bit-identically
                    cache_u0 = jnp.asarray(restored["cache_u"])
                    cache_c0 = jnp.asarray(restored["cache_c"])
                    cache_ok0 = bool(restored["cache_ok"])
                    skipped0 = int(restored["skipped"])
            else:
                # per-row noise keys: fold the row index into the job's
                # seed — exactly the solo program's key derivation, so
                # every row matches its solo run bit-for-bit in key space
                keys = jnp.stack(
                    [jax.random.fold_in(key_for_seed(int(seed)), r)
                     for r in range(rows)] +
                    [key_for_seed(int(seed))] * (eb - rows))
                carry, x0 = pipe.stepper_row_init_fn(
                    batch=eb, height=height, width=width)(
                        keys, jnp.float32(sig[start_step]))
                carry_rows, x0_rows, old_rows = carry[:rows], x0[:rows], None
                if init_rows is not None:
                    # img2img/inpaint prologue: x = init + noise * sigma
                    # (row_init returned the noise term at sigma[start])
                    x0_rows = init_rows + x0_rows
        _LANE_ADMIT_SECONDS.observe(time.perf_counter() - t_prep)
        job = _RowJob(
            job_id=job_id, n_rows=rows, steps=steps,
            guidance=float(guidance_scale), sigmas=sig, timesteps=ts,
            ctx_u=ctx_u[:rows], ctx_c=ctx_c[:rows],
            pooled_u=None if pooled_u is None else pooled_u[:rows],
            pooled_c=None if pooled_c is None else pooled_c[:rows],
            keys0=carry_rows, x0=x0_rows,
            resume_step=resume_step, old0=old_rows,
            workload=workload, start_step=start_step,
            known0=init_rows if mask is not None else None,
            mask0=mask_rows, cond0=cond_rows,
            cscale=float(control_scale),
            reuse_schedule=reuse,
            cache_u0=cache_u0, cache_c0=cache_c0,
            cache_ok0=cache_ok0, skipped0=skipped0,
            deadline=time.monotonic() + (deadline_s if deadline_s is not None
                                         else self.row_deadline_s()))
        self._enqueue(key, pipe, job, lane_rows, height, width, cap, sampler,
                      control=controlnet, bounds=(bounds_lo, bounds_hi),
                      reuse=bool(reuse))
        return job.future

    def _validate_resume(self, pipe, resume: dict[str, Any], *,
                         steps: int, rows: int, height: int, width: int,
                         guidance: float, start: int = 0,
                         workload: str = "txt2img",
                         reuse_schedule: tuple[int, ...] = (),
                         ) -> tuple[int, dict[str, np.ndarray]]:
        """Check a redelivered job's checkpoint against the job it claims
        to resume; returns (step, restored host arrays) or raises
        :class:`ResumeReject`. Every field is hostile until proven
        consistent — the payload crossed two serializations and a worker
        death."""
        if resume.get("kind") != "lane":
            raise ResumeReject(
                f"not a lane checkpoint (kind={resume.get('kind')!r})")
        try:
            step = int(resume["step"])
            ck_steps = int(resume["steps"])
            ck_rows = int(resume["rows"])
            ck_h, ck_w = int(resume["height"]), int(resume["width"])
            ck_guidance = float(resume["guidance"])
            # pre-ISSUE-7 checkpoints carry no workload fields: they
            # could only have come from txt2img lanes, which is exactly
            # what the defaults assert
            ck_start = int(resume.get("start", 0))
            ck_workload = str(resume.get("workload", "txt2img"))
            x = unpack_array(resume["x"])
            keys = unpack_array(resume["keys"])
            old = unpack_array(resume["old"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ResumeReject(f"corrupt payload: {exc}") from exc
        if not start < step < steps:
            raise ResumeReject(f"step {step} outside ({start}, {steps})")
        if (ck_start, ck_workload) != (start, workload):
            # a checkpoint stepped down a different ladder suffix (or a
            # different workload's trajectory) must not finish under
            # this job's identity — restart clean instead
            raise ResumeReject(
                f"workload mismatch: checkpoint is {ck_workload} from "
                f"step {ck_start}, job is {workload} from {start}")
        if (ck_steps, ck_rows) != (steps, rows):
            raise ResumeReject(
                f"job mismatch: checkpoint is {ck_rows} row(s) x "
                f"{ck_steps} step(s), job wants {rows} x {steps}")
        if (ck_h, ck_w) != (height, width):
            raise ResumeReject(
                f"size mismatch: checkpoint {ck_h}x{ck_w}, "
                f"job {height}x{width}")
        if ck_guidance != guidance:
            # latents stepped so far under a DIFFERENT guidance would
            # finish under this job's and deliver the wrong image as a
            # success — a mixed-up checkpoint must restart clean instead
            raise ResumeReject(
                f"guidance mismatch: checkpoint {ck_guidance}, "
                f"job {guidance}")
        lh, lw = pipe._latent_hw(height, width)
        ch = pipe.c.family.vae.latent_channels
        if x.shape != (rows, lh, lw, ch) or old.shape != x.shape:
            raise ResumeReject(
                f"latent shape {x.shape} != {(rows, lh, lw, ch)}")
        if x.dtype != np.float32 or old.dtype != np.float32:
            raise ResumeReject(
                f"latent dtype {x.dtype}/{old.dtype}, lanes carry float32")
        # the per-row carry keys must match the lane's key template in
        # FULL shape and dtype: a (rows,)-shaped or wrong-dtype keys
        # array would pass a first-axis check here only to explode
        # inside lane admission, where _fail_all takes every co-resident
        # job down with it
        from chiaswarm_tpu.core.rng import key_for_seed

        template = np.asarray(key_for_seed(0))
        if keys.shape != (rows,) + template.shape or \
                keys.dtype != template.dtype:
            raise ResumeReject(
                f"key array {keys.dtype}{keys.shape} != expected "
                f"{template.dtype}{(rows,) + template.shape}")
        restored: dict[str, Any] = {"x": x, "keys": keys, "old": old}
        # DeepCache identity (ISSUE 12): a checkpoint stepped under a
        # DIFFERENT reuse schedule walked a different trajectory — it
        # must not finish under this job's identity. Tampered schedules
        # and missing/corrupt cache state restart clean.
        try:
            ck_reuse = tuple(int(j) for j in
                             (resume.get("reuse_schedule") or ()))
        except (TypeError, ValueError) as exc:
            raise ResumeReject(
                f"corrupt reuse_schedule: {exc}") from exc
        if ck_reuse != tuple(reuse_schedule):
            raise ResumeReject(
                f"reuse-schedule mismatch: checkpoint {list(ck_reuse)}, "
                f"job {list(reuse_schedule)}")
        if reuse_schedule:
            try:
                cache_u = unpack_array(resume["cache_u"])
                cache_c = unpack_array(resume["cache_c"])
                cache_ok = bool(resume["cache_ok"])
                skipped = int(resume["skipped"])
            except (KeyError, TypeError, ValueError) as exc:
                raise ResumeReject(
                    f"corrupt DeepCache state: {exc}") from exc
            c1 = pipe.c.family.unet.block_out_channels[1]
            cache_dtype = np.dtype(pipe.c.unet.dtype)
            want = (rows, lh, lw, c1)
            if cache_u.shape != want or cache_c.shape != want:
                raise ResumeReject(
                    f"deep-cache shape {cache_u.shape} != {want}")
            if cache_u.dtype != cache_dtype or \
                    cache_c.dtype != cache_dtype:
                raise ResumeReject(
                    f"deep-cache dtype {cache_u.dtype}, lanes carry "
                    f"{cache_dtype}")
            if not 0 <= skipped < steps:
                raise ResumeReject(
                    f"skipped tally {skipped} outside [0, {steps})")
            restored.update(cache_u=cache_u, cache_c=cache_c,
                            cache_ok=cache_ok, skipped=skipped)
        return step, restored

    def _enqueue(self, key, pipe, job, lane_rows, height, width, cap,
                 sampler, control=None, bounds=None,
                 reuse: bool = False) -> None:
        created = False
        with self._lock:
            lane = self._lanes.get(key)
            # a lane narrower than the job could never admit it and
            # _admit_locked is FIFO — the job (and everything behind it)
            # would starve while the lane stays busy. An adaptive lane
            # grows to fit at its next boundary, UNLESS an OOM width cap
            # holds it below the job's rows; a pinned lane never grows.
            # Either way out: open a fresh, wide-enough lane — the old
            # one drains its residents and idles out.
            if lane is not None and lane.width < job.n_rows:
                limit = self._width_limits.get(key)
                can_grow = lane._adaptive and (limit is None
                                               or limit >= job.n_rows)
                if not can_grow:
                    lane = None
            if lane is None or not lane.try_enqueue(job):
                lane = Lane(self, key, pipe, width=lane_rows, height=height,
                            width_px=width, steps_cap=cap, sampler=sampler,
                            control=control, width_bounds=bounds,
                            reuse=reuse)
                self._lanes[key] = lane
                created = True
                if not lane.try_enqueue(job):  # pragma: no cover
                    raise LaneRetired("fresh lane refused the job")
        if created:  # outside the lock: _count takes it too
            self._count(lanes_created=1)

    # ---- lifecycle / observability ----

    def _lane_done(self, lane: Lane) -> None:
        with self._lock:
            if self._lanes.get(lane.key) is lane:
                del self._lanes[lane.key]

    def _note_lane_failure(self, key: tuple, width: int) -> None:
        with self._lock:
            self._failed_lane_hints[key] = int(width)
            while len(self._failed_lane_hints) > 32:  # bounded
                self._failed_lane_hints.pop(
                    next(iter(self._failed_lane_hints)))

    def note_oom(self) -> None:
        """Degradation-ladder hook: after an OOM'd lane run, future lanes
        rebuild at half width (the burst analog splits and re-runs
        serially, node/worker.py). Limits are sticky for the process —
        a chip that OOM'd once at width W will OOM again. Halves ONCE
        per lane incident: every resident job's collector reports the
        same failure, and N jobs must not shrink the width 2^N-fold."""
        with self._lock:
            incident = self._stats.get("lanes_failed", 0)
            if incident == self._last_oom_incident:
                return
            self._last_oom_incident = incident
            keys = (set(self._lanes) | set(self._width_limits)
                    | set(self._failed_lane_hints))
            for key in keys:
                cur = self._width_limits.get(key)
                if cur is None:
                    lane = self._lanes.get(key)
                    cur = (lane.width if lane is not None
                           else self._failed_lane_hints.get(key, 2))
                self._width_limits[key] = max(1, cur // 2)

    def _count(self, **kw: int) -> None:
        with self._lock:
            for k, v in kw.items():
                if v:
                    self._stats[k] += v
            self._total_steps = self._stats.get("steps_executed", 0)

    def _maybe_fault(self, lane: Lane) -> None:
        """Chaos seam (tests/test_chaos.py): raise a scripted fault inside
        the driver loop once the scheduler has executed N total steps."""
        if not self._fault:
            return
        with self._lock:
            if self._fault and self._total_steps >= self._fault[0][0]:
                _, exc = self._fault.pop(0)
                raise exc

    def inject_fault(self, after_steps: int, exc: BaseException) -> None:
        with self._lock:
            self._fault.append((int(after_steps), exc))

    def stats(self) -> dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            data = dict(self._stats)
            lanes = list(self._lanes.values())
            rate = self._arrivals.rate(now)
            step_ewma = self._step_ewma
        active = sum(lane.occupancy()[0] for lane in lanes)
        width = sum(lane.occupancy()[1] for lane in lanes)
        steps_a = data.get("row_steps_active", 0)
        steps_p = data.get("row_steps_padded", 0)
        denom = max(1, steps_a + steps_p)
        data.update({
            "lanes_live": len(lanes),
            "rows_active": active,
            "lane_rows_total": width,
            "lane_occupancy": round(steps_a / denom, 4),
            "padding_waste": round(steps_p / denom, 4),
            "arrival_rate": round(rate, 4),
            "step_seconds_ewma": round(step_ewma, 6),
        })
        return data

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait for every lane to go empty (in-flight rows finish, pending
        rows admitted and finished). True when drained."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self._lock:
                lanes = list(self._lanes.values())
            if not any(lane.busy() for lane in lanes):
                return True
            time.sleep(0.01)
        return False

    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Stop every lane; unfinished rows fail with LaneRetired so their
        jobs bounce to the per-job path (or envelope) — never lost."""
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.stop()
        for lane in lanes:
            lane.join(timeout_s)


_EXIT_SCHEDULERS: "weakref.WeakSet[StepScheduler]"


def _register_for_exit(sched: StepScheduler) -> None:
    """Stop every lane at interpreter exit: a daemon driver thread still
    dispatching XLA programs during teardown aborts the process with a
    C++ ``terminate`` on this backend."""
    global _EXIT_SCHEDULERS
    try:
        _EXIT_SCHEDULERS.add(sched)
        return
    except NameError:
        pass
    import atexit
    import weakref

    _EXIT_SCHEDULERS = weakref.WeakSet()
    _EXIT_SCHEDULERS.add(sched)

    @atexit.register
    def _stop_all_lanes() -> None:
        for scheduler in list(_EXIT_SCHEDULERS):
            try:
                scheduler.shutdown(timeout_s=2.0)
            except Exception:  # teardown must never raise
                pass


def aggregate_stats(steppers) -> dict[str, Any]:
    """Merge several schedulers' stats (one per slot) for /healthz:
    counters sum, the occupancy/waste ratios recompute from the summed
    row-step totals."""
    total = collections.Counter()
    rate = step_ewma = 0.0
    for stepper in steppers:
        for key, value in stepper.stats().items():
            if key == "arrival_rate":
                rate = max(rate, value)  # EWMAs do not sum
                continue
            if key == "step_seconds_ewma":
                step_ewma = max(step_ewma, value)
                continue
            if key in ("lane_occupancy", "padding_waste"):
                continue
            total[key] += value
    steps_a = total.get("row_steps_active", 0)
    steps_p = total.get("row_steps_padded", 0)
    denom = max(1, steps_a + steps_p)
    data = dict(total)
    data["lane_occupancy"] = round(steps_a / denom, 4)
    data["padding_waste"] = round(steps_p / denom, 4)
    data["arrival_rate"] = round(rate, 4)
    data["step_seconds_ewma"] = round(step_ewma, 6)
    return data


def retire_lanes_for_owner(owner_id: int) -> int:
    """Process-wide eviction→lane-retire hook: ask EVERY scheduler's
    lanes built on the components object ``id(c) == owner_id`` to
    retire at drain (idle lanes retire immediately). Called by the
    residency ledger when it evicts a model (serving/residency.py) so
    the lane's device state — the last holder of the evicted params —
    frees at eviction, not after the idle grace."""
    try:
        schedulers = list(_EXIT_SCHEDULERS)
    except NameError:  # no StepScheduler was ever constructed
        return 0
    return sum(sched.retire_lanes_for_owner(owner_id)
               for sched in schedulers)


_ATTACH_LOCK = threading.Lock()


def get_stepper(slot: Any) -> StepScheduler:
    """The slot's resident StepScheduler (created on first use). Lanes —
    not the slot depth semaphore — serialize lane traffic; the slot is
    only consulted for its mesh data width."""
    with _ATTACH_LOCK:
        stepper = getattr(slot, "_stepper", None)
        if stepper is None:
            stepper = StepScheduler(slot)
            try:
                slot._stepper = stepper
            except (AttributeError, TypeError):  # exotic slot stubs
                pass
        return stepper
