"""HBM-resident model fleet: measured residency, eviction, prefetch.

The hive dispatches a dozen model families to one node (SD 1.5/2.1/XL,
ControlNet bundles, upscale, video, audio, caption, TTS — PAPER.md §1),
but until ISSUE 8 the worker's residency story was implicit: the compile
cache LRU-evicted param trees under a static byte budget guessed from
``core/mesh.py::_PARAM_HBM_FRACTION``, and the worker *estimated*
footprints from the largest family's bf16 size. This module owns the
HBM ledger end to end:

- **Measured footprints.** Every load measures the live param tree
  (summed ``.nbytes`` across each leaf's addressable shards, max over
  devices — ``pipelines/components.py::measured_param_bytes``) and
  remembers it per model in ``<settings root>/residency.json``, so the
  next load — and the worker's mesh policy after a restart — plans with
  real numbers instead of the bf16 family estimate. The old knobs
  (``_PARAM_HBM_FRACTION``, the family estimate) remain only as the
  initial budget / first-load fallback before anything has loaded.

- **Donation: evict-then-load under one reservation.** A miss reserves
  the model's remembered (or estimated) footprint FIRST, evicting
  victims in (priority, LRU) order until the reservation fits, and only
  then runs the loader — a swap never holds victim and replacement
  simultaneously. ``peak_bytes`` tracks resident + reserved high-water;
  the churn tests assert it never exceeds budget + one model (the
  allowance for a first-ever load whose footprint nothing remembers).

- **Graceful degradation rungs.** A model whose measured footprint no
  longer fits the budget degrades to load-per-job: the loader still
  runs, but the value is returned UNCACHED with a transient reservation
  released when the job's references die (``weakref.finalize``) — slow,
  but the job completes. A model that cannot even fit transiently
  (footprint > hard limit, or the transient reservation cannot be
  granted within ``reserve_wait_s``) bounces as :class:`ModelUnavailable`
  — ``error_kind: model_unavailable`` WITHOUT the fatal flag, so a
  lease-aware mini-hive redispatches the job to a node that can serve
  it (node/minihive.py ``REDISPATCH_KINDS``).

- **Demand-driven prefetch.** Every acquire feeds a per-model
  :class:`ArrivalEwma` (the LaneWidthController demand pattern,
  serving/stepper.py reuses this class). When the worker's poll loop
  comes back idle it calls :meth:`note_idle`; the manager picks the
  hottest evicted model whose remembered footprint fits the FREE budget
  (prefetch never evicts — background warm loads must not churn the
  working set) and warm-loads it on a daemon thread, synced before
  admission (cross-thread device-array discipline, ROADMAP).

The registry (node/registry.py) is a thin client: every ``*_pipeline``
entry point routes through :meth:`acquire`. Residency state (bytes,
eviction/prefetch counters, per-model state enum shared with
quarantine) is exported as swarmscope families (obs/metrics.py
``residency_*``) and surfaced in ``/healthz``.

Stdlib-only at import (like ``analysis/`` and ``obs/``): jax is touched
lazily, only for budget autodetection and prefetch syncing — the ledger
unit tests run with fake loaders and no devices.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
import weakref
from pathlib import Path
from typing import Any, Callable, Hashable

from chiaswarm_tpu.obs import metrics as obs_metrics

log = logging.getLogger("chiaswarm.residency")

ENV_BUDGET = "CHIASWARM_RESIDENCY_BUDGET"
ENV_HARD_LIMIT = "CHIASWARM_RESIDENCY_HARD_LIMIT"
ENV_PREFETCH = "CHIASWARM_RESIDENCY_PREFETCH"

# ---- swarmscope families (obs/metrics.py declares + documents them) ----
_RESIDENT_BYTES = obs_metrics.residency_bytes_gauge()
_BUDGET_BYTES = obs_metrics.residency_budget_gauge()
_PEAK_BYTES = obs_metrics.residency_peak_gauge()
_MODELS = obs_metrics.residency_models_gauge()
_EVICTIONS = obs_metrics.residency_evictions_counter()
_LOADS = obs_metrics.residency_loads_counter()
_BOUNCES = obs_metrics.residency_bounces_counter()
_LOAD_SECONDS = obs_metrics.residency_load_seconds_histogram()

# pre-seed every label vocabulary so the families render zeroes from the
# FIRST /metrics scrape (dashboards need the zeroes — the ISSUE-6
# convention, same as the stepper control-loop families)
for _state in obs_metrics.RESIDENCY_STATES:
    _MODELS.set(0, state=_state)
for _reason in obs_metrics.RESIDENCY_EVICT_REASONS:
    _EVICTIONS.inc(0, reason=_reason)
for _mode in obs_metrics.RESIDENCY_LOAD_MODES:
    _LOADS.inc(0, mode=_mode)


class _PrefetchSkip(RuntimeError):
    """A background warm load found no free budget (the race window
    between candidate selection and reservation): skipped silently —
    prefetch must never evict or error a job."""


class ModelUnavailable(ValueError):
    """This node cannot hold the model even transiently. The message
    carries the ``is not available on this node`` marker, so
    ``node/resilience.py::classify_exception`` sorts it as
    ``model_unavailable`` — non-fatal, breaker fodder, and a hive-side
    redispatch signal (another node may have the HBM this one lacks)."""


#: half-life of the PER-MODEL arrival EWMAs the prefetch ranking reads.
#: Deliberately longer than the lane demand EWMA's 10 s default: model
#: reuse has minutes-scale locality while lane demand has seconds-scale
#: — the swarmload harness sweep (ISSUE 9, node/loadgen.py::
#: sweep_prefetch_window, seed "swarmload") ranks 20 s best across its
#: seeded regime-shift streams, and tests/test_loadgen.py pins this
#: constant to the sweep winner.
PREFETCH_RANK_WINDOW_S = 20.0


class ArrivalEwma:
    """Events/second EWMA over inter-arrival gaps, decayed while idle.

    The demand signal the adaptive lane-width controller reads
    (serving/stepper.py) and, per model, the prefetch ranking here. All
    methods take an explicit monotonic ``now`` (testable on a fake
    clock; obs R8 forbids wallclock deltas anyway)."""

    def __init__(self, window_s: float = 10.0) -> None:
        self.window_s = float(window_s)
        self._rate = 0.0
        self._last: float | None = None

    def note(self, rows: int, now: float) -> None:
        if self._last is not None:
            gap = max(now - self._last, 1e-3)
            decay = 0.5 ** (gap / self.window_s)
            self._rate = decay * self._rate + (1.0 - decay) * (rows / gap)
        self._last = now

    def rate(self, now: float) -> float:
        if self._last is None:
            return 0.0
        return self._rate * 0.5 ** (max(now - self._last, 0.0)
                                    / self.window_s)


def default_budget_bytes() -> int:
    """Resident-param budget: ``CHIASWARM_RESIDENCY_BUDGET`` wins, else
    the mesh policy's HBM fraction of the measured per-chip memory —
    the ISSUE-8 satellite keeps the old knob as the initial-budget
    fallback (core/mesh.py::resident_param_budget_bytes)."""
    try:
        from chiaswarm_tpu.core.mesh import resident_param_budget_bytes

        return resident_param_budget_bytes()
    except Exception:  # no jax / no devices: the old CompileCache default
        raw = os.environ.get(ENV_BUDGET, "").strip()
        if raw:
            with contextlib.suppress(ValueError):
                return max(1, int(float(raw)))
        return 24 * 1024**3


def default_hard_limit_bytes(budget: int) -> int:
    """Absolute transient ceiling: a load may briefly exceed the
    resident budget (degraded load-per-job), never this. Defaults to
    90% of per-chip HBM — params past that leave no activation room."""
    raw = os.environ.get(ENV_HARD_LIMIT, "").strip()
    if raw:
        with contextlib.suppress(ValueError):
            return max(int(budget), int(float(raw)))
    try:
        from chiaswarm_tpu.core.mesh import device_hbm_bytes

        return max(int(budget), int(0.9 * device_hbm_bytes()))
    except Exception:
        return int(budget) * 2


def prefetch_enabled_default() -> bool:
    return os.environ.get(ENV_PREFETCH, "").strip().lower() not in (
        "0", "false", "off", "no")


def is_transient(value: Any) -> bool:
    """True when ``value`` came from a degraded load-per-job acquire —
    holders (lanes!) must not keep it resident past the job."""
    return bool(getattr(value, "_residency_transient", False))


def _block_until_ready(value: Any) -> None:
    """Sync a loaded value's param tree before cross-thread handoff
    (prefetch loads happen on a daemon thread; executor threads consume
    the arrays — the container-jax discipline from the ROADMAP)."""
    params = getattr(getattr(value, "c", value), "params", None)
    if params is None:
        return
    try:
        import jax

        jax.block_until_ready(jax.tree.leaves(params))
    except Exception:  # stub values in unit tests, no jax, host trees
        pass


def current_weights_format() -> str:
    """The serving weight format (``convert/quantize.py`` owns the env
    var; read directly here so the ledger stays importable without
    jax). Footprints are namespaced by it: an int8 measurement must not
    size a bf16 restart's reservations (~2x wrong both ways)."""
    raw = os.environ.get("CHIASWARM_WEIGHTS", "").strip().lower()
    return raw or "bf16"


class _Entry:
    __slots__ = ("key", "model", "value", "bytes", "priority",
                 "last_used", "owner_id")

    def __init__(self, key: Hashable, model: str, value: Any,
                 nbytes: int, priority: int, last_used: float) -> None:
        self.key = key
        self.model = model
        self.value = value
        self.bytes = int(nbytes)
        self.priority = int(priority)
        self.last_used = float(last_used)
        # the executable-cache owner (pipelines key their compiled fns
        # by id(components)); eviction purges those entries — they can
        # never hit again and would thrash the bounded executable LRU
        owner = getattr(value, "c", None)
        self.owner_id = None if owner is None else id(owner)


class _Recipe:
    """Everything needed to re-load an evicted entry in the background."""

    __slots__ = ("loader", "model", "size_of", "priority")

    def __init__(self, loader: Callable[[], Any], model: str,
                 size_of: Callable[[Any], int] | None,
                 priority: int) -> None:
        self.loader = loader
        self.model = model
        self.size_of = size_of
        self.priority = priority


class ResidencyManager:
    """The HBM ledger: measured residency, priority eviction with
    donation, demand-driven prefetch, and the degradation rungs.

    One per process in production (:func:`default_manager`, shared by
    every registry like ``GLOBAL_CACHE``); tests construct private
    managers with explicit budgets and their own metrics registry."""

    #: sentinel: "use <settings root>/residency.json"; an explicit None
    #: turns persistence OFF (benches and tests must not write the
    #: operator's real footprint file)
    DEFAULT_PERSIST: Any = object()

    def __init__(self, budget_bytes: int | None = None,
                 hard_limit_bytes: int | None = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 persist_path: Path | str | None | Any = DEFAULT_PERSIST,
                 prefetch: bool | None = None,
                 reserve_wait_s: float = 15.0,
                 metrics_registry: Any = None) -> None:
        self.budget_bytes = int(budget_bytes if budget_bytes is not None
                                else default_budget_bytes())
        self.hard_limit_bytes = int(
            hard_limit_bytes if hard_limit_bytes is not None
            else default_hard_limit_bytes(self.budget_bytes))
        self.reserve_wait_s = float(reserve_wait_s)
        self.prefetch_enabled = (prefetch_enabled_default()
                                 if prefetch is None else bool(prefetch))
        self._clock = clock
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._entries: dict[Hashable, _Entry] = {}
        self._loading: dict[Hashable, threading.Event] = {}
        self._resident_bytes = 0
        # reservations split by kind: resident-bound loads count against
        # the BUDGET, transient (load-per-job) ones only against the
        # HARD limit — an in-flight degraded load must not make every
        # concurrent resident reserve evict the working set and bounce
        self._reserved_resident = 0
        self._reserved_transient = 0
        self.peak_bytes = 0
        self._states: dict[str, str] = {}
        self._quarantined: set[str] = set()
        self._arrivals: dict[str, ArrivalEwma] = {}
        self._recipes: dict[Hashable, _Recipe] = {}
        # fleet-planner placement hint (swarmplan, ISSUE 19): the
        # models the current plan assigns this worker, in plan order —
        # idle-poll prefetch warms these BEFORE the local arrival
        # ranking, so placement shifts ahead of the traffic
        self._placement: tuple[str, ...] = ()
        self.placement_hints = 0
        self._prefetch_thread: threading.Thread | None = None
        # counters mirrored into /healthz snapshots (the metric families
        # are process-global; hermetic views need per-manager numbers)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.degraded_loads = 0
        self.prefetch_loads = 0
        self.bounces = 0
        if metrics_registry is not None:
            reg = metrics_registry
            self._m_bytes = obs_metrics.residency_bytes_gauge(reg)
            self._m_budget = obs_metrics.residency_budget_gauge(reg)
            self._m_peak = obs_metrics.residency_peak_gauge(reg)
            self._m_models = obs_metrics.residency_models_gauge(reg)
            self._m_evictions = obs_metrics.residency_evictions_counter(reg)
            self._m_loads = obs_metrics.residency_loads_counter(reg)
            self._m_bounces = obs_metrics.residency_bounces_counter(reg)
            self._m_load_s = obs_metrics.residency_load_seconds_histogram(reg)
        else:
            self._m_bytes, self._m_budget = _RESIDENT_BYTES, _BUDGET_BYTES
            self._m_peak, self._m_models = _PEAK_BYTES, _MODELS
            self._m_evictions, self._m_loads = _EVICTIONS, _LOADS
            self._m_bounces, self._m_load_s = _BOUNCES, _LOAD_SECONDS
        # measured footprints survive restarts: the worker's mesh policy
        # and the first post-restart swap plan with real numbers
        if persist_path is ResidencyManager.DEFAULT_PERSIST:
            self._persist_path = self._default_persist_path()
        else:
            self._persist_path = (None if persist_path is None
                                  else Path(persist_path))
        self._footprints: dict[str, int] = {}
        self._load_footprints()
        self._refresh_gauges_locked()

    # ---- persistence of measured footprints --------------------------

    @staticmethod
    def _default_persist_path() -> Path | None:
        try:
            from chiaswarm_tpu.node.settings import settings_root

            return settings_root() / "residency.json"
        except Exception:
            return None

    def _load_footprints(self) -> None:
        """Restore the CURRENT weight format's section (an int8
        measurement must not size a bf16 restart's reservations)."""
        path = self._persist_path
        if path is None or not path.is_file():
            return
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
            sections = data.get("footprints") or {}
            raw = sections.get(current_weights_format()) or {}
            self._footprints = {str(m): int(b) for m, b in raw.items()
                                if int(b) > 0}
            self._persisted_sections = {
                str(fmt): dict(entries)
                for fmt, entries in sections.items()
                if isinstance(entries, dict)}
        except (OSError, json.JSONDecodeError, TypeError, ValueError,
                AttributeError) as exc:
            log.warning("unreadable residency footprint file %s (%s); "
                        "starting from estimates", path, exc)

    def _save_footprints(self) -> None:
        path = self._persist_path
        if path is None:
            return
        try:
            sections = dict(getattr(self, "_persisted_sections", {}))
            sections[current_weights_format()] = dict(self._footprints)
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(path.suffix + ".tmp")
            tmp.write_text(json.dumps(
                {"version": 2, "footprints": sections},
                sort_keys=True), encoding="utf-8")
            tmp.replace(path)
            self._persisted_sections = sections
        except OSError as exc:  # persistence must never break serving
            log.warning("residency footprint persist to %s failed: %s",
                        path, exc)

    # ---- ledger internals (call with self._lock held) -----------------

    @property
    def _reserved_bytes(self) -> int:
        return self._reserved_resident + self._reserved_transient

    def _note_peak_locked(self) -> None:
        total = self._resident_bytes + self._reserved_bytes
        if total > self.peak_bytes:
            self.peak_bytes = total

    def _set_state_locked(self, model: str, state: str) -> None:
        self._states[model] = state

    def _models_with_entries_locked(self) -> set[str]:
        return {e.model for e in self._entries.values()}

    @staticmethod
    def _drop_owner_executables(owner_id: int | None, model: str) -> None:
        """Purge the bounded executable LRU of entries keyed by a dead
        components' id — after an eviction (or a transient release) they
        can never hit again, and leaving them would thrash live models'
        compiled programs out of the 16-entry cache on every swap."""
        if owner_id is None:
            return
        try:
            from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE

            dropped = GLOBAL_CACHE.executables.drop_where(
                lambda k: isinstance(k, tuple) and k and k[0] == owner_id)
            if dropped:
                log.debug("dropped %d orphaned executable(s) of %s",
                          dropped, model)
        except Exception:  # cache hygiene must never break the ledger
            pass

    @staticmethod
    def _retire_owner_lanes(owner_id: int | None, model: str) -> None:
        """Eviction→lane-retire (ISSUE 9 satellite, ROADMAP item 4c
        residue): a resident stepper lane holds the evicted model's
        pipeline between jobs, so without this hook its HBM only frees
        after the lane's idle grace (the old README caveat). Retire the
        victim's lanes at drain — idle lanes free immediately. Lazy
        import: stepper imports this module's ArrivalEwma, so the
        dependency must stay one-way at import time."""
        if owner_id is None:
            return
        try:
            from chiaswarm_tpu.serving.stepper import retire_lanes_for_owner

            retired = retire_lanes_for_owner(owner_id)
            if retired:
                log.info("eviction of %s retired %d lane(s) at drain",
                         model, retired)
        except Exception:  # lane hygiene must never break the ledger
            pass

    def _charge_locked(self, need_bytes: int, limit: int,
                       count_transient: bool) -> int:
        """Bytes the ``limit`` check sees: resident + resident-bound
        reservations (+ transient ones only for hard-limit checks) +
        the incoming need. Resident-budget checks EXCLUDE in-flight
        transient reservations — a degraded load-per-job in progress
        must not starve (or mass-evict for) resident loads that fit."""
        reserved = self._reserved_resident
        if count_transient:
            reserved += self._reserved_transient
        return self._resident_bytes + reserved + need_bytes - limit

    def _evict_locked(self, need_bytes: int, limit: int, reason: str,
                      count_transient: bool = False) -> bool:
        """Drop (priority, LRU)-ordered victims until ``need_bytes`` more
        fit under ``limit``. Returns True when they do. The donation
        invariant lives here: this runs BEFORE the incoming load, under
        its reservation, so victim and replacement never coexist."""
        while self._charge_locked(need_bytes, limit, count_transient) > 0:
            victims = list(self._entries.values())
            if not victims:
                return self._charge_locked(need_bytes, limit,
                                           count_transient) <= 0
            victim = min(victims,
                         key=lambda e: (e.priority, e.last_used))
            del self._entries[victim.key]
            self._resident_bytes -= victim.bytes
            self.evictions += 1
            self._m_evictions.inc(reason=reason)
            if victim.model not in self._models_with_entries_locked():
                self._set_state_locked(victim.model, "evicted")
            self._drop_owner_executables(victim.owner_id, victim.model)
            self._retire_owner_lanes(victim.owner_id, victim.model)
            log.info("evicted %s (%.1f MiB, priority %d, reason %s); "
                     "resident now %.1f MiB", victim.model,
                     victim.bytes / 2**20, victim.priority, reason,
                     self._resident_bytes / 2**20)
            self._space.notify_all()
        return True

    def _refresh_gauges_locked(self) -> None:
        self._m_bytes.set(self._resident_bytes)
        self._m_budget.set(self.budget_bytes)
        self._m_peak.set(self.peak_bytes)
        counts = {state: 0 for state in obs_metrics.RESIDENCY_STATES}
        for model, state in self._states.items():
            if model in self._quarantined:
                state = "quarantined"
            counts[state] = counts.get(state, 0) + 1
        for state, n in counts.items():
            self._m_models.set(n, state=state)

    # ---- the acquire path ---------------------------------------------

    def acquire(self, key: Hashable, loader: Callable[[], Any], *,
                model: str,
                size_of: Callable[[Any], int] | None = None,
                estimate: Callable[[], int | None] | None = None,
                priority: int = 0,
                mode: str = "demand") -> Any:
        """Resident value for ``key``, loading (and evicting) as needed.

        ``size_of`` measures the built value's live footprint (the
        registry passes ``pipe.c.param_bytes()`` — summed shard
        ``.nbytes``); ``estimate`` is the pre-load reservation fallback
        for a model never measured before (the bf16/int8 family
        estimate). Raises :class:`ModelUnavailable` when the model
        cannot fit even transiently."""
        model = str(model)
        now = self._clock()
        with self._lock:
            if mode != "prefetch":
                # prefetch re-loads must not inflate the demand signal
                # they themselves are ranked by
                self._arrivals.setdefault(
                    model, ArrivalEwma(
                        window_s=PREFETCH_RANK_WINDOW_S)).note(1, now)
                self._recipes[key] = _Recipe(loader, model, size_of,
                                             priority)
            entry = self._entries.get(key)
            if entry is not None:
                entry.last_used = now
                self.hits += 1
                return entry.value
        # serialize concurrent loads of one key: the second caller waits
        # for the first instead of double-loading a multi-GB tree
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    entry.last_used = self._clock()
                    self.hits += 1
                    return entry.value
                event = self._loading.get(key)
                if event is None:
                    event = threading.Event()
                    self._loading[key] = event
                    break
            if not event.wait(timeout=600.0):
                raise TimeoutError(
                    f"timed out waiting for a concurrent load of {model!r}")
            # loader finished: loop re-checks residency (a degraded load
            # admits nothing — this caller becomes the next loader)
        try:
            return self._load(key, loader, model=model, size_of=size_of,
                              estimate=estimate, priority=priority,
                              mode=mode)
        finally:
            with self._lock:
                self._loading.pop(key, None)
            event.set()

    def _expected_bytes(self, model: str,
                        estimate: Callable[[], int | None] | None) -> int:
        measured = self._footprints.get(model)
        if measured:
            return int(measured)
        if estimate is not None:
            try:
                guess = estimate()
                if guess:
                    return int(guess)
            except Exception as exc:  # estimates must never block serving
                log.debug("footprint estimate for %s failed: %s", model,
                          exc)
        return 0

    def _reserve(self, model: str, expected: int, transient: bool,
                 mode: str) -> bool:
        """Take the pre-load reservation, evicting for it (donation).
        Resident reservations check the BUDGET (excluding in-flight
        transient bytes — see ``_charge_locked``); transient
        (over-budget) loads reserve against the HARD limit, counting
        everything, and may wait ``reserve_wait_s`` for in-flight
        transients to release. Prefetch reservations never evict — a
        background warm load racing a demand load must not churn the
        working set the demand load just built. Returns False when the
        space never materializes (bounce / prefetch skip)."""
        limit = self.hard_limit_bytes if transient else self.budget_bytes
        deadline = self._clock() + self.reserve_wait_s
        with self._space:
            while True:
                if mode == "prefetch":
                    fits = self._charge_locked(expected, limit,
                                               count_transient=True) <= 0
                else:
                    fits = self._evict_locked(
                        expected, limit, reason="capacity",
                        count_transient=transient)
                if fits:
                    if transient:
                        self._reserved_transient += expected
                    else:
                        self._reserved_resident += expected
                    self._note_peak_locked()
                    self._set_state_locked(model, "loading")
                    self._refresh_gauges_locked()
                    return True
                if mode == "prefetch":
                    return False  # never evict, never wait: just skip
                # no room even after evicting everything evictable:
                # CONCURRENT reservations hold the rest. They settle
                # into evictable entries (or release) quickly — wait
                # for them instead of spuriously bouncing a model that
                # fits the node sequentially.
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._space.wait(timeout=min(remaining, 1.0))

    def _release_transient(self, nbytes: int, model: str,
                           owner_id: int | None) -> None:
        """A degraded load-per-job value's last reference died: release
        its reservation and drop its orphaned executables (they were
        keyed by the dead components' id and can never hit again)."""
        with self._space:
            self._reserved_transient = max(
                0, self._reserved_transient - nbytes)
            self._refresh_gauges_locked()
            self._space.notify_all()
        self._drop_owner_executables(owner_id, model)

    def _load(self, key: Hashable, loader: Callable[[], Any], *,
              model: str, size_of: Callable[[Any], int] | None,
              estimate: Callable[[], int | None] | None,
              priority: int, mode: str) -> Any:
        self.misses += 1
        expected = self._expected_bytes(model, estimate)
        if expected > self.hard_limit_bytes:
            self._bounce(model, expected)
        transient = expected > self.budget_bytes
        if not self._reserve(model, expected, transient, mode):
            if mode == "prefetch":
                raise _PrefetchSkip(
                    f"no free budget to prefetch {model!r}")
            self._bounce(model, expected)

        def release_reservation_locked(nbytes: int) -> None:
            if transient:
                self._reserved_transient = max(
                    0, self._reserved_transient - nbytes)
            else:
                self._reserved_resident = max(
                    0, self._reserved_resident - nbytes)

        evicted_before = self.evictions
        t0 = time.perf_counter()
        try:
            value = loader()
        except BaseException:
            with self._space:
                release_reservation_locked(expected)
                if model not in self._models_with_entries_locked():
                    self._set_state_locked(model, "unavailable")
                self._refresh_gauges_locked()
                self._space.notify_all()
            raise
        actual = expected
        if size_of is not None:
            try:
                actual = int(size_of(value))
            except Exception as exc:
                log.warning("footprint measurement for %s failed (%s); "
                            "keeping the %.1f MiB reservation", model,
                            exc, expected / 2**20)
        load_mode = ("prefetch" if mode == "prefetch"
                     else "per_job" if (transient
                                        or actual > self.budget_bytes)
                     else "resident")
        self._m_load_s.observe(
            time.perf_counter() - t0, mode=load_mode,
            swapped="1" if self.evictions > evicted_before else "0")
        with self._space:
            # swap the pre-load reservation for the measured footprint
            release_reservation_locked(expected)
            if actual > 0:
                self._footprints[model] = actual
            if transient or actual > self.budget_bytes:
                # degradation rung: serve load-per-job; the transient
                # reservation releases when the value's refs die
                self._reserved_transient += actual
                self._note_peak_locked()
                self.degraded_loads += 1
                self._m_loads.inc(mode="per_job")
                self._set_state_locked(model, "degraded")
                owner = getattr(value, "c", None)
                try:
                    value._residency_transient = True
                except (AttributeError, TypeError):
                    pass  # slotted stubs: is_transient just reads False
                weakref.finalize(value, self._release_transient, actual,
                                 model, None if owner is None
                                 else id(owner))
                self._refresh_gauges_locked()
                self._space.notify_all()
                log.warning(
                    "model %s (%.1f MiB measured) exceeds the %.1f MiB "
                    "residency budget; degraded to load-per-job", model,
                    actual / 2**20, self.budget_bytes / 2**20)
            else:
                # admit: evict again only if the measurement overshot
                # the estimate. Prefetch still never evicts — it skips
                # instead (the next demand acquire reloads properly).
                if mode == "prefetch":
                    if self._charge_locked(actual, self.budget_bytes,
                                           count_transient=True) > 0:
                        self._set_state_locked(model, "evicted")
                        self._refresh_gauges_locked()
                        self._space.notify_all()
                        raise _PrefetchSkip(
                            f"free budget for {model!r} vanished mid-load")
                elif not self._evict_locked(actual, self.budget_bytes,
                                            reason="capacity"):
                    # nothing left to evict: CONCURRENT reservations
                    # hold the rest of the budget. The memory is
                    # already allocated (the value is loaded) — admit
                    # anyway with honest accounting; the ledger trims
                    # back under budget on the next reservation, once
                    # those in-flight loads settle into evictable
                    # entries. Refusing the job here would waste the
                    # load AND mislabel a healthy model.
                    log.warning(
                        "admitting %s (%.1f MiB) above budget: "
                        "concurrent reservations hold %.1f MiB; the "
                        "ledger trims on the next load", model,
                        actual / 2**20, self._reserved_bytes / 2**20)
                self._entries[key] = _Entry(key, model, value, actual,
                                            priority, self._clock())
                self._resident_bytes += actual
                self._note_peak_locked()
                self._m_loads.inc(mode=load_mode)
                if mode == "prefetch":
                    self.prefetch_loads += 1
                self._set_state_locked(model, "resident")
                self._refresh_gauges_locked()
        self._save_footprints()
        return value

    def _bounce(self, model: str, expected: int) -> None:
        with self._lock:
            self.bounces += 1
            self._m_bounces.inc()
            self._set_state_locked(model, "unavailable")
            self._refresh_gauges_locked()
        raise ModelUnavailable(
            f"model {model!r} is not available on this node: its "
            f"~{expected / 2**20:.0f} MiB footprint cannot fit the "
            f"{self.hard_limit_bytes / 2**20:.0f} MiB transient HBM "
            f"limit (budget {self.budget_bytes / 2**20:.0f} MiB)")

    # ---- budget control (the chaos "budget squeeze" seam) --------------

    def set_budget(self, budget_bytes: int,
                   hard_limit_bytes: int | None = None) -> None:
        """Shrink (or grow) the ledger at runtime; a shrink evicts down
        to the new budget immediately, counted ``reason="squeeze"``."""
        with self._space:
            self.budget_bytes = max(0, int(budget_bytes))
            if hard_limit_bytes is not None:
                self.hard_limit_bytes = max(self.budget_bytes,
                                            int(hard_limit_bytes))
            else:
                self.hard_limit_bytes = max(self.budget_bytes,
                                            self.hard_limit_bytes)
            self._evict_locked(0, self.budget_bytes, reason="squeeze")
            self._refresh_gauges_locked()

    def reset_peak(self) -> None:
        """Re-arm the high-water mark (tests/benches bracket one swap)."""
        with self._lock:
            self.peak_bytes = self._resident_bytes + self._reserved_bytes
            self._refresh_gauges_locked()

    # ---- prefetch (worker idle-poll hook) ------------------------------

    def note_placement(self, models: Any) -> None:
        """Accept the fleet planner's model assignment for this worker
        (swarmplan, ISSUE 19 — delivered on heartbeat acks). Purely
        advisory: it reorders the idle-poll prefetch preference below;
        it never loads, evicts, or blocks anything by itself."""
        cleaned = tuple(str(m) for m in (models or ()) if str(m))
        with self._lock:
            if cleaned != self._placement:
                self.placement_hints += 1
                log.info("placement hint: %s", list(cleaned) or "(clear)")
            self._placement = cleaned

    def note_idle(self) -> bool:
        """The poll loop came back empty: warm-load the hottest evicted
        model that fits the FREE budget, on a daemon thread. Returns
        True when a prefetch was started. Plan-assigned models (a
        ``note_placement`` hint) outrank the local arrival EWMAs, in
        plan order — the planner sees fleet-wide demand this worker's
        local stream has not delivered yet."""
        with self._lock:
            if not self.prefetch_enabled:
                return False
            if (self._prefetch_thread is not None
                    and self._prefetch_thread.is_alive()):
                return False
            now = self._clock()
            free = (self.budget_bytes - self._resident_bytes
                    - self._reserved_bytes)
            hint_order = {model: index
                          for index, model in enumerate(self._placement)}
            best_key, best_rate = None, 0.0
            best_hint: tuple[int, Hashable] | None = None
            for key, recipe in self._recipes.items():
                if key in self._entries or key in self._loading:
                    continue
                if recipe.model in self._quarantined:
                    continue
                footprint = self._footprints.get(recipe.model)
                if not footprint or footprint > self.budget_bytes:
                    continue  # degraded models never prefetch
                if footprint > free:
                    continue  # prefetch must not evict the working set
                hint = hint_order.get(recipe.model)
                if hint is not None and (best_hint is None
                                         or hint < best_hint[0]):
                    best_hint = (hint, key)
                ewma = self._arrivals.get(recipe.model)
                rate = ewma.rate(now) if ewma is not None else 0.0
                if rate > best_rate:
                    best_key, best_rate = key, rate
            if best_hint is not None:
                best_key = best_hint[1]
                model = self._recipes[best_key].model
                ewma = self._arrivals.get(model)
                best_rate = ewma.rate(now) if ewma is not None else 0.0
            if best_key is None:
                return False
            recipe = self._recipes[best_key]

            def warm(key=best_key, recipe=recipe):
                try:
                    value = self.acquire(
                        key, recipe.loader, model=recipe.model,
                        size_of=recipe.size_of, priority=recipe.priority,
                        mode="prefetch")
                    # sync before any executor thread can consume the
                    # freshly dispatched arrays (ROADMAP discipline)
                    _block_until_ready(value)
                    log.info("prefetched %s (arrival rate %.2f/s)",
                             recipe.model, best_rate)
                except _PrefetchSkip as exc:
                    log.debug("prefetch skipped: %s", exc)
                except Exception as exc:
                    log.warning("prefetch of %s failed: %s", recipe.model,
                                exc)

            self._prefetch_thread = threading.Thread(
                target=warm, name="residency-prefetch", daemon=True)
            self._prefetch_thread.start()
            return True

    # ---- state shared with the registry (quarantine enum merge) --------

    def note_quarantined(self, model: str) -> None:
        with self._lock:
            self._quarantined.add(str(model))
            self._refresh_gauges_locked()

    def note_unquarantined(self, model: str) -> None:
        with self._lock:
            self._quarantined.discard(str(model))
            self._refresh_gauges_locked()

    def would_degrade(self, model: str) -> bool:
        """True when the model's remembered footprint no longer fits the
        budget — the executor's pre-load check that keeps degraded
        models off resident lanes (node/executor.py)."""
        with self._lock:
            footprint = self._footprints.get(str(model))
            return bool(footprint and footprint > self.budget_bytes)

    def model_states(self) -> dict[str, str]:
        """The authoritative per-model state enum (ISSUE 8 satellite):
        quarantine overrides residency; models never touched read as
        absent (the registry fills catalog entries in as ``cold``)."""
        with self._lock:
            out = dict(self._states)
            for model in self._quarantined:
                out[model] = "quarantined"
            return out

    def measured_footprints(self) -> dict[str, int]:
        with self._lock:
            return dict(self._footprints)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    @property
    def reserved_bytes(self) -> int:
        with self._lock:
            return self._reserved_bytes

    def resident_models(self) -> list[str]:
        with self._lock:
            return sorted(self._models_with_entries_locked())

    def snapshot(self) -> dict[str, Any]:
        """/healthz view (node/worker.py): the ledger at a glance."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "hard_limit_bytes": self.hard_limit_bytes,
                "resident_bytes": self._resident_bytes,
                "reserved_bytes": self._reserved_bytes,
                "peak_bytes": self.peak_bytes,
                "resident_models":
                    sorted(self._models_with_entries_locked()),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "degraded_loads": self.degraded_loads,
                "prefetch_loads": self.prefetch_loads,
                "bounces": self.bounces,
                "prefetch_enabled": self.prefetch_enabled,
                "placement": list(self._placement),
                "placement_hints": self.placement_hints,
            }


_DEFAULT_MANAGER: ResidencyManager | None = None
_DEFAULT_LOCK = threading.Lock()


def default_manager() -> ResidencyManager:
    """Process-wide manager (lazy: the budget autodetects from the
    devices, which must not happen at import time)."""
    global _DEFAULT_MANAGER
    with _DEFAULT_LOCK:
        if _DEFAULT_MANAGER is None:
            _DEFAULT_MANAGER = ResidencyManager()
        return _DEFAULT_MANAGER
