"""``swarm-tpu`` — the node operator CLI.

One entry point over the reference's three module scripts
(``python -m swarm.initialize`` / ``swarm.worker`` / ``swarm.test``,
SURVEY.md §1 L6):

    swarm-tpu init [--reset --silent --warm-compile]   configure + prefetch
    swarm-tpu worker                                   serve the swarm
    swarm-tpu smoke [--workflow X | --all]             hermetic smoke jobs
    swarm-tpu bench                                    BASELINE.json configs
    swarm-tpu info                                     device/mesh report
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def cmd_info(_args) -> int:
    import jax

    from chiaswarm_tpu import WORKER_VERSION
    from chiaswarm_tpu.core.chip_pool import ChipPool

    pool = ChipPool(n_slots=1)
    print(json.dumps({
        "worker_version": WORKER_VERSION,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "slots": pool.descriptor(),
    }, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="swarm-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("init", add_help=False)
    sub.add_parser("worker")
    sub.add_parser("smoke", add_help=False)
    sub.add_parser("bench")
    sub.add_parser("info")

    args, rest = parser.parse_known_args(argv)

    if args.command == "init":
        from chiaswarm_tpu.node.initialize import init

        return asyncio.run(init(rest))
    if args.command == "worker":
        from chiaswarm_tpu.node.worker import run_worker

        # the guard's restart rung surfaces as a distinct exit code
        # (serving/guard.py GUARD_RESTART_EXIT_CODE) so supervisors
        # restart-on-73 instead of paging a crash
        return asyncio.run(run_worker())
    if args.command == "smoke":
        from chiaswarm_tpu.node.smoke import main as smoke_main

        return smoke_main(rest)
    if args.command == "bench":
        from chiaswarm_tpu.benchmark import main as bench_main

        bench_main()
        return 0
    if args.command == "info":
        return cmd_info(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
