"""Cascaded pixel-space diffusion (DeepFloyd-IF-class models).

Capability parity with swarm/diffusion/diffusion_func_if.py:14-92: a
three-stage cascade — 64px T5-conditioned base, 4x super-resolution to
256px, then a final upscale to ~1024px — with the prompt embedding computed
ONCE and shared across stages (:45-61; the reference re-encodes on stage 1
and passes embeds down).

TPU-first redesign:
- stages 1 and 2 are each ONE jitted program (text encode is hoisted out
  and shared; denoise is a lax.scan; no VAE — pixel space);
- stage 2 conditions by channel-concatenating the nearest-upsampled stage-1
  output (sample_channels = 6), the same concat-conditioning pattern as the
  latent upscaler;
- the UNets predict epsilon + learned variance (out_channels = 6); the
  sigma-space samplers consume the epsilon half;
- stage 3 runs the jitted SD-x4-upscaler (pipelines/upscale.py::
  Upscale4xPipeline) — the SAME text-conditioned x4 SR model class the
  reference uses (diffusion_func_if.py:31-40), 256 -> 1024 in one pass;
  the pass loop also accepts an x2-class upscaler (two passes) for nodes
  without the x4 checkpoint.

The reference's known stage-2 bug (negative_prompt fed from ``prompt``,
diffusion_func_if.py:44) is intentionally NOT reproduced.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import (
    toplevel_jit,
    GLOBAL_CACHE,
    bucket_batch,
    static_cache_key,
)
from chiaswarm_tpu.parallel.context import seq_parallel_wrap
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.common import upsample2x_nearest
from chiaswarm_tpu.models.configs import UNetConfig
from chiaswarm_tpu.models.t5 import T5Config, T5Encoder
from chiaswarm_tpu.models.tokenizer import HashTokenizer
from chiaswarm_tpu.models.unet import UNet
from chiaswarm_tpu.schedulers import (
    make_noise_schedule,
    make_sampling_schedule,
    resolve,
    sampler_step,
    scale_model_input,
)
from chiaswarm_tpu.schedulers.common import ScheduleConfig
from chiaswarm_tpu.schedulers.sampling import init_sampler_state


@dataclasses.dataclass(frozen=True)
class CascadeFamily:
    """Architecture of one IF-class cascade (base + super-res stages)."""

    name: str
    t5: T5Config
    stage1: UNetConfig          # base: sample_channels=3, out_channels=6
    stage2: UNetConfig          # super-res: sample_channels=6, out_channels=6
    base_size: int = 64
    sr_size: int = 256
    beta_schedule: str = "squaredcos_cap_v2"  # IF trains on a cosine schedule


# IF-I-XL / IF-II-L shaped (DeepFloyd/IF-I-XL-v1.0 + IF-II-L-v1.0)
IF_XL = CascadeFamily(
    name="if_xl",
    t5=T5Config(),
    stage1=UNetConfig(
        sample_channels=3, out_channels=6,
        block_out_channels=(192, 384, 768, 1536),
        transformer_depth=(0, 0, 1, 1),
        attention_head_dim=64, head_dim_is_count=False,
        cross_attention_dim=4096,
    ),
    stage2=UNetConfig(
        sample_channels=6, out_channels=6,
        block_out_channels=(128, 256, 512, 1024),
        transformer_depth=(0, 0, 1, 1),
        attention_head_dim=64, head_dim_is_count=False,
        cross_attention_dim=4096,
    ),
)

# Hermetic-test cascade: full structure, toy widths.
TINY_CASCADE = CascadeFamily(
    name="tiny_cascade",
    t5=T5Config(vocab_size=1000, d_model=32, d_kv=8, d_ff=64,
                num_layers=2, num_heads=4, max_length=77, eos_token_id=999,
                dtype="float32"),
    stage1=UNetConfig(sample_channels=3, out_channels=6,
                      block_out_channels=(32, 64), layers_per_block=1,
                      transformer_depth=(0, 1), attention_head_dim=4,
                      head_dim_is_count=True, cross_attention_dim=32,
                      dtype="float32"),
    stage2=UNetConfig(sample_channels=6, out_channels=6,
                      block_out_channels=(32, 64), layers_per_block=1,
                      transformer_depth=(0, 1), attention_head_dim=4,
                      head_dim_is_count=True, cross_attention_dim=32,
                      dtype="float32"),
    base_size=16,
    sr_size=64,
)

CASCADE_FAMILIES = {f.name: f for f in (IF_XL, TINY_CASCADE)}


def get_cascade_family(model_name: str) -> CascadeFamily:
    low = (model_name or "").lower()
    tail = low.rsplit("/", 1)[-1]
    if low in CASCADE_FAMILIES:
        return CASCADE_FAMILIES[low]
    if tail in CASCADE_FAMILIES:
        return CASCADE_FAMILIES[tail]
    return CASCADE_FAMILIES["if_xl"]


@dataclasses.dataclass
class CascadeComponents:
    family: CascadeFamily
    model_name: str
    tokenizer: Any
    t5: T5Encoder
    unet1: UNet
    unet2: UNet
    params: dict[str, Any]  # keys: t5, unet1, unet2

    @classmethod
    def random(cls, family: CascadeFamily | str, seed: int = 0,
               model_name: str | None = None) -> "CascadeComponents":
        if isinstance(family, str):
            family = CASCADE_FAMILIES[family]
        key = jax.random.PRNGKey(seed)
        t5 = T5Encoder(family.t5)
        unet1 = UNet(family.stage1)
        unet2 = UNet(family.stage2)
        tokenizer = HashTokenizer(family.t5.vocab_size, family.t5.max_length,
                                  family.t5.eos_token_id,
                                  pad_id=family.t5.pad_token_id,
                                  add_bos=False)
        ids = jnp.zeros((1, family.t5.max_length), jnp.int32)
        key, k1, k2, k3 = jax.random.split(key, 4)
        params = {"t5": jax.jit(t5.init)(k1, ids)}
        ctx = jnp.zeros((1, family.t5.max_length, family.t5.d_model),
                        jnp.float32)
        s = 8
        params["unet1"] = jax.jit(unet1.init)(
            k2, jnp.zeros((1, s, s, family.stage1.sample_channels)),
            jnp.zeros((1,)), ctx)
        params["unet2"] = jax.jit(unet2.init)(
            k3, jnp.zeros((1, s, s, family.stage2.sample_channels)),
            jnp.zeros((1,)), ctx)
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, t5=t5, unet1=unet1, unet2=unet2,
                   params=params)

    def param_bytes(self) -> int:
        leaves = jax.tree.leaves(self.params)
        return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


class CascadePipeline:
    """Resident compile-cached IF-class cascade executor."""

    def __init__(self, components: CascadeComponents,
                 attn_impl: str = "auto") -> None:
        self.c = components
        fam = components.family
        if attn_impl != "auto":
            if attn_impl != fam.stage1.attn_impl:
                components.unet1 = UNet(dataclasses.replace(
                    fam.stage1, attn_impl=attn_impl))
            if attn_impl != fam.stage2.attn_impl:
                components.unet2 = UNet(dataclasses.replace(
                    fam.stage2, attn_impl=attn_impl))
        self.schedule_config = ScheduleConfig(
            beta_schedule=fam.beta_schedule,
            prediction_type="epsilon",
        )
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    def _build_fn(self, *, batch: int, steps1: int, steps2: int,
                  sampler, use_cfg: bool):
        fam = self.c.family
        t5, unet1, unet2 = self.c.t5, self.c.unet1, self.c.unet2
        sched1 = make_sampling_schedule(self.noise_schedule, steps1, sampler)
        sched2 = make_sampling_schedule(self.noise_schedule, steps2, sampler)
        s1, s2 = fam.base_size, fam.sr_size
        if s2 % s1 != 0 or (s2 // s1) & (s2 // s1 - 1):
            raise ValueError("sr_size must be a power-of-two multiple of "
                             "base_size")

        def denoise(unet, params, sched, steps, x, ctx, cond, guidance,
                    row_keys):
            """Shared scan: ``cond`` (static None or array) is channel-
            concatenated every step (stage-2 conditioning). ``row_keys``
            is one PRNG key PER batch row — row b's ancestral noise
            depends only on its own key, so an image is identical at any
            batch size (the diffusion pipeline's per-sample contract,
            pipelines/diffusion.py)."""

            def body(carry, i):
                x, state, row_keys = carry
                inp = scale_model_input(sched, x, i)
                if cond is not None:
                    inp = jnp.concatenate([inp, cond], axis=-1)
                if use_cfg:
                    inp2 = jnp.concatenate([inp, inp], axis=0)
                    t2 = sched.timesteps[i][None].repeat(inp2.shape[0], axis=0)
                    out = unet.apply(params, inp2, t2, ctx)
                    eps = out[..., : x.shape[-1]]  # drop learned variance
                    eps_u, eps_c = jnp.split(eps, 2, axis=0)
                    eps = eps_u + guidance * (eps_c - eps_u)
                else:
                    t1 = sched.timesteps[i][None].repeat(x.shape[0], axis=0)
                    out = unet.apply(params, inp, t1, ctx)
                    eps = out[..., : x.shape[-1]]
                both = jax.vmap(jax.random.split)(row_keys)
                row_keys, skeys = both[:, 0], both[:, 1]
                noise = jax.vmap(lambda k: jax.random.normal(
                    k, x.shape[1:], jnp.float32))(skeys)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=noise, start_index=0)
                return (x, state, row_keys), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), row_keys),
                jnp.arange(steps))
            return x

        def fn(params, ids, neg_ids, row_keys, guidance):
            # the IF serving path hands T5 the tokenizer padding mask
            # (pad id 0) — padding tokens must not shape the prompt embeds
            pad = fam.t5.pad_token_id
            ctx = t5.apply(params["t5"], ids, ids != pad)
            if use_cfg:
                nctx = t5.apply(params["t5"], neg_ids, neg_ids != pad)
                ctx2 = jnp.concatenate([nctx, ctx], axis=0)
            else:
                ctx2 = ctx

            def stage_keys(stage: int):
                return jax.vmap(
                    lambda k: jax.random.fold_in(k, stage))(row_keys)

            # ---- stage 1: 64px base
            x = jax.vmap(lambda k: jax.random.normal(
                k, (s1, s1, 3), jnp.float32))(stage_keys(1))
            x = x * sched1.sigmas[0]
            x = denoise(unet1, params["unet1"], sched1, steps1, x, ctx2,
                        None, guidance, stage_keys(2))
            x = jnp.clip(x, -1.0, 1.0)

            # ---- stage 2: super-res, conditioned on upsampled stage 1
            # (cond is concatenated pre-CFG-doubling inside denoise, so it
            # stays at the plain batch size)
            cond = x
            for _ in range((s2 // s1).bit_length() - 1):
                cond = upsample2x_nearest(cond)
            y = jax.vmap(lambda k: jax.random.normal(
                k, (s2, s2, 3), jnp.float32))(stage_keys(3))
            y = y * sched2.sigmas[0]
            y = denoise(unet2, params["unet2"], sched2, steps2, y, ctx2,
                        cond, guidance, stage_keys(4))
            # quantize ON DEVICE: uint8 moves 4x fewer bytes over the
            # host link (pipelines/diffusion.py rationale)
            return (jnp.clip((y + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                    ).astype(jnp.uint8)

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "cascade", static),
            lambda: self._build_fn(**static))

    def submit(self, prompt: str, negative_prompt: str = "",
               steps: int = 50, sr_steps: int = 30,
               guidance_scale: float = 7.0, batch: int = 1,
               seed: int = 0, scheduler: str | None = None,
               first_row: int = 0):
        """Dispatch the stage-1+2 program WITHOUT blocking on the result.

        Returns ``(device_img, requested, config)`` — the uint8 output is
        still materializing on the chip (jax async dispatch), so a caller
        can queue more work (the next item's stages, another submesh's
        stage 3) before paying the transfer. The blocking path is
        ``__call__``.

        Row b's noise key is ``fold_in(key_for_seed(seed), first_row+b)``
        — the per-sample contract: a (seed, row) pair draws the same
        image whether it runs inside a batch or as a batch-1 program at
        ``first_row=row`` (generate_stage_parallel relies on this)."""
        requested = max(1, batch)
        batch = bucket_batch(requested)
        sampler = resolve(scheduler, prediction_type="epsilon")
        use_cfg = guidance_scale > 1.0
        tok = self.c.tokenizer
        ids = jnp.asarray(tok.encode_batch([prompt] * batch))
        neg = jnp.asarray(tok.encode_batch([negative_prompt or ""] * batch))

        fn = self._get_fn(batch=batch, steps1=int(steps),
                          steps2=int(sr_steps), sampler=sampler,
                          use_cfg=use_cfg)
        base_key = key_for_seed(seed)
        row_keys = jax.vmap(
            lambda r: jax.random.fold_in(base_key, r)
        )(jnp.arange(first_row, first_row + batch))
        img = fn(self.c.params, ids, neg, row_keys,
                 jnp.float32(guidance_scale))
        config = {
            "model_name": self.c.model_name,
            "family": self.c.family.name,
            "mode": "cascade_txt2img",
            "steps": int(steps),
            "sr_steps": int(sr_steps),
            "guidance_scale": float(guidance_scale),
            "size": [self.c.family.sr_size, self.c.family.sr_size],
            "scheduler": sampler.kind,
        }
        return img, requested, config

    def __call__(self, prompt: str, negative_prompt: str = "",
                 steps: int = 50, sr_steps: int = 30,
                 guidance_scale: float = 7.0, batch: int = 1,
                 seed: int = 0, scheduler: str | None = None,
                 upscaler=None, final_size: int | None = None,
                 ) -> tuple[np.ndarray, dict]:
        """Full IF protocol. Stages 1+2 (base -> sr_size) always run; when
        ``upscaler`` (a LatentUpscalePipeline) is provided the cascade runs
        its third stage — repeated x2 latent-upscale denoise passes until
        ``final_size`` (default 4 * sr_size, the reference's x4-upscaler
        output: 256 -> 1024, diffusion_func_if.py:31-40,63-65)."""
        img, requested, config = self.submit(
            prompt, negative_prompt, steps=steps, sr_steps=sr_steps,
            guidance_scale=guidance_scale, batch=batch, seed=seed,
            scheduler=scheduler)
        img_u8 = np.asarray(jax.device_get(img))  # uint8 off-chip
        img_u8 = img_u8[:requested]  # trim the pow2 compile bucket padding
        stages = 2
        if upscaler is not None:
            img_u8, stage3 = _run_stage3(img_u8, upscaler, prompt, seed,
                                         final_size or
                                         self.c.family.sr_size * 4)
            config.update(stage3)
            if "stage3_passes" in stage3:
                stages += 1
            config["size"] = list(img_u8.shape[1:3])
        config["stages"] = stages
        return img_u8, config


def _run_stage3(img_u8: np.ndarray, upscaler, prompt: str, seed: int,
                final_size: int, first_row: int = 0,
                ) -> tuple[np.ndarray, dict]:
    """Stage 3: upscale denoise passes to ``final_size`` (one x4 pass for
    the SD-x4-upscaler; two passes for an x2-class stand-in). The
    reference's stage 3 re-conditions on the raw prompt STRING
    (diffusion_func_if.py:63-65 — the shared T5 embeds stop at stage 2;
    the x4-upscaler is CLIP-conditioned), so passing ``prompt`` down is
    the faithful contract here too."""
    target = int(final_size)
    config: dict = {}
    passes = 0
    prev_size = 0
    # the upscaler buckets its input at 1024 max, so output caps at
    # 2048: stop when a pass makes no progress (else a hive job with an
    # oversized final_size would spin this loop forever)
    while img_u8.shape[1] < target and img_u8.shape[1] > prev_size:
        prev_size = img_u8.shape[1]
        img_u8, up_config = upscaler(img_u8, prompt=prompt or "",
                                     seed=seed, first_row=first_row)
        passes += 1
        config.update(up_config)
    if passes:
        config["stage3_passes"] = passes
    return img_u8, config


def generate_stage_parallel(pipe: CascadePipeline, upscaler, *,
                            prompt: str, negative_prompt: str = "",
                            steps: int = 50, sr_steps: int = 30,
                            guidance_scale: float = 7.0, n_images: int = 1,
                            seed: int = 0, scheduler: str | None = None,
                            final_size: int | None = None,
                            ) -> tuple[np.ndarray, dict]:
    """Pipeline-parallel cascade: stages 1+2 and stage 3 on DISJOINT
    submeshes (core/mesh.py::split_mesh), images streamed through.

    ``pipe``'s params live on submesh A and ``upscaler``'s on submesh B
    (the registry places each per its own mesh). Every image's stage-1+2
    program is dispatched up front (jax async dispatch queues them on A),
    then each result is handed to stage 3 on B as it lands — so image
    i+1's base/SR denoise runs CONCURRENTLY with image i's x4 upscale on
    different chips. Wall-clock approaches max(sum_A, sum_B) + one stage
    latency, vs their sum when the stages share chips. The reference runs
    the three IF stages strictly sequentially on one GPU
    (diffusion_func_if.py:41-65).

    Image i runs as a batch-1 program at ``first_row=i``, so its noise
    keys are ``fold_in(key_for_seed(seed), i)`` — EXACTLY what row i of
    the single-program batched path draws. The same (seed, index) yields
    the same image on any slot topology (the diffusion pipeline's
    per-sample noise-key contract)."""
    n_images = max(1, int(n_images))
    submitted = []
    for i in range(n_images):
        img_dev, _, config = pipe.submit(
            prompt, negative_prompt, steps=steps, sr_steps=sr_steps,
            guidance_scale=guidance_scale, batch=1, seed=seed,
            scheduler=scheduler, first_row=i)
        submitted.append((img_dev, config))

    outs = []
    config = dict(submitted[0][1])
    stages = 2
    for i, (img_dev, _) in enumerate(submitted):
        img_u8 = np.asarray(jax.device_get(img_dev))[:1]
        if upscaler is not None:
            img_u8, stage3 = _run_stage3(
                img_u8, upscaler, prompt, seed,
                final_size or pipe.c.family.sr_size * 4, first_row=i)
            config.update(stage3)
            if "stage3_passes" in stage3:
                stages = 3
        outs.append(img_u8)
    images = np.concatenate(outs, axis=0)
    config["size"] = list(images.shape[1:3])
    config["stages"] = stages
    config["pipeline_parallel"] = 2
    return images, config
