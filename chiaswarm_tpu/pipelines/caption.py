"""Captioning / VQA pipeline (img2txt) — native BLIP, one program per stage.

Replaces the torch BLIP classes the reference instantiates per job
(swarm/captioning/caption_image.py:12-30). Stage structure:

- caption: vision encode (jit) -> greedy cross-attending scan decode
  (models/blip.py::generate_text, one compiled program).
- VQA: vision encode -> question tower (bidirectional, cross-attends the
  image) -> answer decoder cross-attending the question states. The
  question's pad mask rides into the decoder as a cross-attention bias so
  padding never leaks into the answer.

Host side only resizes/normalizes the image and decodes WordPiece ids.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.models.blip import (
    BLIP_CONFIGS,
    BlipConfig,
    BlipTextModel,
    BlipVisionEncoder,
    generate_text,
)
from chiaswarm_tpu.core.compile_cache import toplevel_jit
from chiaswarm_tpu.models.tokenizer import WordPieceTokenizer


def _tiny_vocab() -> dict[str, int]:
    """Synthetic WordPiece vocab for hermetic tiny-BLIP runs (ids < 1000)."""
    vocab = {"[PAD]": 0, "[UNK]": 100, "[CLS]": 101, "[SEP]": 999,
             "[DEC]": 998}
    i = 1
    while len(vocab) < 990:
        if i not in (100, 101, 998, 999):
            vocab[f"tok{i}"] = i
        i += 1
    return vocab


@dataclasses.dataclass
class CaptionComponents:
    config: BlipConfig
    model_name: str
    tokenizer: WordPieceTokenizer
    vision: BlipVisionEncoder
    decoder: BlipTextModel
    encoder: BlipTextModel | None  # VQA question tower (None = caption-only)
    params: dict[str, Any]         # keys: vision, decoder[, encoder]

    @classmethod
    def random(cls, config: BlipConfig | str = "blip_tiny", seed: int = 0,
               model_name: str | None = None,
               vqa: bool = True) -> "CaptionComponents":
        if isinstance(config, str):
            config = BLIP_CONFIGS[config]
        vision = BlipVisionEncoder(config.vision)
        decoder = BlipTextModel(config.text)
        encoder = BlipTextModel(config.text, with_lm_head=False) if vqa \
            else None
        key = jax.random.PRNGKey(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        pixels = jnp.zeros(
            (1, config.vision.image_size, config.vision.image_size, 3),
            jnp.float32)
        ids = jnp.zeros((1, 4), jnp.int32)
        enc = jnp.zeros((1, config.vision.num_tokens,
                         config.text.encoder_hidden_size), jnp.float32)
        head_dim = config.text.hidden_size // config.text.num_heads
        dummy_kvs = [
            (jnp.zeros((1, enc.shape[1], config.text.num_heads, head_dim),
                       jnp.float32),) * 2
            for _ in range(config.text.num_layers)
        ]
        params: dict[str, Any] = {
            "vision": jax.jit(vision.init)(k1, pixels),
        }
        # two init passes share one RNG key: __call__ materializes every
        # param except the cross K/V projections, which only run inside
        # method=cross_kvs — merge the trees
        params["decoder"] = _merge(
            jax.jit(lambda k: decoder.init(
                k, ids, causal=True, cross_kvs=dummy_kvs))(k2),
            jax.jit(lambda k: decoder.init(k, enc,
                                           method="cross_kvs"))(k2))
        if encoder is not None:
            params["encoder"] = _merge(
                jax.jit(lambda k: encoder.init(
                    k, ids, causal=False, cross_kvs=dummy_kvs,
                    logits=False))(k3),
                jax.jit(lambda k: encoder.init(k, enc,
                                               method="cross_kvs"))(k3))
        tokenizer = WordPieceTokenizer(_tiny_vocab()) \
            if config.text.vocab_size < 30000 else None
        if tokenizer is None:
            raise ValueError("random() is for tiny configs; real vocabs "
                             "need a checkpoint (from_checkpoint)")
        return cls(config=config,
                   model_name=model_name or f"random/{config.name}",
                   tokenizer=tokenizer, vision=vision, decoder=decoder,
                   encoder=encoder, params=params)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str | Path, model_name: str,
                        config: BlipConfig | str = "blip_base",
                        ) -> "CaptionComponents":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_blip_text,
            convert_blip_vision,
            read_torch_weights,
        )

        if isinstance(config, str):
            config = BLIP_CONFIGS[config]
        checkpoint_dir = Path(checkpoint_dir)
        state = read_torch_weights(checkpoint_dir)
        params: dict[str, Any] = {
            "vision": convert_blip_vision(state),
            "decoder": convert_blip_text(state, "text_decoder."),
        }
        encoder = None
        if any(k.startswith("text_encoder.") for k in state):
            params["encoder"] = convert_blip_text(state, "text_encoder.",
                                                  with_lm_head=False)
            encoder = BlipTextModel(config.text, with_lm_head=False)
        vocab = checkpoint_dir / "vocab.txt"
        if not vocab.exists():
            raise FileNotFoundError(f"no vocab.txt under {checkpoint_dir}")
        return cls(config=config, model_name=model_name,
                   tokenizer=WordPieceTokenizer.from_vocab_file(vocab),
                   vision=BlipVisionEncoder(config.vision),
                   decoder=BlipTextModel(config.text), encoder=encoder,
                   params=params)

    def param_bytes(self) -> int:
        return sum(x.nbytes for x in jax.tree.leaves(self.params))


def _merge(a: dict, b: dict) -> dict:
    """Deep-merge two flax param trees (b wins on leaves)."""
    out = dict(a)
    for k, v in b.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


class CaptionPipeline:
    """``__call__(image, prompt, vqa=...) -> text``. The hive's model
    type — not the checkpoint contents — picks the mode (the reference
    instantiates whichever class the server names,
    caption_image.py:12-13): ``vqa=True`` answers the prompt through the
    question tower; otherwise a prompt *conditions* the caption
    (caption_image.py:21-23 conditional mode)."""

    # conditioning prompts pad to one static bucket ([DEC] + 16 tokens):
    # exactly two compiled decode programs total (len-1 and len-17)
    PROMPT_BUCKET = 17

    def __init__(self, components: CaptionComponents,
                 max_new_tokens: int = 24) -> None:
        self.c = components
        self.max_new = max_new_tokens
        self._encode_image = toplevel_jit(
            lambda p, x: self.c.vision.apply(p, x))
        if self.c.encoder is not None:
            self._encode_question = toplevel_jit(self._question_fwd)

    # ---- host-side image prep ----
    def preprocess(self, image: np.ndarray) -> jnp.ndarray:
        from PIL import Image

        size = self.c.config.vision.image_size
        pil = Image.fromarray(image.astype(np.uint8)).convert("RGB")
        pil = pil.resize((size, size), Image.BICUBIC)
        arr = np.asarray(pil, np.float32) / 255.0
        mean = np.asarray(self.c.config.pixel_mean, np.float32)
        std = np.asarray(self.c.config.pixel_std, np.float32)
        return jnp.asarray((arr - mean) / std)[None]

    def _question_fwd(self, params, ids, mask, enc_states):
        cross_kvs = self.c.encoder.apply(params, enc_states,
                                         method="cross_kvs")
        states, _ = self.c.encoder.apply(
            params, ids, causal=False, attn_mask=mask, cross_kvs=cross_kvs,
            logits=False)
        return states

    def __call__(self, image: np.ndarray, prompt: str = "",
                 vqa: bool | None = None) -> str:
        c = self.c
        if vqa is None:
            vqa = False  # default model type is conditional generation
        if vqa and c.encoder is None:
            raise ValueError(
                f"{c.model_name!r} has no question tower (VQA requested)")
        pixels = self.preprocess(image)
        enc_states = self._encode_image(c.params["vision"], pixels)

        if vqa and prompt:
            # VQA: question tower over the image, then answer decode
            q_len = 32
            q_ids = jnp.asarray(
                [c.tokenizer.encode(prompt, q_len)], jnp.int32)
            q_mask = (q_ids != c.tokenizer.pad_id).astype(jnp.int32)
            q_states = self._encode_question(c.params["encoder"], q_ids,
                                             q_mask, enc_states)
            dec_in = jnp.asarray([[c.config.text.bos_token_id]], jnp.int32)
            ids = generate_text(c.decoder, c.params["decoder"], dec_in,
                                q_states, q_mask, prompt_len=1,
                                max_new=self.max_new)
            return c.tokenizer.decode(np.asarray(ids)[0])

        # caption; a prompt conditions the decoder (caption_image.py:21-23
        # conditional mode). Conditioned prefixes pad to PROMPT_BUCKET
        # with actual_len traced — no recompile per prompt length.
        cond_tokens = c.tokenizer.tokenize(prompt) if prompt else []
        used = cond_tokens[: self.PROMPT_BUCKET - 1]
        prefix = [c.config.text.bos_token_id] + used
        actual = len(prefix)
        if prompt:
            bucket = self.PROMPT_BUCKET
            prefix = prefix + [c.tokenizer.pad_id] * (bucket - actual)
        else:
            bucket = 1
        dec_in = jnp.asarray([prefix], jnp.int32)
        ids = generate_text(c.decoder, c.params["decoder"], dec_in,
                            enc_states, None, prompt_len=bucket,
                            max_new=self.max_new,
                            actual_len=jnp.int32(actual))
        text = c.tokenizer.decode(np.asarray(ids)[0])
        if prompt:
            # prepend only what actually conditioned the decode: when the
            # prompt exceeds the bucket, echoing the full text would claim
            # a prefix the model never saw
            head = (prompt.strip() if len(used) == len(cond_tokens)
                    else c.tokenizer.decode(used))
            text = f"{head} {text}".strip()
        return text
