"""Model component bundles: modules + params + tokenizers for one checkpoint.

The reference builds a diffusers pipeline object per job from the HF cache
(swarm/diffusion/diffusion_func.py:41-46). The TPU equivalent is a
:class:`Components` bundle that stays resident (core/compile_cache.py): the
Flax modules are cheap static descriptions; the params live on device.

Construction paths:
- :meth:`Components.random` — random-init weights for hermetic tests and
  architecture benchmarks (weights don't change FLOPs).
- :meth:`Components.from_checkpoint` — converted torch/safetensors weights
  via chiaswarm_tpu.convert (the initialize-time warm cache replacing
  swarm/initialize.py:62-94).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.clip import ClipTextEncoder
from chiaswarm_tpu.models.configs import FAMILIES, ModelFamily, get_family
from chiaswarm_tpu.models.tokenizer import HashTokenizer, Tokenizer, load_tokenizer
from chiaswarm_tpu.models.unet import UNet
from chiaswarm_tpu.models.vae import AutoencoderKL


def materialize_host(shape_tree, rng, dtype: str = "bfloat16"):
    """Materialize an ``eval_shape`` param tree with host-numpy values —
    no XLA init program (on-device fp32 init of billion-param families
    exhausts single-chip HBM and compiles for minutes). Big kernels are
    zeros: sampling billions of host normals dominates runtime, and value
    content does not change TPU op timing (no denormal penalties)."""
    import numpy as np

    out_dtype = jnp.dtype(dtype)

    def leaf(s):
        dt = out_dtype if s.dtype == jnp.float32 else s.dtype
        if int(np.prod(s.shape)) > 1_000_000:
            return jnp.zeros(s.shape, dt)
        return jnp.asarray(
            rng.standard_normal(s.shape).astype(np.float32) * 0.02, dt)

    return jax.tree.map(leaf, shape_tree)


def abstract_params(family: ModelFamily | str) -> dict[str, Any]:
    """Param SHAPE trees for every module of a family — pure
    ``jax.eval_shape`` tracing, no arrays and no compile. Drives
    random_host materialization and the mesh policy's size estimate."""
    if isinstance(family, str):
        family = FAMILIES[family]
    text_encoders = [ClipTextEncoder(cfg) for cfg in family.text_encoders]
    unet = UNet(family.unet)
    vae = AutoencoderKL(family.vae)

    key = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, family.text_encoders[0].max_position_embeddings),
                    jnp.int32)
    shapes: dict[str, Any] = {}
    for i, te in enumerate(text_encoders):
        shapes[f"text_encoder_{i}"] = jax.eval_shape(te.init, key, ids)
    latent = jnp.zeros((1, 8, 8, family.unet.sample_channels))
    ctx = jnp.zeros((1, ids.shape[1], family.unet.cross_attention_dim))
    added = None
    if family.unet.addition_embed_dim is not None:
        added = {
            "time_ids": jnp.zeros((1, 6)),
            "text_embeds": jnp.zeros((1, family.unet.addition_pooled_dim)),
        }
    labels = (jnp.zeros((1,), jnp.int32)
              if family.unet.num_class_embeds is not None else None)
    shapes["unet"] = jax.eval_shape(
        lambda k, s, t, c, a, cl: unet.init(k, s, t, c, a, class_labels=cl),
        key, latent, jnp.zeros((1,)), ctx, added, labels)
    shapes["vae"] = jax.eval_shape(
        vae.init, key, jnp.zeros((1, 16, 16, family.vae.in_channels)))
    return shapes


def measured_param_bytes(tree: Any) -> int:
    """MEASURED per-chip HBM footprint of a live param tree (ISSUE 8):
    sum each leaf's ``.nbytes`` across its addressable shards, bucketed
    per device, max over devices — replicated copies cost every chip
    their full size, tensor-parallel shards split it. This is what the
    residency ledger (serving/residency.py) accounts with, replacing
    the worker's bf16 family-size estimate. Host/numpy leaves (not yet
    placed) count toward a shared bucket. int8-quantized leaves
    (convert/quantize.py Int8Param pytree nodes) flatten to their code
    + scale arrays, so the measurement sees the real int8 bytes."""
    per_device: dict[Any, int] = {}
    host_bytes = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for shard in shards:
                nbytes = int(getattr(shard.data, "nbytes", 0) or 0)
                per_device[shard.device] = (
                    per_device.get(shard.device, 0) + nbytes)
        else:
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is None:
                import numpy as np

                nbytes = np.asarray(leaf).nbytes
            host_bytes += int(nbytes)
    if not per_device:
        return host_bytes
    return max(per_device.values()) + host_bytes


_FAMILY_BYTES_CACHE: dict[tuple[str, int], int] = {}


def estimate_family_bytes(family: ModelFamily | str,
                          bytes_per_param: int = 2) -> int:
    """Serving-footprint estimate (bf16 by default) for one family's full
    param set — from abstract shapes, so big families cost a trace, not
    memory. Used by the worker's default dp x tp policy (core/mesh.py)."""
    if isinstance(family, str):
        family = FAMILIES[family]
    cache_key = (family.name, bytes_per_param)
    if cache_key not in _FAMILY_BYTES_CACHE:
        import numpy as np

        shapes = abstract_params(family)
        total = sum(int(np.prod(leaf.shape))
                    for leaf in jax.tree.leaves(shapes))
        _FAMILY_BYTES_CACHE[cache_key] = total * bytes_per_param
    return _FAMILY_BYTES_CACHE[cache_key]


@dataclasses.dataclass
class Components:
    family: ModelFamily
    model_name: str
    tokenizers: Sequence[Tokenizer]
    text_encoders: Sequence[ClipTextEncoder]
    unet: UNet
    vae: AutoencoderKL
    params: dict[str, Any]  # keys: text_encoder_{i}, unet, vae

    @classmethod
    def random(cls, family: ModelFamily | str, seed: int = 0,
               model_name: str | None = None) -> "Components":
        if isinstance(family, str):
            family = FAMILIES[family]
        key = jax.random.PRNGKey(seed)
        text_encoders = [ClipTextEncoder(cfg) for cfg in family.text_encoders]
        tokenizers = [
            HashTokenizer(cfg.vocab_size, cfg.max_position_embeddings,
                          cfg.eos_token_id)
            for cfg in family.text_encoders
        ]
        unet = UNet(family.unet)
        vae = AutoencoderKL(family.vae)

        # jit every init: eager flax init dispatches thousands of tiny ops,
        # which is pathologically slow from worker threads on remote-tunnel
        # TPU platforms; one compiled program per module is thread-agnostic.
        params: dict[str, Any] = {}
        ids = jnp.zeros((1, family.text_encoders[0].max_position_embeddings),
                        jnp.int32)
        for i, te in enumerate(text_encoders):
            key, sub = jax.random.split(key)
            params[f"text_encoder_{i}"] = jax.jit(te.init)(sub, ids)

        latent = jnp.zeros(
            (1, 8, 8, family.unet.sample_channels), jnp.float32
        )
        ctx = jnp.zeros((1, ids.shape[1], family.unet.cross_attention_dim),
                        jnp.float32)
        added = None
        if family.unet.addition_embed_dim is not None:
            added = {
                "time_ids": jnp.zeros((1, 6), jnp.float32),
                "text_embeds": jnp.zeros(
                    (1, family.unet.addition_pooled_dim), jnp.float32
                ),
            }
        labels = (jnp.zeros((1,), jnp.int32)
                  if family.unet.num_class_embeds is not None else None)
        key, sub = jax.random.split(key)
        params["unet"] = jax.jit(
            lambda k, s, t, c, a, cl: unet.init(k, s, t, c, a,
                                                class_labels=cl)
        )(sub, latent, jnp.zeros((1,)), ctx, added, labels)
        key, sub = jax.random.split(key)
        params["vae"] = jax.jit(vae.init)(
            sub, jnp.zeros((1, 16, 16, family.vae.in_channels), jnp.float32)
        )
        return cls(
            family=family,
            model_name=model_name or f"random/{family.name}",
            tokenizers=tokenizers,
            text_encoders=text_encoders,
            unet=unet,
            vae=vae,
            params=params,
        )

    @classmethod
    def random_host(cls, family: ModelFamily | str, seed: int = 0,
                    model_name: str | None = None,
                    dtype: str = "bfloat16") -> "Components":
        """Random components built WITHOUT running any XLA program: module
        param shapes come from ``jax.eval_shape`` (abstract tracing) and
        the values from host numpy. For benchmarks on big families —
        on-device fp32 init of SDXL-class weights both exhausts a single
        chip's HBM and takes minutes of init-graph compilation; this path
        takes seconds and the FLOPs/memory traffic are identical to a
        converted checkpoint."""
        import numpy as np

        if isinstance(family, str):
            family = FAMILIES[family]
        text_encoders = [ClipTextEncoder(cfg) for cfg in family.text_encoders]
        tokenizers = [
            HashTokenizer(cfg.vocab_size, cfg.max_position_embeddings,
                          cfg.eos_token_id)
            for cfg in family.text_encoders
        ]
        unet = UNet(family.unet)
        vae = AutoencoderKL(family.vae)

        rng = np.random.default_rng(seed)
        shapes = abstract_params(family)
        params = {module: materialize_host(tree, rng, dtype)
                  for module, tree in shapes.items()}
        return cls(
            family=family,
            model_name=model_name or f"random/{family.name}",
            tokenizers=tokenizers,
            text_encoders=text_encoders,
            unet=unet,
            vae=vae,
            params=params,
        )

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str | Path,
                        model_name: str | None = None,
                        family: ModelFamily | str | None = None) -> "Components":
        from chiaswarm_tpu.convert.torch_to_flax import load_checkpoint

        checkpoint_dir = Path(checkpoint_dir)
        model_name = model_name or checkpoint_dir.name
        if family is None:
            family = get_family(model_name)
        elif isinstance(family, str):
            family = FAMILIES[family]
        params = load_checkpoint(checkpoint_dir, family)
        text_encoders = [ClipTextEncoder(cfg) for cfg in family.text_encoders]
        tokenizers = [
            load_tokenizer(checkpoint_dir, cfg.vocab_size, cfg.eos_token_id,
                           cfg.max_position_embeddings)
            for cfg in family.text_encoders
        ]
        return cls(
            family=family,
            model_name=model_name,
            tokenizers=tokenizers,
            text_encoders=text_encoders,
            unet=UNet(family.unet),
            vae=AutoencoderKL(family.vae),
            params=params,
        )

    def param_bytes(self) -> int:
        return measured_param_bytes(self.params)


@dataclasses.dataclass
class ControlNetBundle:
    """A ControlNet checkpoint attachable to a base-family pipeline.

    The reference loads a ``ControlNetModel`` next to the pipeline per job
    (swarm/diffusion/diffusion_func.py:29-34); here the bundle is resident
    and LRU-cached like every other param tree (node/registry.py). The
    ``params`` dict holds two trees: ``net`` (the control branch) and
    ``embed`` (the conditioning-image hint encoder, hoisted out of the
    denoise scan by the pipeline).
    """

    family: ModelFamily
    model_name: str
    params: dict[str, Any]  # keys: net, embed

    @classmethod
    def random(cls, family: ModelFamily | str, seed: int = 0,
               model_name: str | None = None) -> "ControlNetBundle":
        from chiaswarm_tpu.models.controlnet import (
            ControlCondEmbedding,
            ControlNet,
        )

        if isinstance(family, str):
            family = FAMILIES[family]
        cfg = family.unet
        key = jax.random.PRNGKey(seed)
        net = ControlNet(cfg)
        embed = ControlCondEmbedding(cfg.block_out_channels[0],
                                     downscale=family.vae.downscale)
        f = family.vae.downscale
        lh = lw = 8
        latent = jnp.zeros((1, lh, lw, cfg.sample_channels), jnp.float32)
        cond = jnp.zeros((1, lh * f, lw * f, 3), jnp.float32)
        ctx = jnp.zeros((1, 77, cfg.cross_attention_dim), jnp.float32)
        added = None
        if cfg.addition_embed_dim is not None:
            added = {
                "time_ids": jnp.zeros((1, 6), jnp.float32),
                "text_embeds": jnp.zeros(
                    (1, cfg.addition_pooled_dim), jnp.float32),
            }
        key, k1, k2 = jax.random.split(key, 3)
        params = {
            "embed": jax.jit(embed.init)(k1, cond),
        }
        cond_emb = embed.apply(params["embed"], cond)
        params["net"] = jax.jit(net.init)(
            k2, latent, jnp.zeros((1,)), ctx, cond_emb, added
        )
        return cls(family=family,
                   model_name=model_name or f"random/controlnet-{family.name}",
                   params=params)

    @classmethod
    def random_host(cls, family: ModelFamily | str, seed: int = 0,
                    model_name: str | None = None,
                    dtype: str = "bfloat16") -> "ControlNetBundle":
        """Host-materialized random bundle (see ``materialize_host``) —
        benchmarks attach SDXL-class control branches without an on-device
        init program."""
        import numpy as np

        from chiaswarm_tpu.models.controlnet import (
            ControlCondEmbedding,
            ControlNet,
        )

        if isinstance(family, str):
            family = FAMILIES[family]
        cfg = family.unet
        net = ControlNet(cfg)
        embed = ControlCondEmbedding(cfg.block_out_channels[0],
                                     downscale=family.vae.downscale)
        f = family.vae.downscale
        lh = lw = 8
        latent = jnp.zeros((1, lh, lw, cfg.sample_channels), jnp.float32)
        cond = jnp.zeros((1, lh * f, lw * f, 3), jnp.float32)
        ctx = jnp.zeros((1, 77, cfg.cross_attention_dim), jnp.float32)
        added = None
        if cfg.addition_embed_dim is not None:
            added = {
                "time_ids": jnp.zeros((1, 6), jnp.float32),
                "text_embeds": jnp.zeros(
                    (1, cfg.addition_pooled_dim), jnp.float32),
            }
        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(0)
        params = {"embed": materialize_host(
            jax.eval_shape(embed.init, key, cond), rng, dtype)}
        cond_emb_shape = jax.eval_shape(
            lambda p, c: embed.apply(p, c), params["embed"], cond)
        cond_emb = jnp.zeros(cond_emb_shape.shape, cond_emb_shape.dtype)
        params["net"] = materialize_host(
            jax.eval_shape(net.init, key, latent, jnp.zeros((1,)), ctx,
                           cond_emb, added), rng, dtype)
        return cls(family=family,
                   model_name=model_name or f"random/controlnet-{family.name}",
                   params=params)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str | Path,
                        model_name: str | None = None,
                        family: ModelFamily | str | None = None,
                        ) -> "ControlNetBundle":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_controlnet,
            read_torch_weights,
        )

        checkpoint_dir = Path(checkpoint_dir)
        if (checkpoint_dir / "controlnet").is_dir():  # full pipeline snapshot
            checkpoint_dir = checkpoint_dir / "controlnet"
        model_name = model_name or checkpoint_dir.name
        if family is None:
            family = get_family(model_name)
        elif isinstance(family, str):
            family = FAMILIES[family]
        state = read_torch_weights(checkpoint_dir)
        return cls(family=family, model_name=model_name,
                   params=convert_controlnet(state, family.unet))

    def param_bytes(self) -> int:
        return measured_param_bytes(self.params)
