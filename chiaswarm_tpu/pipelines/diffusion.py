"""The unified diffusion pipeline: txt2img / img2img / inpaint in ONE jitted
program.

The reference runs four diffusers pipeline classes for these modes, chosen by
server-sent class names (swarm/job_arguments.py:104-151) and executed at
swarm/diffusion/diffusion_func.py:96. TPU-first redesign: one compiled
executable per (family, batch, size, steps, mode) bucket containing the whole
flow — text encode -> (optional) init-latent prep -> lax.scan denoise loop
with classifier-free guidance -> VAE decode. No host round-trips inside; the
only host work is tokenization and uint8 conversion.

Modes fold into static booleans:
- txt2img: no init latents (pure noise at sigma_max)
- img2img: init latents + noise at sigma[start] (strength -> start index,
  mirroring the reference's strength semantics)
- inpaint: img2img + per-step known-region re-projection (model-agnostic
  "legacy" inpainting; 9-channel inpaint checkpoints plug in via family
  config sample_channels)

Guidance scale rides as a *traced* scalar so changing it never recompiles.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import (
    toplevel_jit,
    GLOBAL_CACHE,
    bucket_batch,
    bucket_image_size,
    static_cache_key,
)
from chiaswarm_tpu.obs import numerics as _numerics
from chiaswarm_tpu.obs import trace as obs_trace
from chiaswarm_tpu.obs.profiling import annotate
from chiaswarm_tpu.obs.trace import span
from chiaswarm_tpu.parallel.context import seq_parallel_wrap
from chiaswarm_tpu.convert.quantize import (
    dequantize_tree,
    fake_quant_activation,
)
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.vae import AutoencoderKL
from chiaswarm_tpu.pipelines.components import Components
from chiaswarm_tpu.schedulers import (
    SamplerConfig,
    SamplingSchedule,
    make_noise_schedule,
    make_sampling_schedule,
    reproject_known,
    reproject_known_rows,
    resolve,
    sampler_step,
    sampler_step_rows,
    scale_model_input,
    scale_model_input_rows,
)
from chiaswarm_tpu.obs.metrics import (
    STEPPER_UNET_EVAL_MODES,
    steps_skipped_counter,
    unet_evals_counter,
    unet_evals_per_image_histogram,
)
from chiaswarm_tpu.schedulers.common import ScheduleConfig
from chiaswarm_tpu.schedulers.sampling import SamplerState, init_sampler_state

# ---- step collapse: DeepCache feature reuse (ISSUE 12) -----------------
#
# The denoise loop's dominant cost is steps x full-UNet. DeepCache (Ma
# et al. 2023) observes that the DEEP UNet features change slowly across
# adjacent steps: on designated steps the deep blocks are skipped and
# their cached activation is replayed, only the shallow level-0 blocks
# recompute (models/unet.py documents the seam). Master switch is
# ``CHIASWARM_DEEPCACHE``; the schedule itself is PER JOB
# (``GenerateRequest.reuse_schedule`` / the job's ``reuse_schedule``
# parameter) and rides as a TRACED table, so changing it never
# recompiles — the executable is keyed only by the static ``reuse``
# flag, and with the env off the lowered program is byte-identical to
# the pre-reuse build (the PR-11 taps-off gate pattern).

ENV_DEEPCACHE = "CHIASWARM_DEEPCACHE"

#: step-collapse observability (obs/metrics.py, ISSUE 12): per-row UNet
#: evaluations by mode, deep-blocks-skipped steps, and the per-image
#: full-eval histogram — pre-seeded so dashboards see zeroes from the
#: first scrape (the ISSUE-6 convention)
_UNET_EVALS = unet_evals_counter()
_STEPS_SKIPPED = steps_skipped_counter()
_EVALS_PER_IMAGE = unet_evals_per_image_histogram()
for _mode in STEPPER_UNET_EVAL_MODES:
    _UNET_EVALS.inc(0, mode=_mode)
_STEPS_SKIPPED.inc(0)


def deepcache_enabled() -> bool:
    """DeepCache feature reuse is OPT-IN (quality-gated like int8
    weights, ISSUE 8): with the env unset/off every per-job
    ``reuse_schedule`` is ignored and the compiled programs are the
    pre-reuse builds bit for bit."""
    return os.environ.get(ENV_DEEPCACHE, "").strip().lower() in (
        "1", "true", "on", "yes")


def normalize_reuse_schedule(steps: int, schedule: Iterable[int] | str,
                             start_step: int = 0) -> tuple[int, ...]:
    """Canonicalize a per-job DeepCache reuse schedule.

    Accepts an iterable of ladder indices (the steps whose deep blocks
    replay the cache) or the compact cadence form ``"every:N"`` —
    refresh the cache every Nth executed step, reuse the rest (N=3
    skips 2 of every 3 deep passes). Indices must lie strictly inside
    ``(start_step, steps)``: the first executed step has no cache to
    reuse, and out-of-range indices are a caller error, not a silent
    no-op. Returns a sorted, deduplicated tuple — the canonical form
    checkpoints record and resume validation compares
    (serving/stepper.py::_validate_resume)."""
    if isinstance(schedule, str):
        text = schedule.strip().lower()
        if not text.startswith("every:"):
            raise ValueError(
                f"reuse_schedule string must be 'every:N', got "
                f"{schedule!r}")
        try:
            cadence = int(text.split(":", 1)[1])
        except ValueError as exc:
            raise ValueError(
                f"reuse_schedule cadence in {schedule!r} is not an "
                f"integer") from exc
        if cadence < 2:
            raise ValueError("reuse cadence must be >= 2 (1 would never "
                             "refresh the cache)")
        schedule = [i for i in range(start_step + 1, steps)
                    if (i - start_step) % cadence != 0]
    try:
        out = sorted({int(i) for i in schedule})
    except (TypeError, ValueError) as exc:
        # a bare int / None entries must stay a ValueError: the lane
        # path converts ValueError to LaneReject and the solo path's
        # canonical user error is classified fatal-bad-request — a
        # TypeError here would escape into the breaker taxonomy and
        # let K malformed requests quarantine a healthy model
        raise ValueError(
            f"reuse_schedule must be 'every:N' or an iterable of "
            f"ladder indices, got {schedule!r}") from exc
    for i in out:
        if not start_step < i < steps:
            raise ValueError(
                f"reuse step {i} outside the executed ladder "
                f"({start_step}, {steps}) — the first executed step "
                f"must run the full UNet to fill the cache")
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class GenerateRequest:
    """One generation request (pre-normalized by the node dispatcher).

    ``prompt``/``negative_prompt`` may be a tuple of per-ROW prompts
    (length == ``batch``) — the coalesced-jobs path rides different
    hive jobs on one batched program (node/executor.py). When
    ``sample_seed_rows`` is set, row b's noise key is
    ``fold_in(key_for_seed(seed_b), row_b)`` — exactly what that row
    would get in its own solo job — instead of deriving every row from
    ``seed``.
    """

    prompt: str | tuple[str, ...]
    negative_prompt: str | tuple[str, ...] = ""
    steps: int = 30
    guidance_scale: float = 7.5
    height: int = 512
    width: int = 512
    batch: int = 1
    seed: int = 0
    scheduler: str | None = None  # diffusers class name from the hive
    # per-row (seed, row-index) pairs, length == batch (coalesced jobs)
    sample_seed_rows: tuple[tuple[int, int], ...] | None = None
    # explicit standard-normal initial noise (B|1, H/f, W/f, C): replaces
    # the per-row drawn noise so a fixed-latent render can be compared
    # image-for-image against an external reference (diffusers golden,
    # tests/test_real_checkpoint.py). Deterministic samplers (DDIM/DPM)
    # then walk the exact same trajectory.
    init_noise: np.ndarray | None = None
    # img2img / inpaint
    init_image: np.ndarray | None = None   # (H, W, 3) uint8 or float [-1,1]
    strength: float = 0.8
    mask: np.ndarray | None = None         # (H, W) float, 1 = regenerate
    # coalesced img2img/inpaint: ``init_image`` is a per-JOB (J, H, W, 3)
    # stack (``mask`` a per-JOB (J, H, W) stack) and init_groups[j] =
    # (encode_seed, n_rows) — job j's image is VAE-encoded with ITS OWN
    # seed through the same batch-1 executable its solo run uses (bitwise
    # solo equality by construction), then repeated over its rows
    init_groups: tuple[tuple[int, int], ...] | None = None
    tiled_decode: bool = False
    # ControlNet (swarm/diffusion/diffusion_func.py:29-39)
    controlnet: Any = None                 # ControlNetBundle
    control_image: np.ndarray | None = None  # (H, W, 3) conditioning image
    control_scale: float = 1.0             # traced; never recompiles
    # instruct-pix2pix dual guidance (image_conditioned families)
    image_guidance_scale: float = 1.5      # traced; never recompiles
    # DeepCache step-level feature reuse (ISSUE 12): ladder indices
    # whose deep UNet blocks replay the cached activation, or the
    # "every:N" cadence form — see normalize_reuse_schedule. Ignored
    # unless CHIASWARM_DEEPCACHE is on; rides as a traced table, so
    # per-job schedules never recompile.
    reuse_schedule: tuple[int, ...] | str | None = None


def _make_text_encode(text_encoders):
    """Trace-time text-encode over a tuple of encoder modules — shared by
    the solo generate program and the step scheduler's context-encode
    executable so both produce identical embeddings for a row."""
    def encode_text(params, ids_list):
        seqs, pooled = [], None
        for i, te in enumerate(text_encoders):
            seq, pool = te.apply(params[f"text_encoder_{i}"], ids_list[i])
            seqs.append(seq)
            pooled = pool  # SDXL: pooled comes from the last encoder
        return (jnp.concatenate(seqs, axis=-1)
                if len(seqs) > 1 else seqs[0]), pooled

    return encode_text


def _params_mesh(params):
    """The dp x tp mesh the params are sharded over, or None (single-chip
    or unsharded)."""
    from jax.sharding import NamedSharding

    for leaf in jax.tree.leaves(params):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding) and "data" in s.mesh.shape \
                and s.mesh.devices.size > 1:
            return s.mesh
    return None


def img2img_start_index(steps: int, strength: float) -> int:
    """img2img strength -> denoise start index, the ONE quantization
    (clip to [0.05, 1], round, never past the last step). Shared by the
    solo program (below), the lane scheduler (serving/stepper.py) and
    the ticket's observable ``denoise_steps`` (workloads/diffusion.py)
    — resume validation keys on this value, so a drift between call
    sites would force spurious clean restarts."""
    strength = float(np.clip(strength, 0.05, 1.0))
    return min(int(round(steps * (1.0 - strength))), steps - 1)


def latent_mask(mask: np.ndarray, lh: int, lw: int,
                downscale: int) -> np.ndarray:
    """Arbitrary-size inpaint mask -> binarized (lh, lw) latent-grid mask
    (1 = regenerate). Shared by the solo generate program's prep and the
    lane admission path (serving/stepper.py) so an inpaint row's mask
    quantization is identical wherever the job runs."""
    mask = np.asarray(mask, dtype=np.float32)
    if mask.shape != (lh, lw):
        if mask.shape != (lh * downscale, lw * downscale):
            # bring arbitrary mask sizes onto the bucketed pixel grid
            from PIL import Image

            mask = np.asarray(Image.fromarray(
                (mask * 255).clip(0, 255).astype(np.uint8)
            ).resize((lw * downscale, lh * downscale), Image.NEAREST),
                dtype=np.float32) / 255.0
        # downsample to the latent grid by box-averaging
        mask = mask.reshape(lh, downscale, lw, downscale).mean((1, 3))
    return (mask > 0.5).astype(np.float32)


def _to_float_image(img: np.ndarray) -> np.ndarray:
    img = np.asarray(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 127.5 - 1.0
    return img.astype(np.float32)


def _resize_batch(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Host-side LANCZOS resize onto the bucketed grid (uint8 or float)."""
    from PIL import Image

    single = img.ndim == 3
    frames = img[None] if single else img
    as_u8 = frames.dtype == np.uint8
    out = []
    for frame in frames:
        if not as_u8:
            frame = ((frame + 1.0) * 127.5).clip(0, 255).astype(np.uint8)
        resized = np.asarray(Image.fromarray(frame).resize(
            (width, height), Image.LANCZOS))
        out.append(resized if as_u8 else
                   resized.astype(np.float32) / 127.5 - 1.0)
    stacked = np.stack(out)
    return stacked[0] if single else stacked


@dataclasses.dataclass
class PendingImages:
    """A dispatched (possibly still-executing) generate program's uint8
    output. ``wait()`` blocks on the device->host transfer and un-buckets
    back to the exact requested size."""

    device_images: Any
    compiled_hw: tuple[int, int]
    requested_hw: tuple[int, int]
    requested_batch: int

    def wait(self) -> np.ndarray:
        # the "decode" span: under async dispatch the denoise + VAE
        # decode + device->host transfer all settle HERE, so for a solo
        # job this is where the chip time shows in the trace
        with span("decode", batch=self.requested_batch):
            return self._wait()

    def _wait(self) -> np.ndarray:
        img_u8 = np.asarray(jax.device_get(self.device_images))
        height, width = self.compiled_hw
        req_h, req_w = self.requested_hw
        # un-bucket: scale-to-cover + center-crop back to the exact request
        # (plain resize would stretch when the bucket changed aspect ratio)
        if (height, width) != (req_h, req_w):
            from PIL import Image

            scale = max(req_h / height, req_w / width)
            rh, rw = (max(req_h, round(height * scale)),
                      max(req_w, round(width * scale)))
            y0, x0 = (rh - req_h) // 2, (rw - req_w) // 2
            img_u8 = np.stack([
                np.asarray(Image.fromarray(frame).resize(
                    (rw, rh), Image.LANCZOS))[y0:y0 + req_h, x0:x0 + req_w]
                for frame in img_u8
            ])
        return img_u8[: self.requested_batch]


class DiffusionPipeline:
    """Resident, compile-cached executor for one Components bundle."""

    def __init__(self, components: Components, attn_impl: str = "auto") -> None:
        self.c = components
        if attn_impl != components.unet.config.attn_impl and attn_impl != "auto":
            # modules are cheap static descriptions: rebuild the UNet with
            # the forced attention dispatch (param tree is unchanged)
            from chiaswarm_tpu.models.unet import UNet

            components.unet = UNet(
                dataclasses.replace(components.family.unet,
                                    attn_impl=attn_impl)
            )
        fam = components.family
        self.schedule_config = ScheduleConfig(
            beta_schedule=fam.beta_schedule,
            prediction_type=fam.prediction_type,
        )
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    # ---------- host-side helpers ----------

    def _tokenize(self, prompts: list[str]) -> list[np.ndarray]:
        return [tok.encode_batch(prompts) for tok in self.c.tokenizers]

    def _latent_hw(self, height: int, width: int) -> tuple[int, int]:
        f = self.c.family.vae.downscale
        return height // f, width // f

    # ---------- jitted core ----------

    def _build_fn(self, *, batch: int, height: int, width: int, steps: int,
                  start_step: int, sampler: SamplerConfig, use_cfg: bool,
                  has_init: bool, has_mask: bool, tiled: bool,
                  has_control: bool = False, has_noise: bool = False,
                  reuse: bool = False):
        # capture only the static module descriptions — NOT the Components
        # bundle, whose .params would otherwise stay pinned by the
        # executable-cache closure after the param LRU evicts them
        fam = self.c.family
        text_encoders = tuple(self.c.text_encoders)
        unet = self.c.unet
        vae = self.c.vae
        lh, lw = self._latent_hw(height, width)
        sched = make_sampling_schedule(self.noise_schedule, steps, sampler)
        needs_xl = fam.unet.addition_embed_dim is not None

        control_net = control_embed = None
        if has_control:
            from chiaswarm_tpu.models.controlnet import (
                ControlCondEmbedding,
                ControlNet,
            )

            control_net = ControlNet(fam.unet)
            control_embed = ControlCondEmbedding(
                fam.unet.block_out_channels[0],
                downscale=fam.vae.downscale)

        encode_text = _make_text_encode(text_encoders)

        pix2pix = fam.image_conditioned
        if reuse and (pix2pix or has_control):
            # dual-CFG conditioning and the ControlNet trunk both feed
            # the deep blocks per step — skipping those blocks while
            # still paying their conditioning is incoherent; submit()
            # never requests this combination
            raise ValueError("DeepCache reuse supports the plain "
                             "txt2img/img2img/inpaint programs only")

        def fn(params, ids, neg_ids, sample_keys, guidance, init_latent,
               mask, control_params, control_cond, control_scale,
               image_guidance, noise_override, reuse_tab=None):
            # int8 weight residency (convert/quantize.py): dequantize AT
            # USE, inside the traced program — HBM holds the int8 codes,
            # XLA fuses the casts into the consumers. No-op on fp trees.
            params = dequantize_tree(params)
            control_params = dequantize_tree(control_params)
            ctx, pooled = encode_text(params, ids)
            # swarmlens probes (ISSUE 11): identity unless the probe is
            # enabled via CHIASWARM_NUMERICS at trace time — the cache
            # key carries the tap fingerprint, so flipping the env can
            # never serve a tapped program from a taps-off slot
            ctx = _numerics.tap("diffusion.text_ctx", ctx)
            if pix2pix:
                # dual CFG rides a tripled batch: [uncond, image-only,
                # text+image] (timbrooks/instruct-pix2pix semantics; the
                # reference reaches it via the diffusers pipeline class)
                nctx, _ = encode_text(params, neg_ids)
                ctx = jnp.concatenate([nctx, nctx, ctx], axis=0)
            elif use_cfg:
                nctx, npooled = encode_text(params, neg_ids)
                ctx = jnp.concatenate([nctx, ctx], axis=0)
                if pooled is not None:
                    pooled = jnp.concatenate([npooled, pooled], axis=0)

            ctx = fake_quant_activation(ctx, tag="unet.ctx")

            added = None
            if needs_xl:
                time_ids = jnp.asarray(
                    [height, width, 0, 0, height, width], jnp.float32
                )[None, :].repeat(ctx.shape[0], axis=0)
                added = {"time_ids": time_ids,
                         "text_embeds": pooled[:, : fam.unet.addition_pooled_dim]}

            # per-SAMPLE noise streams: row b's noise depends only on its
            # own key, so image b is identical whether generated at
            # batch=1 or inside a larger batch (seed reproducibility is
            # batch-size-invariant — and the precondition for ever
            # coalescing different jobs into one batched program)
            def draw(keys):
                return jax.vmap(lambda k: jax.random.normal(
                    k, (lh, lw, fam.vae.latent_channels), jnp.float32)
                )(keys)

            both = jax.vmap(jax.random.split)(sample_keys)  # (B, 2, key)
            sample_keys, nkeys = both[:, 0], both[:, 1]
            noise = noise_override if has_noise else draw(nkeys)
            sigma_start = sched.sigmas[start_step]
            if pix2pix:
                # image latents condition via channel-concat (UNSCALED, the
                # pix2pix convention); generation starts from pure noise
                img_cond = init_latent / fam.vae.scaling_factor
                x = noise * sched.sigmas[0]
            elif has_init:
                x = init_latent + noise * sigma_start
            else:
                x = noise * sigma_start

            if has_mask:
                known = init_latent  # clean latents of the source image

            cond_emb = None
            if has_control:
                # hint embedding is timestep-independent: evaluate ONCE
                # here, outside the scan (diffusers recomputes per step)
                cond_emb = control_embed.apply(
                    control_params["embed"], control_cond)
                cond_emb = jnp.repeat(cond_emb, batch, axis=0)
                if use_cfg:
                    cond_emb = jnp.concatenate([cond_emb, cond_emb], axis=0)

            if reuse:
                # DeepCache carry: the deep activation for the (CFG-
                # expanded) batch + a validity flag. Both branches of the
                # lax.cond are compiled ONCE — the per-step reuse_tab
                # lookup selects at run time, so any schedule rides the
                # same executable and only the taken branch executes.
                cache0 = jnp.zeros(
                    ((2 * batch if use_cfg else batch), lh, lw,
                     fam.unet.block_out_channels[1]), unet.dtype)

                def unet_reuse_eval(inp_b, t_b, ctx_b, added_b, cache, ok,
                                    i):
                    reuse_now = jnp.logical_and(reuse_tab[i], ok)

                    def shallow(ops):
                        inp_b, t_b, cache = ops
                        out = unet.apply(params["unet"], inp_b, t_b,
                                         ctx_b, added_b,
                                         cached_deep=cache)
                        return out, cache

                    def full(ops):
                        inp_b, t_b, _cache = ops
                        return unet.apply(params["unet"], inp_b, t_b,
                                          ctx_b, added_b,
                                          return_deep=True)

                    out, cache = jax.lax.cond(reuse_now, shallow, full,
                                              (inp_b, t_b, cache))
                    return out, cache, jnp.ones((), bool)

            def body(carry, idx):
                if reuse:
                    x, state, carry_keys, cache, cache_ok = carry
                else:
                    x, state, carry_keys = carry
                i = idx + start_step
                inp = scale_model_input(sched, x, i)
                # low-precision activations (CHIASWARM_ACTIVATIONS,
                # default off = identity): the UNet block input for this
                # step — every branch below (pix2pix triple, CFG double,
                # solo) derives its batch from this tensor, so one seam
                # covers them all; the text context is quantized once
                # outside the scan
                inp = fake_quant_activation(inp, tag="unet.in")
                if pix2pix:
                    inp3 = jnp.concatenate([inp, inp, inp], axis=0)
                    img3 = jnp.concatenate(
                        [jnp.zeros_like(img_cond), img_cond, img_cond],
                        axis=0)
                    t3 = sched.timesteps[i][None].repeat(3 * batch, axis=0)
                    out = unet.apply(params["unet"],
                                     jnp.concatenate([inp3, img3], axis=-1),
                                     t3, ctx, added)
                    e_unc, e_img, e_full = jnp.split(out, 3, axis=0)
                    eps = (e_unc + image_guidance * (e_img - e_unc)
                           + guidance * (e_full - e_img))
                elif use_cfg:
                    inp2 = jnp.concatenate([inp, inp], axis=0)
                    t2 = sched.timesteps[i][None].repeat(2 * batch, axis=0)
                    down_res = mid_res = None
                    if has_control:
                        down_res, mid_res = control_net.apply(
                            control_params["net"], inp2, t2, ctx, cond_emb,
                            added, control_scale)
                    if reuse:
                        out, cache, cache_ok = unet_reuse_eval(
                            inp2, t2, ctx, added, cache, cache_ok, i)
                    else:
                        out = unet.apply(params["unet"], inp2, t2, ctx,
                                         added, down_res, mid_res)
                    eps_u, eps_c = jnp.split(out, 2, axis=0)
                    eps = eps_u + guidance * (eps_c - eps_u)
                else:
                    t1 = sched.timesteps[i][None].repeat(batch, axis=0)
                    down_res = mid_res = None
                    if has_control:
                        down_res, mid_res = control_net.apply(
                            control_params["net"], inp, t1, ctx, cond_emb,
                            added, control_scale)
                    if reuse:
                        eps, cache, cache_ok = unet_reuse_eval(
                            inp, t1, ctx, added, cache, cache_ok, i)
                    else:
                        eps = unet.apply(params["unet"], inp, t1, ctx,
                                         added, down_res, mid_res)
                eps = _numerics.tap("diffusion.eps", eps, step=i)
                keys, skeys = jax.vmap(
                    lambda k: tuple(jax.random.split(k)))(carry_keys)
                step_noise = draw(skeys)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=step_noise,
                                        start_index=start_step)
                if has_mask:
                    # re-project known region onto the next noise level
                    keys, mkeys = jax.vmap(
                        lambda k: tuple(jax.random.split(k)))(keys)
                    renoise = draw(mkeys)
                    x = reproject_known(sched, i, x, known, mask, renoise)
                # the scheduler carry: the value the next step consumes
                x = _numerics.tap("diffusion.latents", x, step=i)
                if reuse:
                    return (x, state, keys, cache, cache_ok), None
                return (x, state, keys), None

            n_steps = steps - start_step
            carry0 = ((x, init_sampler_state(x), sample_keys, cache0,
                       jnp.zeros((), bool)) if reuse
                      else (x, init_sampler_state(x), sample_keys))
            carry_out, _ = jax.lax.scan(body, carry0, jnp.arange(n_steps))
            x = carry_out[0]
            x = _numerics.tap("diffusion.final_latents", x)

            if tiled:
                from chiaswarm_tpu.models.vae import tiled_decode

                img = tiled_decode(vae, params["vae"], x)
            else:
                img = vae.apply(params["vae"], x,
                                method=AutoencoderKL.decode)
            # quantize ON DEVICE: the host link (a tunnel on dev pods, PCIe
            # otherwise) moves 4x fewer bytes as uint8 — at 1024px this is
            # worth ~0.5s/image end-to-end
            return _numerics.tap(
                "diffusion.image_u8",
                (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                 ).astype(jnp.uint8))

        # seq>1 param meshes trace under the sequence-parallel context so
        # ops.attention routes the large spatial self-attentions through
        # the ppermute ring (parallel/ring_attention.py)
        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static: Any):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "generate", static),
            lambda: self._build_fn(**static)
        )

    # ---------- public API ----------

    def encode_init_image(self, image: np.ndarray, height: int, width: int,
                          seed: int) -> jnp.ndarray:
        """Host image(s) -> scaled latents (the img2img/inpaint init).

        Accepts (H, W, 3) for one shared init or (B, H, W, 3) for per-item
        inits (video frames riding the batch axis, workloads/video.py).

        COMPILED: an eager ``vae.apply`` dispatches hundreds of tiny ops
        per call — on a tunneled chip that alone costs seconds per
        img2img job (the r2 bench regression). The executable rides the
        global LRU like every other program (thread-safe, evictable) and
        the batch is padded to the pow2 compile bucket so per-frame-count
        vid2vid chunks cannot fan out executables; the module closure
        carries no params (they pass as an argument, so the param LRU
        can still evict the tree)."""
        img = _to_float_image(image)
        if img.ndim == 3:
            img = img[None]
        if img.shape[1:3] != (height, width):
            raise ValueError(
                f"init image {img.shape[1:3]} != requested {(height, width)}; "
                "resize on host first (node.job_args does this)"
            )
        n = img.shape[0]
        bucket = bucket_batch(n)
        if n < bucket:
            img = np.concatenate(
                [img, np.repeat(img[-1:], bucket - n, axis=0)], axis=0)
        vae = self.c.vae
        fn = GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "encode",
                             {"batch": bucket, "height": height,
                              "width": width}),
            lambda: toplevel_jit(
                lambda params, x, key: vae.apply(
                    dequantize_tree(params), x, key,
                    method=AutoencoderKL.encode)))
        z = fn(self.c.params["vae"], jnp.asarray(img), key_for_seed(seed))
        return z[:n]

    # ---------- step-scheduler executables (serving/stepper.py) ----------
    #
    # Continuous step-level batching decomposes the solo generate program
    # into four resident executables per lane bucket: context encode, row
    # init (initial noise draw), ONE denoise step over the whole lane
    # (per-row timesteps/sigmas — rows at different progress coexist),
    # and VAE decode for retiring rows. All four ride the global
    # executable LRU, so admitting a row never compiles anything: the
    # lane-program count is bounded by the (batch, size, steps-capacity,
    # sampler) buckets alone.

    def stepper_encode_fn(self, *, batch: int):
        """(params, ids, neg_ids) -> (ctx_u, ctx_c, pooled_u, pooled_c)
        for ``batch`` rows — the admission-time text encode. Same
        per-row math as the solo program's in-trace encode."""
        text_encoders = tuple(self.c.text_encoders)

        def build():
            encode_text = _make_text_encode(text_encoders)

            def fn(params, ids, neg_ids):
                params = dequantize_tree(params)
                ctx_c, pooled_c = encode_text(params, ids)
                ctx_u, pooled_u = encode_text(params, neg_ids)
                return ctx_u, ctx_c, pooled_u, pooled_c

            return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "stepper_encode",
                             {"batch": batch}), build)

    def stepper_row_init_fn(self, *, batch: int, height: int, width: int):
        """(sample_keys, sigma0) -> (carry_keys, x0): the initial split +
        noise draw for freshly admitted rows. Identical to the solo
        program's prologue (split, draw, scale by sigma[start]), so a
        spliced row starts on exactly its solo trajectory."""
        fam = self.c.family
        lh, lw = self._latent_hw(height, width)

        def build():
            def fn(sample_keys, sigma0):
                both = jax.vmap(jax.random.split)(sample_keys)
                carry, nkeys = both[:, 0], both[:, 1]
                noise = jax.vmap(lambda k: jax.random.normal(
                    k, (lh, lw, fam.vae.latent_channels), jnp.float32)
                )(nkeys)
                return carry, noise * sigma0.reshape(-1, 1, 1, 1)

            return toplevel_jit(fn)

        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "stepper_init",
                             {"batch": batch, "height": height,
                              "width": width}), build)

    def stepper_step_fn(self, *, batch: int, height: int, width: int,
                        steps_cap: int, sampler: SamplerConfig,
                        has_control: bool = False, reuse: bool = False):
        """ONE denoise step over a full lane of ``batch`` rows.

        Per-row traced state: latents, carry keys, step index, start
        index, sigma/timestep tables (each row owns its ladder, padded to
        ``steps_cap``), guidance scale, multistep history, active mask —
        and, since ISSUE 7, the image-mode row state: ``known`` (clean
        source latents), ``mask`` (latent-grid inpaint mask) and
        ``mask_on`` (per-row flag selecting the inpaint re-projection).
        Inpaint math is always compiled in and selected per ROW: rows
        without a mask keep the txt2img/img2img carry-key trajectory
        bit-for-bit (the second key split is computed but discarded), so
        txt2img, img2img (nonzero per-row start index) and inpaint rows
        share one lane program. Inactive (padding / retired) rows
        compute and are discarded by the active mask — their carries
        freeze, so a row admitted into their slot later starts clean.
        Classifier-free guidance is always compiled in; per-row guidance
        rides as a traced vector.

        ``has_control`` compiles the ControlNet branch in: the lane then
        additionally takes the bundle's params, a per-row pre-embedded
        hint stack (``stepper_control_embed_fn``) and a per-row
        conditioning-scale vector. Control lanes are keyed by bundle
        (serving/stepper.py), so every row shares the branch params
        while conditioning images/scales stay per row.

        ``reuse`` compiles the DeepCache branch in (ISSUE 12): the lane
        additionally carries per-row cached deep activations (uncond +
        cond halves) and takes a scalar ``reuse_now`` flag the DRIVER
        decides host-side — True only when every active row's schedule
        wants reuse at its current step AND holds a valid cache (so the
        lax.cond stays a scalar branch the compiled program executes
        one side of; mixed lanes degrade to full evals, never to wrong
        math). Reuse lanes are keyed separately, so with the env off
        every lane runs this program's pre-reuse build unchanged.
        """
        fam = self.c.family
        unet = self.c.unet
        lh, lw = self._latent_hw(height, width)
        needs_xl = fam.unet.addition_embed_dim is not None
        if reuse and has_control:
            raise ValueError("DeepCache reuse lanes do not take the "
                             "ControlNet branch")

        control_net = None
        if has_control:
            from chiaswarm_tpu.models.controlnet import ControlNet

            control_net = ControlNet(fam.unet)

        def build():
            def fn(params, ctx_u, ctx_c, pooled_u, pooled_c, x, carry_keys,
                   idx, start_idx, sigmas_tab, ts_tab, guidance,
                   old_denoised, active, known, mask, mask_on,
                   control_params, cond, cscale,
                   cache_u=None, cache_c=None, reuse_now=None):
                params = dequantize_tree(params)
                control_params = dequantize_tree(control_params)
                sched_rows = SamplingSchedule(sigmas=sigmas_tab,
                                              timesteps=ts_tab)
                inp = scale_model_input_rows(sched_rows, x, idx)
                t = jax.vmap(lambda ts, i: ts[i])(ts_tab, idx)
                ctx = jnp.concatenate([ctx_u, ctx_c], axis=0)
                inp2 = jnp.concatenate([inp, inp], axis=0)
                t2 = jnp.concatenate([t, t], axis=0)
                added = None
                if needs_xl:
                    time_ids = jnp.asarray(
                        [height, width, 0, 0, height, width], jnp.float32
                    )[None, :].repeat(2 * batch, axis=0)
                    pooled = jnp.concatenate([pooled_u, pooled_c], axis=0)
                    added = {"time_ids": time_ids,
                             "text_embeds":
                                 pooled[:, : fam.unet.addition_pooled_dim]}
                down_res = mid_res = None
                if has_control:
                    # per-row conditioning: hint embeddings and scales are
                    # row state; the scale broadcasts (2B,1,1,1) over the
                    # zero-conv residuals — scalar-scale solo math per row
                    cond2 = jnp.concatenate([cond, cond], axis=0)
                    scale2 = jnp.concatenate(
                        [cscale, cscale]).reshape(-1, 1, 1, 1)
                    down_res, mid_res = control_net.apply(
                        control_params["net"], inp2, t2, ctx, cond2,
                        added, scale2)
                if reuse:
                    cache2 = jnp.concatenate([cache_u, cache_c], axis=0)

                    def shallow(ops):
                        inp2, t2, cache2 = ops
                        out = unet.apply(params["unet"], inp2, t2, ctx,
                                         added, cached_deep=cache2)
                        return out, cache2

                    def full(ops):
                        inp2, t2, _cache2 = ops
                        return unet.apply(params["unet"], inp2, t2, ctx,
                                          added, return_deep=True)

                    out, cache2 = jax.lax.cond(reuse_now, shallow, full,
                                               (inp2, t2, cache2))
                    cache_u_next, cache_c_next = jnp.split(cache2, 2,
                                                           axis=0)
                else:
                    out = unet.apply(params["unet"], inp2, t2, ctx, added,
                                     down_res, mid_res)
                eps_u, eps_c = jnp.split(out, 2, axis=0)
                # per-row CFG combine; guidance <= 1 selects the pure
                # conditional prediction — the CFG-free few-step mode
                # (lcm rows, schedulers/sampling.py FEWSTEP_KINDS).
                # For guidance > 1 the selected value is the identical
                # expression as before, so existing rows keep their
                # solo trajectories bit for bit.
                g = guidance.reshape(-1, 1, 1, 1)
                eps = jnp.where(g > 1.0, eps_u + g * (eps_c - eps_u),
                                eps_c)
                both = jax.vmap(jax.random.split)(carry_keys)
                keys, skeys = both[:, 0], both[:, 1]
                step_noise = jax.vmap(lambda k: jax.random.normal(
                    k, (lh, lw, fam.vae.latent_channels), jnp.float32)
                )(skeys)
                x_next, state = sampler_step_rows(
                    sampler, sched_rows, idx, x, eps,
                    SamplerState(old_denoised=old_denoised),
                    step_noise, start_idx)
                # inpaint re-projection, selected per row: the masked
                # variant (and its second key split) is computed for
                # every row, applied only where mask_on — unmasked rows
                # keep the single-split solo trajectory
                both_m = jax.vmap(jax.random.split)(keys)
                keys_m, mkeys = both_m[:, 0], both_m[:, 1]
                renoise = jax.vmap(lambda k: jax.random.normal(
                    k, (lh, lw, fam.vae.latent_channels), jnp.float32)
                )(mkeys)
                x_masked = reproject_known_rows(
                    sched_rows, idx, x_next, known, mask, renoise)
                m_img = mask_on.reshape(-1, 1, 1, 1)
                x_next = jnp.where(m_img, x_masked, x_next)
                keys = jnp.where(mask_on.reshape(-1, 1), keys_m, keys)
                act = active.reshape(-1, 1, 1, 1)
                x_next = jnp.where(act, x_next, x)
                new_old = jnp.where(act, state.old_denoised, old_denoised)
                keys = jnp.where(active.reshape(-1, 1), keys, carry_keys)
                idx_next = idx + active.astype(idx.dtype)
                if reuse:
                    return (x_next, keys, idx_next, new_old,
                            cache_u_next, cache_c_next)
                return x_next, keys, idx_next, new_old

            return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

        # the reuse flag joins the static key only when set, so every
        # pre-existing lane bucket keeps its historical key (and cached
        # executable) byte for byte
        statics = {"batch": batch, "height": height,
                   "width": width, "steps_cap": steps_cap,
                   "sampler": sampler, "has_control": has_control}
        if reuse:
            statics["reuse"] = True
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "stepper_step", statics), build)

    def stepper_control_embed_fn(self, *, height: int, width: int):
        """(embed_params, cond (1, H, W, 3) in [0, 1]) -> (1, lh, lw, C0)
        hint embedding — the admission-time ControlNet prep. The embedder
        is timestep-independent, so each job's conditioning image is
        embedded ONCE here (exactly the solo program's hoisting) and the
        result rides per row as lane state."""
        fam = self.c.family

        def build():
            from chiaswarm_tpu.models.controlnet import ControlCondEmbedding

            control_embed = ControlCondEmbedding(
                fam.unet.block_out_channels[0],
                downscale=fam.vae.downscale)

            def fn(embed_params, cond):
                return control_embed.apply(dequantize_tree(embed_params),
                                           cond)

            return toplevel_jit(fn)

        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "stepper_ctrl_embed",
                             {"height": height, "width": width}), build)

    def stepper_decode_fn(self, *, batch: int, height: int, width: int):
        """Latents -> uint8 images for retiring rows — dispatched
        asynchronously so the transfer/decode of finished rows overlaps
        the lane's ongoing UNet steps."""
        vae = self.c.vae

        def build():
            def fn(params, x):
                params = dequantize_tree(params)
                img = vae.apply(params["vae"], x,
                                method=AutoencoderKL.decode)
                return (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                        ).astype(jnp.uint8)

            return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "stepper_decode",
                             {"batch": batch, "height": height,
                              "width": width}), build)

    def __call__(self, req: GenerateRequest) -> tuple[np.ndarray, dict]:
        """Run a request. Returns (images uint8 (B,H,W,3), config dict)."""
        pending, config = self.submit(req)
        return pending.wait(), config

    def submit(self, req: GenerateRequest) -> tuple["PendingImages", dict]:
        """Dispatch a request WITHOUT blocking on the device->host image
        transfer. JAX's async dispatch returns the uint8 result array as a
        future; ``PendingImages.wait()`` fetches it. Submitting job N+1
        before waiting on job N overlaps N's ~0.2 s host transfer with
        N+1's denoise compute. bench.py measures this steady-state number
        directly; the serving loop gets the same overlap from depth-2
        slots (core/chip_pool.py MeshSlot.depth + node/worker.py
        _slot_worker), where two blocking jobs interleave across threads.
        No reference analog — torch blocks per pipeline call."""
        fam = self.c.family
        # span shape for a solo job (chiaswarm_tpu/obs): "encode" =
        # host-side prep (tokenize, init-image VAE encode, masks),
        # "step" = executable lookup (a cold compile lands here,
        # visibly) + program dispatch; the device compute itself settles
        # in the consumer's "decode" span (PendingImages.wait) because
        # dispatch is async
        parent = obs_trace.current_span()
        enc_span = (parent.child("encode", batch=req.batch)
                    if parent is not None else None)
        try:
            # small sizes are honored like the reference (only a max clamp,
            # swarm/job_arguments.py:96-102): a 192px request generates AT
            # 192px rather than at a 256 floor and downscaled
            height, width = bucket_image_size(req.height, req.width)
            batch = bucket_batch(req.batch)
            steps = max(int(req.steps), 1)
            sampler = resolve(req.scheduler,
                              prediction_type=fam.prediction_type)
            use_cfg = req.guidance_scale > 1.0
            has_init = req.init_image is not None
            has_mask = req.mask is not None
            if has_mask and not has_init:
                raise ValueError("inpainting requires an init image with the mask")
            if fam.image_conditioned:
                if not has_init:
                    raise ValueError(
                        "this model edits an input image; start_image_uri is "
                        "required")
                if has_mask:
                    raise ValueError(
                        "instruct-pix2pix models do not take a mask")
                if req.controlnet is not None:
                    raise ValueError(
                        "instruct-pix2pix models do not support controlnet")

            start_step = 0
            init_latent = jnp.zeros((1,), jnp.float32)  # placeholder
            mask_arr = jnp.zeros((1,), jnp.float32)
            if has_init:
                if not has_mask and not fam.image_conditioned:
                    # img2img: skip the first (1-strength) of the ladder
                    # (pix2pix starts from pure noise instead)
                    start_step = img2img_start_index(steps, req.strength)
                init = np.asarray(req.init_image)
                if init.ndim == 4 and init.shape[1:3] != (height, width) or \
                   init.ndim == 3 and init.shape[:2] != (height, width):
                    init = _resize_batch(init, height, width)
                if req.init_groups is not None:
                    # coalesced jobs: encode each job's image with ITS seed
                    # through the batch-1 executable its solo run uses, then
                    # repeat over that job's rows — bitwise solo equality
                    z = jnp.concatenate([
                        jnp.repeat(self.encode_init_image(
                            init[j], height, width, enc_seed), n_rows, axis=0)
                        for j, (enc_seed, n_rows)
                        in enumerate(req.init_groups)], axis=0)
                else:
                    z = self.encode_init_image(init, height, width, req.seed)
                if z.shape[0] == 1:
                    init_latent = jnp.repeat(z, batch, axis=0)
                elif z.shape[0] == batch:
                    init_latent = z
                else:  # pad per-frame inits up to the bucketed batch
                    pad = jnp.repeat(z[-1:], batch - z.shape[0], axis=0)
                    init_latent = jnp.concatenate([z, pad], axis=0)
            if has_mask:
                lh, lw = self._latent_hw(height, width)
                f = fam.vae.downscale
                m = np.asarray(req.mask, dtype=np.float32)
                if req.init_groups is not None:
                    # per-JOB masks -> per-row stack, padded to the bucket
                    rows_m = np.concatenate([
                        np.repeat(latent_mask(m[j], lh, lw, f)[None],
                                  n_rows, axis=0)
                        for j, (_, n_rows) in enumerate(req.init_groups)])
                    if rows_m.shape[0] < batch:
                        rows_m = np.concatenate(
                            [rows_m, np.repeat(rows_m[-1:],
                                               batch - rows_m.shape[0], 0)])
                    mask_arr = jnp.asarray(rows_m)[:, :, :, None]
                else:
                    mask_arr = jnp.asarray(
                        latent_mask(m, lh, lw, f))[None, :, :, None]

            has_control = req.controlnet is not None
            control_params = {"zero": jnp.zeros((1,), jnp.float32)}
            control_cond = jnp.zeros((1,), jnp.float32)
            if has_control:
                if req.control_image is None:
                    raise ValueError("controlnet requires a conditioning image")
                cond = np.asarray(req.control_image)
                if cond.shape[:2] != (height, width):
                    cond = _resize_batch(cond, height, width)
                # hint encoder expects [0, 1] (diffusers ControlNet training
                # normalization), NOT the VAE's [-1, 1]
                cond = np.asarray(cond, np.float32)
                if req.control_image.dtype == np.uint8 or cond.max() > 1.0:
                    cond = cond / 255.0
                control_cond = jnp.asarray(np.clip(cond, 0.0, 1.0))[None]
                control_params = req.controlnet.params

            def rows(value: str | tuple[str, ...]) -> list[str]:
                vals = (list(value) if isinstance(value, (tuple, list))
                        else [value or ""] * req.batch)
                if len(vals) != req.batch:
                    raise ValueError(
                        f"{len(vals)} per-row prompts for batch {req.batch}")
                # pad to the compile bucket by repeating the last row
                return vals + [vals[-1]] * (batch - len(vals))

            ids = [jnp.asarray(i) for i in self._tokenize(rows(req.prompt))]
            neg = [jnp.asarray(i) for i in
                   self._tokenize(rows(req.negative_prompt))]

            # data parallelism: when the params live on a dp x tp mesh, seed
            # GSPMD's batch-dim propagation by placing the token inputs (and a
            # batch-shaped init) on the 'data' axis — weight sharding alone
            # leaves the batch replicated
            mesh = _params_mesh(self.c.params)
            if mesh is not None and batch % mesh.shape["data"] == 0:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                row = NamedSharding(mesh, P("data", None))
                ids = [jax.device_put(i, row) for i in ids]
                neg = [jax.device_put(i, row) for i in neg]
                if getattr(init_latent, "ndim", 0) == 4 and \
                        init_latent.shape[0] == batch:
                    init_latent = jax.device_put(
                        init_latent,
                        NamedSharding(mesh, P("data", None, None, None)))

            # DeepCache (ISSUE 12): the per-job reuse schedule engages
            # only behind the env switch and never for the dual-CFG /
            # ControlNet programs; OFF means the pre-reuse executable
            # bit for bit (same static key, no reuse table traced in)
            schedule: tuple[int, ...] = ()
            if req.reuse_schedule and deepcache_enabled() \
                    and not fam.image_conditioned and not has_control:
                schedule = normalize_reuse_schedule(
                    steps, req.reuse_schedule, start_step)
            reuse = bool(schedule)

            has_noise = req.init_noise is not None
            noise_arr = jnp.zeros((1,), jnp.float32)  # placeholder
            if has_noise:
                lh, lw = self._latent_hw(height, width)
                noise_np = np.asarray(req.init_noise, np.float32)
                want = (lh, lw, fam.vae.latent_channels)
                if noise_np.ndim == 3:
                    noise_np = noise_np[None]
                if noise_np.shape[1:] != want:
                    raise ValueError(
                        f"init_noise shape {noise_np.shape[1:]} != latent "
                        f"grid {want}")
                if noise_np.shape[0] > batch:
                    raise ValueError(
                        f"init_noise carries {noise_np.shape[0]} rows but the "
                        f"request buckets to batch {batch}")
                if noise_np.shape[0] == 1:
                    noise_np = np.repeat(noise_np, batch, axis=0)
                elif noise_np.shape[0] != batch:
                    pad = np.repeat(noise_np[-1:], batch - noise_np.shape[0],
                                    axis=0)
                    noise_np = np.concatenate([noise_np, pad], axis=0)
                noise_arr = jnp.asarray(noise_np)

        except BaseException:
            # a prep failure (bad init image/mask/noise) must
            # not leave the encode span open until the trace's
            # force-close — the exported duration would absorb
            # the whole execute phase
            if enc_span is not None:
                enc_span.end()
            raise
        if enc_span is not None:
            enc_span.end()
        with span("step", steps=steps, batch=batch), \
                annotate("swarm.generate"):
            # ``reuse`` joins the static set only when ON: every plain
            # request keeps its historical cache key (and executable)
            fn = self._get_fn(
                batch=batch, height=height, width=width, steps=steps,
                start_step=start_step, sampler=sampler, use_cfg=use_cfg,
                has_init=has_init, has_mask=has_mask,
                tiled=req.tiled_decode,
                has_control=has_control, has_noise=has_noise,
                **({"reuse": True} if reuse else {}),
            )
            # one independent key per batch row: fold the row index into
            # the row's seed, so row b is reproducible at ANY batch size
            # (and a coalesced job's rows match what its solo run would
            # produce)
            pairs = (list(req.sample_seed_rows) if req.sample_seed_rows
                     else [(req.seed, i) for i in range(req.batch)])
            if len(pairs) != req.batch:
                raise ValueError(
                    f"{len(pairs)} sample_seed_rows for batch {req.batch}")
            pairs += [pairs[-1]] * (batch - len(pairs))  # bucket padding
            sample_keys = jnp.stack(
                [jax.random.fold_in(key_for_seed(int(s)), int(r))
                 for s, r in pairs])
            args = [
                self.c.params,
                ids,
                neg,
                sample_keys,
                jnp.float32(req.guidance_scale),
                init_latent,
                mask_arr,
                control_params,
                control_cond,
                jnp.float32(req.control_scale),
                jnp.float32(req.image_guidance_scale),
                noise_arr,
            ]
            if reuse:
                tab = np.zeros(steps, bool)
                tab[list(schedule)] = True
                args.append(jnp.asarray(tab))
            img = fn(*args)
        # step-collapse accounting (ISSUE 12): FULL UNet evals each image
        # pays — the cost term BENCH's >=4x reduction gate reads — plus
        # the live counter/histogram families
        full_evals = (steps - start_step) - len(schedule)
        _UNET_EVALS.inc(req.batch * full_evals, mode="full")
        if schedule:
            _UNET_EVALS.inc(req.batch * len(schedule), mode="reuse")
            _STEPS_SKIPPED.inc(req.batch * len(schedule))
        for _ in range(req.batch):
            _EVALS_PER_IMAGE.observe(full_evals)
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "scheduler": sampler.kind,
            "steps": steps,
            # ladder position actually executed (img2img strength maps to
            # a start index; the quantization is an observable contract)
            "denoise_steps": steps - start_step,
            "unet_evals": full_evals,
            "steps_skipped": len(schedule),
            "guidance_scale": float(req.guidance_scale),
            "size": [req.height, req.width],
            "compiled_size": [height, width],
            "batch": batch,
            "mode": ("pix2pix" if fam.image_conditioned else
                     "inpaint" if has_mask else
                     "img2img" if has_init else "txt2img"),
        }
        if schedule:
            config["reuse_schedule"] = list(schedule)
        if fam.image_conditioned:
            config["image_guidance_scale"] = float(req.image_guidance_scale)
        if has_control:
            config["controlnet"] = req.controlnet.model_name
            config["controlnet_scale"] = float(req.control_scale)
        return PendingImages(img, (height, width),
                             (req.height, req.width), req.batch), config
