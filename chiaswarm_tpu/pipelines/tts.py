"""Bark-class text-to-speech: three transformer stages + EnCodec decode.

Capability parity with swarm/audio/bark.py:11-38 — the reference calls
``suno-bark``'s ``preload_models`` + ``generate_audio``. This pipeline
reproduces bark's actual generation protocol natively:

- **semantic**: text ids (+offset, padded to 256) summed with the
  semantic-history embedding, an infer token, then autoregressive decode
  of semantic tokens (vocab suppressed to [0, semantic_vocab] + eos).
- **coarse**: sliding-window decode over [semantic window ; infer token ;
  coarse history], tokens alternating between two codebook ranges.
- **fine**: non-causal window model filling codebooks 2..n over 1024-frame
  buffers (models/gpt.py::FineGPT).
- **codec**: EnCodec-exact SEANet decoder (models/codec.py).

TPU-first mechanics: each stage's decode is ONE compiled scan program
(static window/prefill buckets with a traced actual-length, the padded
ring slots masked out — the models/blip.py trick), sampling happens
on-chip, and only token streams cross the host boundary. Checkpoints
convert 1:1 from the torch bark layout (convert_bark); random tiny
weights serve hermetic tests.

Voice presets (bark's speaker history prompts) ride job parameters as
``history`` arrays {semantic_prompt, coarse_prompt, fine_prompt}.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import toplevel_jit
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.codec import CodecConfig, CodecDecoder
from chiaswarm_tpu.models.gpt import (
    GPT,
    NEG_INF,
    FineGPT,
    GPTConfig,
    init_caches,
)
from chiaswarm_tpu.models.tokenizer import HashTokenizer


@dataclasses.dataclass(frozen=True)
class TTSFamily:
    name: str
    semantic: GPTConfig
    coarse: GPTConfig
    fine: GPTConfig
    codec: CodecConfig
    # ---- bark protocol constants (HF generation_configuration_bark) ----
    text_encoding_offset: int = 10_048
    text_pad_token: int = 129_595
    semantic_infer_token: int = 129_599
    semantic_vocab: int = 10_000          # eos == pad == this id
    max_input_semantic_length: int = 256
    semantic_rate_hz: float = 49.9
    max_semantic_tokens: int = 768
    coarse_rate_hz: float = 75.0
    n_coarse: int = 2
    coarse_semantic_pad: int = 12_048
    coarse_infer_token: int = 12_050
    max_coarse_input_length: int = 256
    max_coarse_history: int = 630
    sliding_window_len: int = 60
    n_fine: int = 8
    fine_history_length: int = 512
    fine_input_length: int = 1024
    codebook_size: int = 1024

    @property
    def coarse_prefill(self) -> int:
        # [semantic window ; infer ; coarse history] padded to one bucket
        return self.max_coarse_input_length + 1 + self.max_coarse_history


BARK = TTSFamily(
    name="bark",
    semantic=GPTConfig(vocab_size=129_600, output_vocab_size=10_048,
                       n_layer=24, n_head=16, n_embd=1024,
                       block_size=1024, dtype="bfloat16"),
    coarse=GPTConfig(vocab_size=12_096, output_vocab_size=12_096,
                     n_layer=24, n_head=16, n_embd=1024,
                     block_size=1024, dtype="bfloat16"),
    fine=GPTConfig(vocab_size=1056, output_vocab_size=1056,
                   n_layer=24, n_head=16, n_embd=1024,
                   block_size=1024, dtype="bfloat16"),
    codec=CodecConfig(),
)

TINY_TTS = TTSFamily(
    name="tiny_tts",
    semantic=GPTConfig(vocab_size=256, output_vocab_size=64, n_layer=2,
                       n_head=2, n_embd=32, block_size=128),
    coarse=GPTConfig(vocab_size=96, output_vocab_size=96, n_layer=2,
                     n_head=2, n_embd=32, block_size=96),
    fine=GPTConfig(vocab_size=24, output_vocab_size=24, n_layer=2,
                   n_head=2, n_embd=32, block_size=32),
    codec=CodecConfig(n_codebooks=4, codebook_size=16, codebook_dim=8,
                      num_filters=4, upsampling_ratios=(4, 2),
                      num_lstm_layers=1, sampling_rate=16000),
    text_encoding_offset=52,
    text_pad_token=200,
    semantic_infer_token=255,
    semantic_vocab=50,
    max_input_semantic_length=16,
    semantic_rate_hz=50.0,
    max_semantic_tokens=32,
    coarse_rate_hz=50.0,
    n_coarse=2,
    coarse_semantic_pad=90,
    coarse_infer_token=91,
    max_coarse_input_length=16,
    max_coarse_history=12,
    sliding_window_len=8,
    n_fine=4,
    fine_history_length=16,
    fine_input_length=32,
    codebook_size=16,
)

TTS_FAMILIES = {f.name: f for f in (BARK, TINY_TTS)}


def get_tts_family(model_name: str) -> TTSFamily:
    low = (model_name or "").lower()
    tail = low.rsplit("/", 1)[-1]
    if low in TTS_FAMILIES:
        return TTS_FAMILIES[low]
    if tail in TTS_FAMILIES:
        return TTS_FAMILIES[tail]
    return TTS_FAMILIES["bark"]


def is_tts_model(model_name: str) -> bool:
    """The ONE bark/TTS routing gate, shared by the job dispatcher
    (node/job_args.py) and warm-compile (node/initialize.py).

    "suno/bark" is the reference's exact TTS gate
    (swarm/job_arguments.py:22-23); any bark-family TAIL (incl. variants
    like "bark-small" and the tiny hermetic family) takes the TTS path —
    matching the tail, not a substring, keeps e.g. "acme/embark-audioldm"
    on the AudioLDM path."""
    tail = (model_name or "").lower().rsplit("/", 1)[-1]
    return tail.startswith("bark") or tail in TTS_FAMILIES


# ------------------------------------------------------------ components

@dataclasses.dataclass
class TTSComponents:
    family: TTSFamily
    model_name: str
    tokenizer: Any
    semantic: GPT
    coarse: GPT
    fine: FineGPT
    codec: CodecDecoder
    params: dict[str, Any]  # keys: semantic, coarse, fine, codec

    @classmethod
    def _modules(cls, family: TTSFamily):
        return (GPT(family.semantic), GPT(family.coarse),
                FineGPT(family.fine, n_codes_total=family.n_fine,
                        n_codes_given=1),
                CodecDecoder(family.codec))

    @classmethod
    def random(cls, family: TTSFamily | str, seed: int = 0,
               model_name: str | None = None) -> "TTSComponents":
        if isinstance(family, str):
            family = TTS_FAMILIES[family]
        key = jax.random.PRNGKey(seed)
        semantic, coarse, fine, codec = cls._modules(family)
        params: dict[str, Any] = {}
        for name, mod in (("semantic", semantic), ("coarse", coarse)):
            key, sub = jax.random.split(key)
            caches = init_caches(mod.config, 1)
            params[name] = jax.jit(mod.init)(
                sub, jnp.zeros((1, 4), jnp.int32), caches, 0, jnp.int32(4))
        key, sub = jax.random.split(key)
        params["fine"] = jax.jit(
            lambda k: fine.init(
                k, jnp.zeros((1, 8, family.n_fine), jnp.int32), 1))(sub)
        key, sub = jax.random.split(key)
        params["codec"] = jax.jit(codec.init)(
            sub, jnp.zeros((1, family.codec.n_codebooks, 8), jnp.int32))
        tokenizer = HashTokenizer(family.text_encoding_offset - 2,
                                  family.max_input_semantic_length)
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, semantic=semantic, coarse=coarse,
                   fine=fine, codec=codec, params=params)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir, model_name: str,
                        family: TTSFamily | str | None = None,
                        ) -> "TTSComponents":
        """Load a torch bark snapshot (HF ``BarkModel`` layout: semantic /
        coarse_acoustics / fine_acoustics / codec_model in one state
        dict) via convert_bark."""
        from pathlib import Path

        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_bark,
            read_torch_weights,
        )
        from chiaswarm_tpu.models.tokenizer import WordPieceTokenizer

        if isinstance(family, str):
            family = TTS_FAMILIES[family]
        family = family or BARK
        root = Path(checkpoint_dir)
        params = convert_bark(read_torch_weights(root), family)
        vocab = root / "vocab.txt"
        if vocab.exists():
            tokenizer = WordPieceTokenizer.from_vocab_file(
                vocab, family.max_input_semantic_length)
        else:
            tokenizer = HashTokenizer(family.text_encoding_offset - 2,
                                      family.max_input_semantic_length)
        semantic, coarse, fine, codec = cls._modules(family)
        return cls(family=family, model_name=model_name,
                   tokenizer=tokenizer, semantic=semantic, coarse=coarse,
                   fine=fine, codec=codec, params=params)

    def param_bytes(self) -> int:
        leaves = jax.tree.leaves(self.params)
        return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


# --------------------------------------------------------- stage decode

@functools.lru_cache(maxsize=1)
def _stage_decode_jit():
    """Jitted stage decoder, built on FIRST USE — not at import — so
    CHIASWARM_XLA_OPTIONS set after module import still applies, matching
    the __init__-bound executables of the other pipeline stages."""
    return toplevel_jit(
        _stage_decode_impl,
        static_argnames=("gpt", "prefill_len", "max_new",
                         "top_k", "use_embeds"))


def _stage_decode(*args, **kwargs):
    return _stage_decode_jit()(*args, **kwargs)


def _stage_decode_impl(gpt: GPT, params, prompt_ids, embeds, actual_len, key,
                  *, prefill_len: int, max_new: int, top_k: int,
                  temperature, step_masks, eos_id, pad_id,
                  use_embeds: bool):
    """Shared semantic/coarse decoder: padded static prefill (real tokens
    left-aligned, ``actual_len`` traced), then one scan generating
    ``max_new`` tokens with per-step additive logit masks.

    ``step_masks``: (2, V) float32 added to the logits; step t uses
    ``step_masks[t % 2]`` (bark's alternating-codebook processor; pass
    the same row twice for the semantic stage). ``eos_id`` < 0 disables
    early stop. ``temperature`` <= ~1e-5 degenerates to argmax."""
    cfg = gpt.config
    b = embeds.shape[0] if use_embeds else prompt_ids.shape[0]
    ring = prefill_len + max_new
    assert ring <= cfg.block_size, (ring, cfg.block_size)
    alen = jnp.int32(actual_len)
    caches = init_caches(cfg, b)
    kpos = jnp.arange(cfg.block_size)

    qpos = jnp.arange(prefill_len)
    ok = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < alen)
    bias = jnp.where(ok, 0.0, NEG_INF)[None, None]
    logits, caches = gpt.apply(
        params, None if use_embeds else prompt_ids, caches, 0, alen,
        embeds=embeds if use_embeds else None, ring_bias=bias)
    last = jnp.take_along_axis(
        logits, jnp.full((b, 1, 1), 1, jnp.int32) * (alen - 1), axis=1
    )[:, 0]

    temp = jnp.maximum(jnp.float32(temperature), 1e-5)

    def pick(key, logits, mask):
        logits = logits + mask
        scaled = logits / temp
        if top_k > 0 and top_k < logits.shape[-1]:
            kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, NEG_INF, scaled)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    key, skey = jax.random.split(key)
    first = pick(skey, last, step_masks[0])
    done0 = first == eos_id

    def body(carry, t):
        caches, tok, key, done = carry
        idx = prefill_len + t  # ring write slot
        ok = (kpos < alen) | ((kpos >= prefill_len) & (kpos <= idx))
        bias = jnp.where(ok, 0.0, NEG_INF)[None, None, None]
        logits, caches = gpt.apply(
            params, tok[:, None], caches, idx, idx + 1, ring_bias=bias,
            pos_index=alen + t)
        key, skey = jax.random.split(key)
        nxt = pick(skey, logits[:, 0], step_masks[(t + 1) % 2])
        nxt = jnp.where(done, jnp.int32(pad_id), nxt)
        done = done | (nxt == eos_id)
        return (caches, nxt, key, done), nxt

    (_, _, _, _), toks = jax.lax.scan(
        body, (caches, first, key, done0), jnp.arange(max_new - 1))
    return jnp.concatenate([first[:, None], toks.swapaxes(0, 1)], axis=1)


def encode_semantic_text(tokenizer, text: str, fam, vocab_size: int,
                         ) -> np.ndarray:
    """Text ids for the semantic stage, bark protocol.

    Bark tokenizes with ``add_special_tokens=False`` and fills the fixed
    window with ``text_pad_token`` (HF modeling_bark.py:635 masked_fill):
    use the tokenizer's RAW ids (``tokenize()``), not ``encode()`` —
    encode() adds [CLS]/[SEP] and pads with [PAD]=0, which after
    ``text_encoding_offset`` becomes an untrained in-vocab token occupying
    most of the fully-attended prefill for short prompts."""
    L = fam.max_input_semantic_length
    ids = tokenizer.tokenize(text)[:L]
    ids = np.asarray(ids, np.int64) + fam.text_encoding_offset
    text_ids = np.full((1, L), fam.text_pad_token, np.int32)
    text_ids[0, : len(ids)] = np.minimum(ids, vocab_size - 1)
    return text_ids


class TTSPipeline:
    """Resident bark-protocol TTS executor."""

    def __init__(self, components: TTSComponents) -> None:
        self.c = components
        self._fine_fwd = toplevel_jit(
            lambda p, buf, ci: self.c.fine.apply(p, buf, ci),
            static_argnums=2)
        self._codec = toplevel_jit(
            lambda p, codes: self.c.codec.apply(p, codes))

    # ---- stage 1: text -> semantic tokens ----
    def _semantic(self, text: str, key, temperature: float, top_k: int,
                  max_new: int, history: np.ndarray | None) -> np.ndarray:
        fam = self.c.family
        cfg = fam.semantic
        L = fam.max_input_semantic_length
        text_ids = encode_semantic_text(self.c.tokenizer, text, fam,
                                        cfg.vocab_size)

        hist = np.full((1, L), fam.semantic_vocab, np.int32)  # semantic pad
        if history is not None:
            h = np.asarray(history, np.int32).reshape(-1)[-L:]
            hist[0, : len(h)] = h

        table = self.c.params["semantic"]["params"]["wte"]["embedding"]
        dtype = jnp.dtype(cfg.dtype)
        emb = (jnp.asarray(table)[jnp.asarray(text_ids)]
               + jnp.asarray(table)[jnp.asarray(hist)]).astype(dtype)
        infer = jnp.asarray(table)[
            jnp.full((1, 1), fam.semantic_infer_token)].astype(dtype)
        embeds = jnp.concatenate([emb, infer], axis=1)  # (1, L+1, C)

        # suppress everything outside [0, semantic_vocab] (eos == vocab)
        mask = np.full(cfg.out_vocab, NEG_INF, np.float32)
        mask[: fam.semantic_vocab + 1] = 0.0
        masks = jnp.asarray(np.stack([mask, mask]))

        out = _stage_decode(
            self.c.semantic, self.c.params["semantic"], None, embeds,
            L + 1, key, prefill_len=L + 1, max_new=max_new, top_k=top_k,
            temperature=temperature, step_masks=masks,
            eos_id=fam.semantic_vocab, pad_id=fam.semantic_vocab,
            use_embeds=True)
        sem = np.asarray(out)[0]
        ends = np.nonzero(sem == fam.semantic_vocab)[0]
        return sem[: int(ends[0])] if len(ends) else sem

    # ---- stage 2: semantic -> coarse codes (sliding windows) ----
    def _coarse_history(self, history, ratio: float, max_sem_hist: int,
                        ) -> tuple[np.ndarray, list[int]]:
        """Bark's preprocess_histories: offset each coarse codebook row,
        flatten time-major into the shared vocab, align/trim both
        histories (modeling_bark.py BarkCoarseModel.preprocess_histories
        semantics)."""
        fam = self.c.family
        if history is None or "coarse_prompt" not in history \
                or "semantic_prompt" not in history:
            return np.zeros(0, np.int32), []
        sem_h = np.asarray(history["semantic_prompt"], np.int32).reshape(-1)
        coarse_h = np.array(history["coarse_prompt"], np.int64)
        coarse_h = coarse_h.reshape(-1, coarse_h.shape[-1])[: fam.n_coarse]
        for n in range(1, coarse_h.shape[0]):
            coarse_h[n] += fam.codebook_size * n
        flat = coarse_h.T.reshape(-1) + fam.semantic_vocab
        n_sem = min(max_sem_hist, len(sem_h) - len(sem_h) % 2,
                    int(np.floor(len(flat) / ratio)))
        n_coarse_h = int(round(n_sem * ratio))
        sem_h = sem_h[len(sem_h) - n_sem:] if n_sem else sem_h[:0]
        flat = flat[len(flat) - n_coarse_h:][:-2]  # bark's alignment trim
        return sem_h.astype(np.int32), flat.astype(np.int32).tolist()

    def _coarse(self, semantic: np.ndarray, key, temperature: float,
                top_k: int, history=None) -> np.ndarray:
        fam = self.c.family
        ratio = fam.coarse_rate_hz / fam.semantic_rate_hz * fam.n_coarse
        max_sem_hist = int(np.floor(fam.max_coarse_history / ratio))
        n_total = int(round(int(np.floor(
            len(semantic) * ratio / fam.n_coarse)) * fam.n_coarse))
        n_total = max(fam.n_coarse, n_total)
        sw = fam.sliding_window_len
        P = fam.coarse_prefill

        sem_hist, x_coarse = self._coarse_history(history, ratio,
                                                  max_sem_hist)
        len_history = len(x_coarse)
        base_sem_idx = len(sem_hist)
        sem = np.concatenate([sem_hist, semantic.astype(np.int32)])
        masks = np.full((2, fam.coarse.out_vocab), NEG_INF, np.float32)
        lo = fam.semantic_vocab
        masks[0, lo: lo + fam.codebook_size] = 0.0
        masks[1, lo + fam.codebook_size: lo + 2 * fam.codebook_size] = 0.0
        masks = jnp.asarray(masks)

        n_windows = int(np.ceil(n_total / sw))
        for _ in range(n_windows):
            generated = len(x_coarse) - len_history
            sem_idx = base_sem_idx + int(round(generated / ratio))
            window = sem[max(0, sem_idx - max_sem_hist):]
            window = window[: fam.max_coarse_input_length]
            inp = np.full(fam.max_coarse_input_length,
                          fam.coarse_semantic_pad, np.int32)
            inp[: len(window)] = window
            hist = np.asarray(x_coarse[-fam.max_coarse_history:], np.int32)
            prompt = np.concatenate(
                [inp, [fam.coarse_infer_token], hist]).astype(np.int32)
            actual = len(prompt)
            prompt = np.pad(prompt, (0, P - actual))[None]

            key, sub = jax.random.split(key)
            out = _stage_decode(
                self.c.coarse, self.c.params["coarse"],
                jnp.asarray(prompt), None, actual, sub, prefill_len=P,
                max_new=sw, top_k=top_k, temperature=temperature,
                step_masks=masks, eos_id=-1, pad_id=0, use_embeds=False)
            take = min(sw, n_total - (len(x_coarse) - len_history))
            x_coarse.extend(np.asarray(out)[0][:take].tolist())
        return np.asarray(x_coarse[len_history:], np.int32)

    # ---- stage 3: coarse -> all fine codebooks (window fills) ----
    def _fine(self, coarse: np.ndarray, key, temperature: float | None,
              history=None) -> np.ndarray:
        fam = self.c.family
        cbs = fam.codebook_size
        frames = len(coarse) // fam.n_coarse
        codes = (coarse[: frames * fam.n_coarse].reshape(frames,
                                                         fam.n_coarse)
                 - fam.semantic_vocab) % cbs
        buf = np.full((frames, fam.n_fine), cbs, np.int32)  # pad token
        buf[:, : fam.n_coarse] = codes

        W, H = fam.fine_input_length, fam.fine_history_length
        n_history = 0
        if history is not None and "fine_prompt" in history:
            fh = np.asarray(history["fine_prompt"],
                            np.int64).reshape(fam.n_fine, -1).T % cbs
            fh = fh[-H:].astype(np.int32)
            n_history = len(fh)
            buf = np.concatenate([fh, buf], axis=0)
        n_remove = max(0, W - buf.shape[0])
        if n_remove:
            buf = np.pad(buf, ((0, n_remove), (0, 0)),
                         constant_values=cbs)
        n_loops = max(0, int(np.ceil((frames - (W - n_history)) / H))) + 1
        total = buf.shape[0]
        for n in range(n_loops):
            start = min(n * H, total - W)
            fill_start = min(n_history + n * H, total - H)
            rel = fill_start - start
            window = jnp.asarray(buf[None, start: start + W])
            for ci in range(fam.n_coarse, fam.n_fine):
                logits = self._fine_fwd(self.c.params["fine"], window, ci)
                rel_logits = logits[0, :, :cbs]
                if temperature is None or temperature <= 1e-4:
                    preds = jnp.argmax(rel_logits, axis=-1)
                else:
                    key, sub = jax.random.split(key)
                    preds = jax.random.categorical(
                        sub, rel_logits / temperature, axis=-1)
                preds = np.asarray(preds, np.int32)
                window = np.array(window)  # writable host copy
                window[0, rel:, ci] = preds[rel:]
                window = jnp.asarray(window)
            buf[start: start + W] = np.asarray(window)[0]
        if n_remove:
            buf = buf[:-n_remove]
        return buf[n_history:].T % cbs  # (n_fine, frames)

    def __call__(self, text: str, duration_s: float = 4.0, seed: int = 0,
                 temperature: float = 0.7, top_k: int = 50,
                 voice_preset_tokens: list[int] | None = None,
                 history: dict[str, np.ndarray] | None = None,
                 ) -> tuple[np.ndarray, int, dict]:
        fam = self.c.family
        key = key_for_seed(seed)
        k1, k2, k3 = jax.random.split(key, 3)

        n_sem = int(min(duration_s * fam.semantic_rate_hz,
                        fam.max_semantic_tokens))
        n_sem = max(8, (n_sem + 31) // 32 * 32)
        n_sem = min(n_sem, fam.max_semantic_tokens,
                    fam.semantic.block_size
                    - fam.max_input_semantic_length - 2)
        if history is None and voice_preset_tokens:
            history = {"semantic_prompt": np.asarray(voice_preset_tokens)}
        sem_hist = None
        if history is not None and "semantic_prompt" in history:
            sem_hist = history["semantic_prompt"]
        max_possible = fam.max_semantic_tokens / fam.semantic_rate_hz
        if duration_s > max_possible + 0.25:
            import logging

            logging.getLogger("chiaswarm.tts").warning(
                "tts request for %.1f s truncated to %.2f s by the "
                "semantic stage context (max %d tokens @ %.1f Hz)",
                duration_s, max_possible, fam.max_semantic_tokens,
                fam.semantic_rate_hz)
        semantic = self._semantic(text, k1, temperature, top_k, n_sem,
                                  sem_hist)
        if len(semantic) == 0:
            semantic = np.zeros(8, np.int32)
        coarse = self._coarse(semantic, k2, temperature, top_k,
                              history=history)
        fine = self._fine(coarse, k3,
                          temperature if fam.n_fine > fam.n_coarse
                          else None, history=history)

        frames = fine.shape[1]
        books = min(fam.codec.n_codebooks, fine.shape[0])
        codes = fine[:books]
        # static frame buckets for the codec program; causal decode makes
        # right-pad + trim exact
        bucket = max(64, (frames + 63) // 64 * 64)
        padded = np.pad(codes, ((0, 0), (0, bucket - frames)))
        wav = self._codec(self.c.params["codec"],
                          jnp.asarray(padded[None]))
        wav = np.asarray(jax.device_get(wav))[:, : frames
                                              * fam.codec.hop_length]
        sr = fam.codec.sampling_rate
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "mode": "tts",
            "semantic_tokens": int(len(semantic)),
            "frames": int(frames),
            "requested_duration_s": float(duration_s),
            "duration_s": round(wav.shape[1] / sr, 3),
            "sample_rate": sr,
        }
        return wav, sr, config
