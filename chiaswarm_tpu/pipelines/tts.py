"""Bark-class text-to-speech: three GPT stages + neural codec decode.

Capability parity with swarm/audio/bark.py:11-38 — the reference calls
``suno-bark``'s ``preload_models`` + ``generate_audio`` and transcodes
wav -> mp3. Bark's own structure is three autoregressive transformers
(text -> semantic tokens -> coarse codec codes -> fine codec codes) over an
EnCodec decoder; this pipeline reproduces that structure TPU-natively:

- every stage is the scan-decoding GPT of models/gpt.py — one compiled
  program per stage generates the full token stream on-chip;
- the fine stage decodes the remaining codebooks conditioned on coarse
  codes (kept autoregressive here; bark's fine model is non-causal —
  a capability deviation, not an API one);
- codes feed the conv codec decoder (models/codec.py) for the waveform.

Voice presets (bark's speaker prompts) plug in as token-prompt prefixes via
``voice_preset_tokens`` — the server can ship them in job parameters.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.codec import CodecConfig, CodecDecoder
from chiaswarm_tpu.models.gpt import GPT, GPTConfig, generate
from chiaswarm_tpu.models.tokenizer import HashTokenizer


@dataclasses.dataclass(frozen=True)
class TTSFamily:
    name: str
    semantic: GPTConfig       # text tokens -> semantic tokens
    coarse: GPTConfig         # semantic -> first 2 codec books (interleaved)
    fine: GPTConfig           # coarse -> remaining books
    codec: CodecConfig
    text_vocab: int = 129595
    semantic_vocab: int = 10000
    semantic_rate_hz: float = 49.9    # semantic tokens per second
    coarse_books: int = 2
    prefill_len: int = 64             # static prompt bucket


BARK = TTSFamily(
    name="bark",
    semantic=GPTConfig(vocab_size=129600, output_vocab_size=10048,
                       n_layer=24, n_head=16, n_embd=1024, block_size=1024,
                       dtype="bfloat16"),
    coarse=GPTConfig(vocab_size=12096, output_vocab_size=12096,
                     n_layer=24, n_head=16, n_embd=1024, block_size=1024,
                     dtype="bfloat16"),
    fine=GPTConfig(vocab_size=1056, output_vocab_size=1024,
                   n_layer=24, n_head=16, n_embd=1024, block_size=1024,
                   dtype="bfloat16"),
    codec=CodecConfig(),
)

TINY_TTS = TTSFamily(
    name="tiny_tts",
    semantic=GPTConfig(vocab_size=256, output_vocab_size=64, n_layer=2,
                       n_head=2, n_embd=32, block_size=128),
    coarse=GPTConfig(vocab_size=128, output_vocab_size=128, n_layer=2,
                     n_head=2, n_embd=32, block_size=128),
    fine=GPTConfig(vocab_size=32, output_vocab_size=16, n_layer=2,
                   n_head=2, n_embd=32, block_size=128),
    codec=CodecConfig(n_codebooks=4, codebook_size=16, codebook_dim=8,
                      hidden=16, upsample_rates=(4, 2), sampling_rate=16000),
    text_vocab=250,
    semantic_vocab=50,
    semantic_rate_hz=50.0,
    prefill_len=16,
)

TTS_FAMILIES = {f.name: f for f in (BARK, TINY_TTS)}


def get_tts_family(model_name: str) -> TTSFamily:
    low = (model_name or "").lower()
    tail = low.rsplit("/", 1)[-1]
    if low in TTS_FAMILIES:
        return TTS_FAMILIES[low]
    if tail in TTS_FAMILIES:
        return TTS_FAMILIES[tail]
    return TTS_FAMILIES["bark"]


@dataclasses.dataclass
class TTSComponents:
    family: TTSFamily
    model_name: str
    tokenizer: Any
    semantic: GPT
    coarse: GPT
    fine: GPT
    codec: CodecDecoder
    params: dict[str, Any]  # keys: semantic, coarse, fine, codec

    @classmethod
    def random(cls, family: TTSFamily | str, seed: int = 0,
               model_name: str | None = None) -> "TTSComponents":
        if isinstance(family, str):
            family = TTS_FAMILIES[family]
        from chiaswarm_tpu.models.gpt import init_caches

        key = jax.random.PRNGKey(seed)
        mods = {"semantic": GPT(family.semantic),
                "coarse": GPT(family.coarse),
                "fine": GPT(family.fine)}
        params: dict[str, Any] = {}
        for name, mod in mods.items():
            key, sub = jax.random.split(key)
            caches = init_caches(mod.config, 1)
            params[name] = jax.jit(mod.init)(
                sub, jnp.zeros((1, 4), jnp.int32), caches, 0, jnp.int32(4))
        codec = CodecDecoder(family.codec)
        key, sub = jax.random.split(key)
        params["codec"] = jax.jit(codec.init)(
            sub, jnp.zeros((1, family.codec.n_codebooks, 8), jnp.int32))
        tokenizer = HashTokenizer(family.text_vocab, family.prefill_len)
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, codec=codec, params=params, **mods)

    def param_bytes(self) -> int:
        leaves = jax.tree.leaves(self.params)
        return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


class TTSPipeline:
    """Resident three-stage TTS executor (one compiled scan per stage)."""

    def __init__(self, components: TTSComponents) -> None:
        self.c = components

    def __call__(self, text: str, duration_s: float = 4.0, seed: int = 0,
                 temperature: float = 0.7, top_k: int = 50,
                 voice_preset_tokens: list[int] | None = None,
                 ) -> tuple[np.ndarray, int, dict]:
        fam = self.c.family
        key = key_for_seed(seed)
        k1, k2, k3 = jax.random.split(key, 3)

        # ---- stage 1: text -> semantic tokens
        prompt = self.c.tokenizer.encode(text)[: fam.prefill_len]
        if voice_preset_tokens:
            keep = fam.prefill_len - len(voice_preset_tokens)
            prompt = (list(voice_preset_tokens) + prompt[: max(keep, 0)])[
                : fam.prefill_len]
        prompt = np.asarray([prompt], np.int32) % fam.semantic.vocab_size
        n_sem = int(min(duration_s * fam.semantic_rate_hz,
                        fam.semantic.block_size - fam.prefill_len - 1))
        # bucket to multiples of 32 so duration changes rarely recompile
        n_sem = max(8, (n_sem + 31) // 32 * 32)
        n_sem = min(n_sem, fam.semantic.block_size - fam.prefill_len - 1)
        semantic = generate(
            self.c.semantic, self.c.params["semantic"],
            jnp.asarray(prompt), k1, prefill_len=fam.prefill_len,
            max_new=n_sem, temperature=temperature, top_k=top_k)
        semantic = jnp.mod(semantic, fam.semantic_vocab)

        # ---- stage 2: semantic -> coarse codes (books interleaved)
        c_prefill = min(n_sem, fam.coarse.block_size // 2)
        coarse_prompt = jnp.mod(semantic[:, :c_prefill],
                                fam.coarse.vocab_size)
        n_coarse = min(
            fam.coarse.block_size - c_prefill - 1,
            fam.coarse_books * int(round(
                n_sem / fam.semantic_rate_hz
                * fam.codec.sampling_rate / fam.codec.hop_length)))
        n_coarse = max(fam.coarse_books * 4,
                       n_coarse - n_coarse % fam.coarse_books)
        # context budget: the coarse ring caps output length; log the
        # truncation instead of silently under-delivering (sliding-window
        # coarse generation, as upstream bark does, is future work)
        frames_possible = n_coarse // fam.coarse_books
        sec_possible = frames_possible * fam.codec.hop_length \
            / fam.codec.sampling_rate
        if sec_possible + 0.25 < duration_s:
            import logging

            logging.getLogger("chiaswarm.tts").warning(
                "tts request for %.1f s truncated to %.2f s by the coarse "
                "stage context (block_size=%d)", duration_s, sec_possible,
                fam.coarse.block_size)
        coarse = generate(
            self.c.coarse, self.c.params["coarse"], coarse_prompt, k2,
            prefill_len=c_prefill, max_new=n_coarse,
            temperature=temperature, top_k=top_k)
        frames = n_coarse // fam.coarse_books
        coarse_codes = jnp.mod(
            coarse[:, : frames * fam.coarse_books].reshape(
                1, frames, fam.coarse_books).swapaxes(1, 2),
            fam.codec.codebook_size)                       # (1, 2, frames)

        # ---- stage 3: coarse -> fine codes for the remaining books
        fine_books = fam.codec.n_codebooks - fam.coarse_books
        f_prefill = min(frames, fam.fine.block_size // 2)
        fine_prompt = jnp.mod(coarse_codes[:, 0, :f_prefill],
                              fam.fine.vocab_size)
        n_fine = min(fine_books * frames,
                     fam.fine.block_size - f_prefill - 1)
        n_fine = max(fine_books, n_fine - n_fine % fine_books)
        fine = generate(
            self.c.fine, self.c.params["fine"], fine_prompt, k3,
            prefill_len=f_prefill, max_new=n_fine,
            temperature=temperature, top_k=top_k)
        ff = n_fine // fine_books
        fine_codes = jnp.mod(
            fine[:, : ff * fine_books].reshape(1, ff, fine_books)
            .swapaxes(1, 2), fam.codec.codebook_size)

        # pad/trim fine frames to the coarse frame count, stack all books
        if ff < frames:
            import logging

            logging.getLogger("chiaswarm.tts").warning(
                "fine stage delivered %d/%d frames (block_size=%d); the "
                "tail of the non-coarse codebooks is zero-padded",
                ff, frames, fam.fine.block_size)
            fine_codes = jnp.pad(fine_codes, ((0, 0), (0, 0),
                                              (0, frames - ff)))
        codes = jnp.concatenate([coarse_codes, fine_codes[:, :, :frames]],
                                axis=1)                    # (1, books, frames)

        wav = self.c.codec.apply(self.c.params["codec"], codes)
        wav = np.asarray(jax.device_get(wav))
        sr = fam.codec.sampling_rate
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "mode": "tts",
            "semantic_tokens": int(n_sem),
            "frames": int(frames),
            "requested_duration_s": float(duration_s),
            "duration_s": round(wav.shape[1] / sr, 3),
            "sample_rate": sr,
        }
        return wav, sr, config
