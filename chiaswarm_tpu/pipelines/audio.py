"""Jitted txt2audio pipeline (AudioLDM-class mel-latent diffusion).

Capability parity with swarm/audio/audioldm.py:12-36 — the reference runs
``cvssp/audioldm-s-full-v2`` (20 steps, 10 s of 16 kHz audio) and encodes
wav -> mp3 on the host. TPU-first redesign: ONE compiled program runs
text encode (pooled embedding conditioning) -> lax.scan denoise over the
mel-spectrogram latent -> VAE decode -> HiFiGAN vocoder, emitting the
waveform straight from the chip. Host work is tokenization + WAV framing
(workloads/audio.py; this image has no ffmpeg, so artifacts are
audio/wav — content negotiation reports the type).

Audio-specific shapes: the "image" is a (T_frames, n_mel) log-mel
spectrogram with ONE channel; sequence length rides the H axis so the
existing NHWC UNet/VAE stack applies unchanged. Duration buckets quantize
T_frames so compile cache entries stay bounded.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import (
    toplevel_jit,
    GLOBAL_CACHE,
    bucket_batch,
    static_cache_key,
)
from chiaswarm_tpu.parallel.context import seq_parallel_wrap
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.clap import ClapTextConfig, ClapTextEncoder
from chiaswarm_tpu.models.configs import (
    UNetConfig,
    VAEConfig,
)
from chiaswarm_tpu.models.tokenizer import HashTokenizer
from chiaswarm_tpu.models.unet import UNet
from chiaswarm_tpu.models.vae import AutoencoderKL
from chiaswarm_tpu.models.vocoder import HifiGan, HifiGanConfig
from chiaswarm_tpu.schedulers import (
    make_noise_schedule,
    make_sampling_schedule,
    resolve,
    sampler_step,
    scale_model_input,
)
from chiaswarm_tpu.schedulers.common import ScheduleConfig
from chiaswarm_tpu.schedulers.sampling import init_sampler_state


@dataclasses.dataclass(frozen=True)
class AudioFamily:
    """Architecture of one AudioLDM-class checkpoint."""

    name: str
    text_encoder: ClapTextConfig      # CLAP text tower (RoBERTa layout)
    unet: UNetConfig                  # over mel latents, FiLM-conditioned
    vae: VAEConfig                    # 1-channel mel autoencoder
    vocoder: HifiGanConfig
    n_mel: int = 64
    beta_schedule: str = "scaled_linear"
    prediction_type: str = "epsilon"


AUDIOLDM = AudioFamily(
    name="audioldm",
    text_encoder=ClapTextConfig(),    # laion/clap-htsat defaults (12x768)
    unet=UNetConfig(
        sample_channels=8, out_channels=8,
        block_out_channels=(128, 256, 384, 640),
        transformer_depth=(1, 1, 1, 1),
        attention_head_dim=32, head_dim_is_count=False,
        # AudioLDM's UNet has NO text cross-attention: the normalized CLAP
        # text_embeds condition every resnet through a simple-projection
        # class embedding concatenated with the time embedding
        cross_attention_dim=None,
        class_proj_dim=512, class_embeddings_concat=True,
    ),
    vae=VAEConfig(in_channels=1, latent_channels=8,
                  block_out_channels=(128, 256, 512),
                  scaling_factor=0.9227),
    vocoder=HifiGanConfig(),
)

TINY_AUDIO = AudioFamily(
    name="tiny_audio",
    # max_length must fit the tiny 130-row position table (the class
    # default is the published 512, which would silently clamp gathers)
    text_encoder=ClapTextConfig(
        vocab_size=1000, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, projection_dim=32,
        max_position_embeddings=130, max_length=77),
    unet=UNetConfig(
        sample_channels=8, out_channels=8,
        block_out_channels=(32, 64), layers_per_block=1,
        transformer_depth=(1, 1), attention_head_dim=4,
        head_dim_is_count=True, cross_attention_dim=None,
        class_proj_dim=32, class_embeddings_concat=True, dtype="float32"),
    vae=VAEConfig(in_channels=1, latent_channels=8,
                  block_out_channels=(16, 32), layers_per_block=1,
                  dtype="float32"),
    vocoder=HifiGanConfig(model_in_dim=16, upsample_initial_channel=32,
                          upsample_rates=(4, 4), upsample_kernel_sizes=(8, 8),
                          resblock_kernel_sizes=(3,),
                          resblock_dilation_sizes=((1, 3),)),
    n_mel=16,
)

AUDIO_FAMILIES = {f.name: f for f in (AUDIOLDM, TINY_AUDIO)}


def get_audio_family(model_name: str) -> AudioFamily:
    low = (model_name or "").lower()
    tail = low.rsplit("/", 1)[-1]
    if low in AUDIO_FAMILIES:
        return AUDIO_FAMILIES[low]
    if tail in AUDIO_FAMILIES:
        return AUDIO_FAMILIES[tail]
    return AUDIO_FAMILIES["audioldm"]


@dataclasses.dataclass
class AudioComponents:
    family: AudioFamily
    model_name: str
    tokenizer: Any
    text_encoder: ClapTextEncoder
    unet: UNet
    vae: AutoencoderKL
    vocoder: HifiGan
    params: dict[str, Any]  # keys: text_encoder, unet, vae, vocoder

    @classmethod
    def random(cls, family: AudioFamily | str, seed: int = 0,
               model_name: str | None = None) -> "AudioComponents":
        if isinstance(family, str):
            family = AUDIO_FAMILIES[family]
        key = jax.random.PRNGKey(seed)
        te = ClapTextEncoder(family.text_encoder)
        unet = UNet(family.unet)
        vae = AutoencoderKL(family.vae)
        voc = HifiGan(family.vocoder)
        tcfg = family.text_encoder
        tokenizer = HashTokenizer(tcfg.vocab_size, tcfg.max_length,
                                  eos_id=tcfg.eos_token_id,
                                  bos_id=tcfg.bos_token_id,
                                  pad_id=tcfg.pad_token_id)
        ids = jnp.zeros((1, tcfg.max_length), jnp.int32)
        key, k1, k2, k3, k4 = jax.random.split(key, 5)
        mel_lat = family.n_mel // family.vae.downscale
        params = {
            "text_encoder": jax.jit(te.init)(k1, ids),
            "unet": jax.jit(unet.init)(
                k2, jnp.zeros((1, 8, mel_lat, family.unet.sample_channels)),
                jnp.zeros((1,)), None,
                class_labels=jnp.zeros((1, family.unet.class_proj_dim))),
            "vae": jax.jit(vae.init)(
                k3, jnp.zeros((1, 8, family.n_mel, 1))),
            "vocoder": jax.jit(voc.init)(
                k4, jnp.zeros((1, 8, family.vocoder.model_in_dim))),
        }
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, text_encoder=te, unet=unet, vae=vae,
                   vocoder=voc, params=params)

    def param_bytes(self) -> int:
        leaves = jax.tree.leaves(self.params)
        return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


class AudioPipeline:
    """Resident compile-cached txt2audio executor."""

    def __init__(self, components: AudioComponents,
                 attn_impl: str = "auto") -> None:
        self.c = components
        fam = components.family
        if attn_impl not in ("auto", fam.unet.attn_impl):
            components.unet = UNet(dataclasses.replace(
                fam.unet, attn_impl=attn_impl))
        self.schedule_config = ScheduleConfig(
            beta_schedule=fam.beta_schedule,
            prediction_type=fam.prediction_type,
        )
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    def _frames_for(self, duration_s: float) -> int:
        """Duration -> mel frame count, bucketed to limit compile cache
        growth: multiples of 64 latent-frames (VAE+UNet need the T axis
        divisible by total downscale)."""
        fam = self.c.family
        sr = fam.vocoder.sampling_rate
        hop = fam.vocoder.hop_length
        frames = int(round(duration_s * sr / hop))
        quantum = fam.vae.downscale * (2 ** (
            len(fam.unet.block_out_channels) - 1))
        return max(quantum, (frames + quantum - 1) // quantum * quantum)

    def _build_fn(self, *, batch: int, frames: int, steps: int, sampler,
                  use_cfg: bool):
        fam = self.c.family
        te, unet, vae, voc = (self.c.text_encoder, self.c.unet, self.c.vae,
                              self.c.vocoder)
        sched = make_sampling_schedule(self.noise_schedule, steps, sampler)
        f = fam.vae.downscale
        lt, lm = frames // f, fam.n_mel // f
        latent_ch = fam.vae.latent_channels

        def fn(params, ids, neg_ids, key, guidance):
            # CLAP conditioning (the serving pipeline's exact protocol):
            # projected text_embeds, L2-normalized, FiLM-injected into the
            # UNet as float class labels — no cross-attention sequence
            def embed(token_ids):
                _, proj = te.apply(params["text_encoder"], token_ids)
                return proj / jnp.maximum(
                    jnp.linalg.norm(proj, axis=-1, keepdims=True), 1e-12)

            cond = embed(ids)
            if use_cfg:
                cond = jnp.concatenate([embed(neg_ids), cond], axis=0)

            key, nkey = jax.random.split(key)
            x = jax.random.normal(nkey, (batch, lt, lm, latent_ch),
                                  jnp.float32) * sched.sigmas[0]

            def body(carry, i):
                x, state, key = carry
                inp = scale_model_input(sched, x, i)
                if use_cfg:
                    inp2 = jnp.concatenate([inp, inp], axis=0)
                    t2 = sched.timesteps[i][None].repeat(2 * batch, axis=0)
                    out = unet.apply(params["unet"], inp2, t2, None,
                                     class_labels=cond)
                    eps_u, eps_c = jnp.split(out, 2, axis=0)
                    eps = eps_u + guidance * (eps_c - eps_u)
                else:
                    t1 = sched.timesteps[i][None].repeat(batch, axis=0)
                    eps = unet.apply(params["unet"], inp, t1, None,
                                     class_labels=cond)
                key, skey = jax.random.split(key)
                noise = jax.random.normal(skey, x.shape, jnp.float32)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=noise, start_index=0)
                return (x, state, key), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), key), jnp.arange(steps))

            mel = vae.apply(params["vae"], x, method=AutoencoderKL.decode)
            return voc.apply(params["vocoder"], mel[..., 0])

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "audio", static),
            lambda: self._build_fn(**static))

    def __call__(self, prompt: str, negative_prompt: str = "",
                 steps: int = 20, guidance_scale: float = 2.5,
                 duration_s: float = 10.0, batch: int = 1, seed: int = 0,
                 scheduler: str | None = None) -> tuple[np.ndarray, int, dict]:
        """Returns (waveform float32 (B, samples), sample_rate, config)."""
        fam = self.c.family
        batch = bucket_batch(max(1, batch))
        frames = self._frames_for(duration_s)
        sampler = resolve(scheduler, prediction_type=fam.prediction_type)
        use_cfg = guidance_scale > 1.0
        tok = self.c.tokenizer
        ids = jnp.asarray(tok.encode_batch([prompt] * batch))
        neg = jnp.asarray(tok.encode_batch([negative_prompt or ""] * batch))

        fn = self._get_fn(batch=batch, frames=frames, steps=int(steps),
                          sampler=sampler, use_cfg=use_cfg)
        wav = fn(self.c.params, ids, neg, key_for_seed(seed),
                 jnp.float32(guidance_scale))
        wav = np.asarray(jax.device_get(wav))
        sr = fam.vocoder.sampling_rate
        want = int(round(duration_s * sr))
        wav = wav[:, :want] if wav.shape[1] >= want else wav
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "mode": "txt2audio",
            "steps": int(steps),
            "guidance_scale": float(guidance_scale),
            "duration_s": round(wav.shape[1] / sr, 3),
            "sample_rate": sr,
            "scheduler": sampler.kind,
        }
        return wav, sr, config
