"""Jitted end-to-end generation pipelines + the workload callback registry."""

from chiaswarm_tpu.pipelines.components import Components, ControlNetBundle
from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline, GenerateRequest

__all__ = ["Components", "ControlNetBundle", "DiffusionPipeline",
           "GenerateRequest"]
