"""Jitted upscalers: latent 2x and text-conditioned pixel 4x.

LatentUpscalePipeline — capability parity with swarm/diffusion/
upscale.py:6-32: the reference runs ``stabilityai/sd-x2-latent-upscaler``
over freshly generated images at 20 steps, guidance 0, with attention
slicing + CPU offload always on. TPU-first redesign: one compiled program
per (batch, size, steps) bucket that does encode -> nearest-2x latent
conditioning -> lax.scan denoise of the 2x latent (UNet sees
concat[noisy_2x, upsampled_low-res], 8 input channels) -> VAE decode.

Upscale4xPipeline — the reference's IF cascade stage 3
(swarm/diffusion/diffusion_func_if.py:31-40 runs
``stabilityai/stable-diffusion-x4-upscaler``): text-conditioned 4x
super-resolution with noise-level conditioning. The UNet denoises 4-ch
latents channel-concatenated with the DDPM-NOISED low-res RGB (7 input
channels), the noise level rides a class-embedding table, and the f=4 VAE
decodes the low-res latent grid straight to 4x pixels. One compiled
program per bucket: encode text -> noise low-res -> scan denoise -> decode.

No offload heuristics in either: bf16 weights + Pallas attention + tiled
decode are always on, and the whole pass stays on-chip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import (
    toplevel_jit,
    GLOBAL_CACHE,
    bucket_batch,
    bucket_image_size,
    static_cache_key,
)
from chiaswarm_tpu.parallel.context import seq_parallel_wrap
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.common import upsample2x_nearest
from chiaswarm_tpu.models.vae import AutoencoderKL, tiled_decode
from chiaswarm_tpu.pipelines.components import Components
from chiaswarm_tpu.schedulers import (
    make_noise_schedule,
    make_sampling_schedule,
    resolve,
    sampler_step,
    scale_model_input,
)
from chiaswarm_tpu.schedulers.common import ScheduleConfig
from chiaswarm_tpu.schedulers.sampling import init_sampler_state

DEFAULT_UPSCALE_STEPS = 20  # swarm/diffusion/upscale.py:22-27


class LatentUpscalePipeline:
    """Resident compile-cached 2x upscaler for one Components bundle."""

    def __init__(self, components: Components, attn_impl: str = "auto") -> None:
        self.c = components
        fam = components.family
        if attn_impl not in ("auto", fam.unet.attn_impl):
            import dataclasses

            from chiaswarm_tpu.models.unet import UNet

            components.unet = UNet(
                dataclasses.replace(fam.unet, attn_impl=attn_impl))
        self.schedule_config = ScheduleConfig(
            beta_schedule=fam.beta_schedule,
            prediction_type=fam.prediction_type,
        )
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    def _build_fn(self, *, batch: int, height: int, width: int, steps: int,
                  sampler, tiled: bool):
        fam = self.c.family
        text_encoders = tuple(self.c.text_encoders)
        unet = self.c.unet
        vae = self.c.vae
        f = fam.vae.downscale
        lh, lw = height // f, width // f
        sched = make_sampling_schedule(self.noise_schedule, steps, sampler)
        latent_ch = fam.vae.latent_channels

        def fn(params, ids, row_keys, image):
            seqs = []
            for i, te in enumerate(text_encoders):
                seq, _ = te.apply(params[f"text_encoder_{i}"], ids[i])
                seqs.append(seq)
            ctx = jnp.concatenate(seqs, axis=-1) if len(seqs) > 1 else seqs[0]

            # one key PER batch row (fold_in(key_for_seed(seed), row)):
            # a (seed, row) pair draws the same latents/noise at any
            # batch size and on any slot topology — the per-sample
            # contract shared with pipelines/diffusion.py and the
            # cascade's stage-parallel path
            def stage_keys(stage: int):
                return jax.vmap(
                    lambda k: jax.random.fold_in(k, stage))(row_keys)

            z_lo = jax.vmap(
                lambda img, k: vae.apply(params["vae"], img[None], k,
                                         method=AutoencoderKL.encode)[0]
            )(image, stage_keys(1))                            # (B,lh,lw,C)
            z_cond = upsample2x_nearest(z_lo)                  # (B,2lh,2lw,C)
            noise = jax.vmap(lambda k: jax.random.normal(
                k, (2 * lh, 2 * lw, latent_ch), jnp.float32))(stage_keys(2))
            x = noise * sched.sigmas[0]

            def body(carry, i):
                x, state, rkeys = carry
                inp = scale_model_input(sched, x, i)
                inp = jnp.concatenate([inp, z_cond], axis=-1)  # 8 channels
                t = sched.timesteps[i][None].repeat(batch, axis=0)
                eps = unet.apply(params["unet"], inp, t, ctx)
                both = jax.vmap(jax.random.split)(rkeys)
                rkeys, skeys = both[:, 0], both[:, 1]
                step_noise = jax.vmap(lambda k: jax.random.normal(
                    k, x.shape[1:], jnp.float32))(skeys)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=step_noise, start_index=0)
                return (x, state, rkeys), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), stage_keys(3)),
                jnp.arange(steps))

            if tiled:
                img = tiled_decode(vae, params["vae"], x)
            else:
                img = vae.apply(params["vae"], x, method=AutoencoderKL.decode)
            # quantize ON DEVICE: uint8 moves 4x fewer bytes over the
            # host link (pipelines/diffusion.py rationale)
            return (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                    ).astype(jnp.uint8)

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "upscale", static),
            lambda: self._build_fn(**static))

    def __call__(self, images: np.ndarray, prompt: str = "",
                 steps: int = DEFAULT_UPSCALE_STEPS, seed: int = 0,
                 scheduler: str | None = None,
                 first_row: int = 0) -> tuple[np.ndarray, dict]:
        """uint8 (B, H, W, 3) -> uint8 (B, 2H, 2W, 3).

        Guidance is 0 by construction (no CFG branch), matching the
        reference's ``guidance_scale=0`` call (upscale.py:22-27).
        ``first_row`` offsets the per-row noise keys so a batch-1 call at
        row i reproduces row i of a batched call (see submit contract in
        pipelines/cascade.py)."""
        fam = self.c.family
        images = np.asarray(images)
        if images.ndim == 3:
            images = images[None]
        in_h, in_w = images.shape[1:3]
        height, width = bucket_image_size(in_h, in_w)
        batch = bucket_batch(images.shape[0])
        sampler = resolve(scheduler, prediction_type=fam.prediction_type)

        fimg = images.astype(np.float32) / 127.5 - 1.0
        if (in_h, in_w) != (height, width):
            from chiaswarm_tpu.pipelines.diffusion import _resize_batch

            fimg = _resize_batch(fimg, height, width)
        if fimg.shape[0] < batch:
            pad = np.repeat(fimg[-1:], batch - fimg.shape[0], axis=0)
            fimg = np.concatenate([fimg, pad], axis=0)

        ids = [tok.encode_batch([prompt] * batch)
               for tok in self.c.tokenizers]
        fn = self._get_fn(batch=batch, height=height, width=width,
                          steps=int(steps), sampler=sampler,
                          tiled=2 * max(height, width) > 1024)
        base_key = key_for_seed(seed)
        row_keys = jax.vmap(
            lambda r: jax.random.fold_in(base_key, r)
        )(jnp.arange(first_row, first_row + batch))
        img = fn(self.c.params, [jnp.asarray(i) for i in ids],
                 row_keys, jnp.asarray(fimg))
        img_u8 = np.asarray(jax.device_get(img))  # uint8 off-chip
        # namespaced keys: this config is merged into the generation job's
        # config by the callers — must not clobber its steps/scheduler
        config = {
            "upscaler": self.c.model_name,
            "scale": 2,
            "upscale_steps": int(steps),
            "upscale_scheduler": sampler.kind,
        }
        return img_u8[: images.shape[0]], config


DEFAULT_X4_STEPS = 75       # StableDiffusionUpscalePipeline default
DEFAULT_X4_GUIDANCE = 9.0   # its guidance_scale default
DEFAULT_NOISE_LEVEL = 20    # its noise_level default


class Upscale4xPipeline:
    """Resident compile-cached SD-x4-upscaler for one Components bundle
    (family kind "upscaler4" — stabilityai/stable-diffusion-x4-upscaler).
    """

    def __init__(self, components: Components, attn_impl: str = "auto") -> None:
        self.c = components
        fam = components.family
        if attn_impl not in ("auto", fam.unet.attn_impl):
            import dataclasses

            from chiaswarm_tpu.models.unet import UNet

            components.unet = UNet(
                dataclasses.replace(fam.unet, attn_impl=attn_impl))
        self.schedule_config = ScheduleConfig(
            beta_schedule=fam.beta_schedule,
            prediction_type=fam.prediction_type,
        )
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    def _build_fn(self, *, batch: int, height: int, width: int, steps: int,
                  sampler, use_cfg: bool, noise_level: int, tiled: bool):
        from chiaswarm_tpu.schedulers.common import add_noise

        fam = self.c.family
        text_encoders = tuple(self.c.text_encoders)
        unet = self.c.unet
        vae = self.c.vae
        sched = make_sampling_schedule(self.noise_schedule, steps, sampler)
        latent_ch = fam.vae.latent_channels
        noise_sched = self.noise_schedule

        def encode(params, ids):
            seqs = []
            for i, te in enumerate(text_encoders):
                seq, _ = te.apply(params[f"text_encoder_{i}"], ids[i])
                seqs.append(seq)
            return (jnp.concatenate(seqs, axis=-1) if len(seqs) > 1
                    else seqs[0])

        def fn(params, ids, neg_ids, row_keys, image, guidance):
            ctx = encode(params, ids)
            if use_cfg:
                ctx = jnp.concatenate([encode(params, neg_ids), ctx], axis=0)

            # per-row keys: the (seed, row) contract shared with the
            # other pipelines (see LatentUpscalePipeline above)
            def stage_keys(stage: int):
                return jax.vmap(
                    lambda k: jax.random.fold_in(k, stage))(row_keys)

            # DDPM-noise the low-res conditioning image at noise_level —
            # the forward process q(x_t | x_0) on the model's own schedule
            # (StableDiffusionUpscalePipeline's low_res_scheduler step)
            level = jnp.full((batch,), noise_level, jnp.int32)
            img_noised = add_noise(
                noise_sched, image,
                jax.vmap(lambda k, shp=image.shape[1:]: jax.random.normal(
                    k, shp, jnp.float32))(stage_keys(1)), level)

            x = jax.vmap(lambda k: jax.random.normal(
                k, (height, width, latent_ch), jnp.float32))(stage_keys(2))
            x = x * sched.sigmas[0]
            labels = (jnp.concatenate([level, level], axis=0)
                      if use_cfg else level)

            def body(carry, i):
                x, state, rkeys = carry
                inp = scale_model_input(sched, x, i)
                inp = jnp.concatenate([inp, img_noised], axis=-1)  # 7 ch
                if use_cfg:
                    inp = jnp.concatenate([inp, inp], axis=0)
                t = sched.timesteps[i][None].repeat(inp.shape[0], axis=0)
                out = unet.apply(params["unet"], inp, t, ctx,
                                 class_labels=labels)
                if use_cfg:
                    out_u, out_c = jnp.split(out, 2, axis=0)
                    out = out_u + guidance * (out_c - out_u)
                both = jax.vmap(jax.random.split)(rkeys)
                rkeys, skeys = both[:, 0], both[:, 1]
                step_noise = jax.vmap(lambda k: jax.random.normal(
                    k, x.shape[1:], jnp.float32))(skeys)
                x, state = sampler_step(sampler, sched, i, x, out, state,
                                        noise=step_noise, start_index=0)
                return (x, state, rkeys), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), stage_keys(3)),
                jnp.arange(steps))

            if tiled:
                img = tiled_decode(vae, params["vae"], x)
            else:
                img = vae.apply(params["vae"], x, method=AutoencoderKL.decode)
            # quantize ON DEVICE (pipelines/diffusion.py rationale)
            return (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                    ).astype(jnp.uint8)

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "upscale4", static),
            lambda: self._build_fn(**static))

    def __call__(self, images: np.ndarray, prompt: str = "",
                 negative_prompt: str = "",
                 steps: int = DEFAULT_X4_STEPS,
                 guidance_scale: float = DEFAULT_X4_GUIDANCE,
                 noise_level: int = DEFAULT_NOISE_LEVEL,
                 seed: int = 0,
                 scheduler: str | None = None,
                 first_row: int = 0) -> tuple[np.ndarray, dict]:
        """uint8 (B, H, W, 3) -> uint8 (B, 4H, 4W, 3).

        The latent grid runs at the LOW-RES spatial size (the f=4 VAE does
        the 4x), so a 256px input costs a 256-grid denoise — cheaper per
        output pixel than the x2 latent upscaler's 2x-grid scan."""
        fam = self.c.family
        images = np.asarray(images)
        if images.ndim == 3:
            images = images[None]
        in_h, in_w = images.shape[1:3]
        height, width = bucket_image_size(in_h, in_w)
        batch = bucket_batch(images.shape[0])
        sampler = resolve(scheduler, prediction_type=fam.prediction_type)
        use_cfg = float(guidance_scale) > 1.0

        fimg = images.astype(np.float32) / 127.5 - 1.0
        if (in_h, in_w) != (height, width):
            from chiaswarm_tpu.pipelines.diffusion import _resize_batch

            fimg = _resize_batch(fimg, height, width)
        if fimg.shape[0] < batch:
            pad = np.repeat(fimg[-1:], batch - fimg.shape[0], axis=0)
            fimg = np.concatenate([fimg, pad], axis=0)

        ids = [tok.encode_batch([prompt] * batch)
               for tok in self.c.tokenizers]
        neg = [tok.encode_batch([negative_prompt or ""] * batch)
               for tok in self.c.tokenizers]
        fn = self._get_fn(batch=batch, height=height, width=width,
                          steps=int(steps), sampler=sampler,
                          use_cfg=use_cfg, noise_level=int(noise_level),
                          tiled=4 * max(height, width) > 1024)
        base_key = key_for_seed(seed)
        row_keys = jax.vmap(
            lambda r: jax.random.fold_in(base_key, r)
        )(jnp.arange(first_row, first_row + batch))
        img = fn(self.c.params, [jnp.asarray(i) for i in ids],
                 [jnp.asarray(i) for i in neg], row_keys,
                 jnp.asarray(fimg), jnp.float32(guidance_scale))
        img_u8 = np.asarray(jax.device_get(img))  # uint8 off-chip
        config = {
            "upscaler": self.c.model_name,
            "scale": 4,
            "upscale_steps": int(steps),
            "upscale_noise_level": int(noise_level),
            "upscale_scheduler": sampler.kind,
        }
        return img_u8[: images.shape[0]], config
