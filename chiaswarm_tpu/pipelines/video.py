"""Jitted text-to-video pipeline (ModelScope-class temporal diffusion).

Capability parity with swarm/video/tx2vid.py:17-57 — the reference runs
``damo-vilab/text-to-video-ms-1.7b`` at a default 25 frames with memory
heuristics for >30 frames on small GPUs. TPU-first redesign: ONE compiled
program runs text encode -> lax.scan denoise over the (B, F, lh, lw, C)
video latent through the temporal UNet (models/video_unet.py) -> per-frame
VAE decode (frames folded into the batch axis). Frame counts bucket to
multiples of 8 to bound the compile cache; no slicing/offload heuristics —
bf16 + flash attention are always on.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from chiaswarm_tpu.core.compile_cache import (
    toplevel_jit,
    GLOBAL_CACHE,
    bucket_image_size,
    static_cache_key,
)
from chiaswarm_tpu.parallel.context import seq_parallel_wrap
from chiaswarm_tpu.core.rng import key_for_seed
from chiaswarm_tpu.models.clip import (
    ClipTextEncoder,
    ClipVisionEncoder,
    VisionConfig,
)
from chiaswarm_tpu.models.configs import (
    TextEncoderConfig,
    UNetConfig,
    VAEConfig,
)
from chiaswarm_tpu.models.tokenizer import HashTokenizer
from chiaswarm_tpu.models.vae import (
    AutoencoderKL,
    AutoencoderKLTemporalDecoder,
)
from chiaswarm_tpu.models.video_unet import UNet3D, UNetSpatioTemporal
from chiaswarm_tpu.schedulers import (
    make_noise_schedule,
    make_sampling_schedule,
    resolve,
    sampler_step,
    scale_model_input,
)
from chiaswarm_tpu.schedulers.common import ScheduleConfig
from chiaswarm_tpu.schedulers.sampling import (
    init_sampler_state,
    make_edm_schedule,
)

@dataclasses.dataclass(frozen=True)
class VideoFamily:
    name: str
    # None for image-conditioned families (SVD has no text tower)
    text_encoder: TextEncoderConfig | None
    unet: UNetConfig
    vae: VAEConfig
    default_size: int = 256
    max_frames: int = 64
    # SVD-class img2vid: CLIP-image conditioning + concat cond latents
    image_conditioned: bool = False
    vision: VisionConfig | None = None
    prediction_type: str = "epsilon"
    # EDM continuous-sigma schedule (SVD): karras ladder over this range
    # with 0.25*log(sigma) timestep conditioning, replacing the
    # beta-derived discrete schedule. None = discrete (ModelScope class).
    edm_sigma_range: tuple[float, float] | None = None
    # default clip length (25 = the reference's txt2vid default,
    # swarm/video/tx2vid.py:20; SVD checkpoints publish their own)
    default_frames: int = 25


# text-to-video-ms-1.7b shaped (CLIP-H text tower, 4-level UNet3D).
# use_linear_projection stays False: diffusers' UNet3DConditionModel builds
# its Transformer2DModels with the conv-projection default, so the
# published snapshot stores (O, I, 1, 1) proj weights.
MODELSCOPE = VideoFamily(
    name="modelscope_t2v",
    text_encoder=TextEncoderConfig(
        hidden_size=1024, intermediate_size=4096, num_layers=23,
        num_heads=16, hidden_act="gelu"),
    unet=UNetConfig(
        block_out_channels=(320, 640, 1280, 1280),
        transformer_depth=(1, 1, 1, 0),
        attention_head_dim=64, head_dim_is_count=False,
        cross_attention_dim=1024,
    ),
    vae=VAEConfig(),
    default_size=256,
)

TINY_VID = VideoFamily(
    name="tiny_vid",
    text_encoder=TextEncoderConfig(
        vocab_size=1000, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, eos_token_id=999),
    unet=UNetConfig(
        block_out_channels=(32, 64), layers_per_block=1,
        transformer_depth=(1, 1), attention_head_dim=4,
        head_dim_is_count=True, cross_attention_dim=32, dtype="float32"),
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=1,
                  dtype="float32"),
    default_size=64,
    max_frames=16,
    default_frames=8,
)

# stable-video-diffusion-img2vid shaped: image-conditioned spatio-temporal
# UNet (8ch input = noise latents ++ VAE cond latents), laion ViT-H/14
# image embedding as the single cross-attention token, (fps, motion bucket,
# noise-aug) micro-conditioning through the 256-dim added embedding.
# BASELINE.json config #5 names this class; the reference itself serves
# only ModelScope-style txt2vid (swarm/video/tx2vid.py) — this family goes
# beyond reference parity to match the driver's config sheet. The denoise
# runs the published EDM schedule: karras sigmas over (0.002, 700) with
# 0.25*log(sigma) conditioning and v-prediction (edm_sigma_range below).
SVD = VideoFamily(
    name="svd_img2vid",
    text_encoder=None,
    unet=UNetConfig(
        sample_channels=8, out_channels=4,
        block_out_channels=(320, 640, 1280, 1280),
        transformer_depth=(1, 1, 1, 0),
        attention_head_dim=64, head_dim_is_count=False,
        cross_attention_dim=1024,
        addition_embed_dim=256,       # 3 ids x 256 -> add_embedding MLP
    ),
    vae=VAEConfig(),
    default_size=512,                 # square bucket; native SVD is 576x1024
    max_frames=25,
    image_conditioned=True,
    vision=VisionConfig(hidden_size=1280, intermediate_size=5120,
                        num_layers=32, num_heads=16, image_size=224,
                        patch_size=14, projection_dim=1024,
                        hidden_act="gelu"),
    prediction_type="v_prediction",
    edm_sigma_range=(0.002, 700.0),   # the published SVD EulerDiscrete
    default_frames=14,
)

TINY_SVD = VideoFamily(
    name="tiny_svd",
    text_encoder=None,
    unet=UNetConfig(
        sample_channels=8, out_channels=4,
        block_out_channels=(32, 64), layers_per_block=1,
        transformer_depth=(1, 1), attention_head_dim=4,
        head_dim_is_count=True, cross_attention_dim=16,
        addition_embed_dim=8, dtype="float32"),
    # layers_per_block=2: the temporal-decoder VAE hardcodes the
    # published 2-resnet mid shape
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=2,
                  dtype="float32"),
    default_size=64,
    max_frames=16,
    image_conditioned=True,
    vision=VisionConfig(hidden_size=16, intermediate_size=32, num_layers=2,
                        num_heads=2, image_size=28, patch_size=14,
                        projection_dim=16),
    prediction_type="v_prediction",
    edm_sigma_range=(0.002, 700.0),
    default_frames=8,
)

VIDEO_FAMILIES = {f.name: f for f in (MODELSCOPE, TINY_VID, SVD, TINY_SVD)}

_VIDEO_NAME_HINTS = (
    ("stable-video", "svd_img2vid"),
    ("svd", "svd_img2vid"),
    ("img2vid", "svd_img2vid"),
)


def get_video_family(model_name: str) -> VideoFamily:
    low = (model_name or "").lower()
    tail = low.rsplit("/", 1)[-1]
    if low in VIDEO_FAMILIES:
        return VIDEO_FAMILIES[low]
    if tail in VIDEO_FAMILIES:
        return VIDEO_FAMILIES[tail]
    for hint, family in _VIDEO_NAME_HINTS:
        if hint in low:
            return VIDEO_FAMILIES[family]
    return VIDEO_FAMILIES["modelscope_t2v"]


def _unet_init_args(family: VideoFamily):
    """Example UNet init args for a family (shape-only)."""
    sample = jnp.zeros((1, 2, 8, 8, family.unet.sample_channels))
    t = jnp.zeros((1,))
    seq = (1 if family.image_conditioned
           else family.text_encoder.max_position_embeddings)
    ctx = jnp.zeros((1, seq, family.unet.cross_attention_dim))
    added = ({"time_ids": jnp.zeros((1, 3))} if family.image_conditioned
             else None)
    return sample, t, ctx, added


def make_video_unet(family: VideoFamily, attn_impl: str = "auto"):
    """The faithful architecture for a family: SVD-class families run the
    spatio-temporal layout, text families the ModelScope UNet3D."""
    cfg = family.unet
    if attn_impl not in ("auto", cfg.attn_impl):
        cfg = dataclasses.replace(cfg, attn_impl=attn_impl)
    cls = UNetSpatioTemporal if family.image_conditioned else UNet3D
    return cls(cfg)


def make_video_vae(family: VideoFamily):
    """SVD-class families ship the temporal-decoder VAE
    (AutoencoderKLTemporalDecoder); text families the standard one."""
    cls = (AutoencoderKLTemporalDecoder if family.image_conditioned
           else AutoencoderKL)
    return cls(family.vae)


def _vae_init_args(family: VideoFamily):
    if family.image_conditioned:   # frame-folded round trip signature
        return (jnp.zeros((1, 2, 16, 16, family.vae.in_channels)),)
    return (jnp.zeros((1, 16, 16, family.vae.in_channels)),)


@dataclasses.dataclass
class VideoComponents:
    family: VideoFamily
    model_name: str
    tokenizer: Any
    text_encoder: ClipTextEncoder | None
    unet: UNet3D | UNetSpatioTemporal
    vae: AutoencoderKL | AutoencoderKLTemporalDecoder
    params: dict[str, Any]  # keys: text_encoder|image_encoder, unet, vae
    image_encoder: ClipVisionEncoder | None = None

    @classmethod
    def random(cls, family: VideoFamily | str, seed: int = 0,
               model_name: str | None = None) -> "VideoComponents":
        if isinstance(family, str):
            family = VIDEO_FAMILIES[family]
        key = jax.random.PRNGKey(seed)
        unet = make_video_unet(family)
        vae = make_video_vae(family)
        key, k1, k2, k3 = jax.random.split(key, 4)
        params = {
            "unet": jax.jit(unet.init)(k2, *_unet_init_args(family)),
            "vae": jax.jit(vae.init)(k3, *_vae_init_args(family)),
        }
        te = tokenizer = image_encoder = None
        if family.image_conditioned:
            image_encoder = ClipVisionEncoder(family.vision)
            s = family.vision.image_size
            params["image_encoder"] = jax.jit(image_encoder.init)(
                k1, jnp.zeros((1, s, s, 3)))
        else:
            te = ClipTextEncoder(family.text_encoder)
            tokenizer = HashTokenizer(
                family.text_encoder.vocab_size,
                family.text_encoder.max_position_embeddings,
                family.text_encoder.eos_token_id)
            ids = jnp.zeros(
                (1, family.text_encoder.max_position_embeddings), jnp.int32)
            params["text_encoder"] = jax.jit(te.init)(k1, ids)
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, text_encoder=te, unet=unet, vae=vae,
                   params=params, image_encoder=image_encoder)

    @classmethod
    def random_host(cls, family: VideoFamily | str, seed: int = 0,
                    model_name: str | None = None,
                    dtype: str = "bfloat16") -> "VideoComponents":
        """Host-materialized random components (components.py
        ``materialize_host``): benches load ModelScope-class weights
        without an on-device init program."""
        import numpy as np

        from chiaswarm_tpu.pipelines.components import materialize_host

        if isinstance(family, str):
            family = VIDEO_FAMILIES[family]
        unet = make_video_unet(family)
        vae = make_video_vae(family)
        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(0)
        params = {
            "unet": materialize_host(
                jax.eval_shape(unet.init, key, *_unet_init_args(family)),
                rng, dtype),
            "vae": materialize_host(
                jax.eval_shape(vae.init, key, *_vae_init_args(family)),
                rng, dtype),
        }
        te = tokenizer = image_encoder = None
        if family.image_conditioned:
            image_encoder = ClipVisionEncoder(family.vision)
            s = family.vision.image_size
            params["image_encoder"] = materialize_host(
                jax.eval_shape(image_encoder.init, key,
                               jnp.zeros((1, s, s, 3))), rng, dtype)
        else:
            te = ClipTextEncoder(family.text_encoder)
            tokenizer = HashTokenizer(
                family.text_encoder.vocab_size,
                family.text_encoder.max_position_embeddings,
                family.text_encoder.eos_token_id)
            ids = jnp.zeros(
                (1, family.text_encoder.max_position_embeddings), jnp.int32)
            params["text_encoder"] = materialize_host(
                jax.eval_shape(te.init, key, ids), rng, dtype)
        return cls(family=family,
                   model_name=model_name or f"random/{family.name}",
                   tokenizer=tokenizer, text_encoder=te, unet=unet, vae=vae,
                   params=params, image_encoder=image_encoder)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir, model_name: str,
                        family: VideoFamily | str | None = None,
                        ) -> "VideoComponents":
        """Load a video snapshot with FULL temporal fidelity.

        - SVD-class (image-conditioned) families require a real
          spatio-temporal snapshot (``unet/`` with spatial_res_block/
          temporal_res_block nesting, ``image_encoder/``, ``vae/``);
          every leaf must convert — nothing is synthesized.
        - Text families: a native ModelScope ``UNet3DConditionModel``
          snapshot (temp_convs/transformer_in keys present) converts
          completely — trained motion weights land in the temporal slots
          (the reference's served model, swarm/video/tx2vid.py:24-27).
          A plain 2D SD snapshot (no temporal keys) falls back to
          AnimateDiff-style 2D inflation: spatial weights convert, the
          temporal modules init at identity (zero output projections) —
          the model animates exactly like its 2D parent at frame 1.

        Either way a leaf that EXISTS in the snapshot is never silently
        replaced: conversion is strict (missing/unconvertible keys raise).
        """
        from pathlib import Path

        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_temporal_vae,
            convert_text_encoder,
            convert_unet,
            convert_unet3d,
            convert_unet_spatio_temporal,
            convert_vae,
            read_torch_weights,
        )
        from chiaswarm_tpu.models.tokenizer import load_tokenizer

        if isinstance(family, str):
            family = VIDEO_FAMILIES[family]
        family = family or MODELSCOPE
        root = Path(checkpoint_dir)

        unet = make_video_unet(family)
        vae = make_video_vae(family)
        state = read_torch_weights(root / "unet")
        if family.image_conditioned and \
                not any(".spatial_res_block." in k for k in state):
            # fail BEFORE the (multi-second) abstract init trace
            raise ValueError(
                f"{model_name}: not an SVD-class spatio-temporal UNet "
                f"snapshot (no spatial_res_block keys). Image-"
                f"conditioned families cannot be 2D-inflated — the "
                f"published UNetSpatioTemporalConditionModel layout "
                f"is required.")
        shapes = jax.eval_shape(unet.init, jax.random.PRNGKey(0),
                                *_unet_init_args(family))

        if family.image_conditioned:
            unet_p = _strict_match(
                shapes, convert_unet_spatio_temporal(state, family.unet),
                model_name)
        elif any(".temp_convs." in k or k.startswith("transformer_in.")
                 for k in state):
            # native ModelScope snapshot: full conversion, zero synthesis
            unet_p = _strict_match(
                shapes, convert_unet3d(state, family.unet), model_name)
        else:
            unet_p = _inflate_2d(shapes, convert_unet(state, family.unet))

        vae_state = read_torch_weights(root / "vae")
        if family.image_conditioned:
            # the published SVD VAE (AutoencoderKLTemporalDecoder):
            # trained temporal-decoder weights convert strictly too
            vae_p = _strict_match(
                jax.eval_shape(vae.init, jax.random.PRNGKey(0),
                               *_vae_init_args(family)),
                convert_temporal_vae(vae_state, family.vae),
                f"{model_name} (vae)")
        else:
            vae_p = convert_vae(vae_state, family.vae)
        params = {"unet": unet_p, "vae": vae_p}
        te = tokenizer = image_encoder = None
        if family.image_conditioned:
            # ``image_encoder/`` is a standard
            # CLIPVisionModelWithProjection (oracle-tested converter)
            from chiaswarm_tpu.convert.torch_to_flax import (
                convert_clip_vision,
            )

            image_encoder = ClipVisionEncoder(family.vision)
            params["image_encoder"] = convert_clip_vision(
                read_torch_weights(root / "image_encoder"))
        else:
            te = ClipTextEncoder(family.text_encoder)
            params["text_encoder"] = convert_text_encoder(
                read_torch_weights(root / "text_encoder"))
            tokenizer = load_tokenizer(
                root, family.text_encoder.vocab_size,
                family.text_encoder.eos_token_id,
                family.text_encoder.max_position_embeddings)
        return cls(family=family, model_name=model_name,
                   tokenizer=tokenizer, text_encoder=te, unet=unet,
                   vae=vae, params=params, image_encoder=image_encoder)

    def param_bytes(self) -> int:
        leaves = jax.tree.leaves(self.params)
        return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


def _flat_leaves(tree) -> dict:
    from flax.traverse_util import flatten_dict

    return {"/".join(k): v for k, v in flatten_dict(tree).items()}


def _strict_match(shape_tree, converted, model_name: str):
    """Every module leaf must come from the snapshot — a video family's
    trained temporal weights are never silently replaced (VERDICT r4 #1).
    Missing, extra, or shape-mismatched leaves raise with the offending
    paths."""
    want = _flat_leaves(shape_tree)
    got = _flat_leaves(converted)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    if missing or extra:
        raise ValueError(
            f"{model_name}: video UNet snapshot does not convert "
            f"completely — {len(missing)} module leaves missing from the "
            f"checkpoint (e.g. {missing[:3]}), {len(extra)} checkpoint "
            f"keys with no module slot (e.g. {extra[:3]})")
        # no fallback: serving a video family with synthesized temporal
        # weights would silently produce motion-free clips
    bad = [p for p in want if tuple(want[p].shape) != tuple(got[p].shape)]
    if bad:
        raise ValueError(
            f"{model_name}: converted leaf shapes disagree with the "
            f"family config at {bad[:3]} "
            f"(checkpoint {[tuple(got[p].shape) for p in bad[:3]]} vs "
            f"config {[tuple(want[p].shape) for p in bad[:3]]})")
    return converted


def _inflate_2d(shape_tree, spatial):
    """AnimateDiff-style 2D inflation for ModelScope-class families fed a
    plain SD snapshot: spatial leaves convert, temporal modules
    (transformer_in / tconvs / tattns) init at identity — zero output
    projections (conv4, proj_out), unit norms — so the clip equals the 2D
    parent framewise until trained temporal weights replace them."""
    rng = np.random.default_rng(0)

    def fill(path: str, s) -> jnp.ndarray:
        if not any(tag in path for tag in
                   ("tconv", "tattn", "transformer_in")):
            raise ValueError(
                f"2D inflation: spatial UNet leaf {path!r} missing from "
                f"the converted checkpoint (converter/key mismatch for "
                f"this architecture variant)")
        leaf = path.rsplit("/", 1)[-1]
        if leaf == "scale":
            return jnp.ones(s.shape, s.dtype)
        if leaf == "bias" or "to_out" in path or "conv4" in path or \
                "proj_out" in path:
            return jnp.zeros(s.shape, s.dtype)
        return jnp.asarray(
            rng.standard_normal(s.shape).astype(np.float32) * 0.02,
            s.dtype)

    def walk(shapes, conv, prefix):
        out = {}
        for key, val in shapes.items():
            path = f"{prefix}/{key}" if prefix else key
            sub = conv.get(key) if isinstance(conv, dict) else None
            if isinstance(val, dict):
                out[key] = walk(val, sub if isinstance(sub, dict) else {},
                                path)
            elif sub is not None:
                out[key] = jnp.asarray(sub)
            else:
                out[key] = fill(path, val)
        return out

    return walk(shape_tree, spatial, "")


def _unbucket_frames(img_u8: np.ndarray, req_height: int, req_width: int,
                     height: int, width: int) -> np.ndarray:
    """Scale-to-cover + center-crop every frame back to the requested
    size after a bucketed generation (same host-side policy as
    pipelines/diffusion.py)."""
    if (height, width) == (req_height, req_width):
        return img_u8
    from PIL import Image

    scale = max(req_height / height, req_width / width)
    rh = max(req_height, round(height * scale))
    rw = max(req_width, round(width * scale))
    y0, x0 = (rh - req_height) // 2, (rw - req_width) // 2
    return np.stack([
        np.asarray(Image.fromarray(frame).resize(
            (rw, rh), Image.LANCZOS))[y0:y0 + req_height,
                                      x0:x0 + req_width]
        for frame in img_u8
    ])


class VideoPipeline:
    """Resident compile-cached txt2vid executor."""

    def __init__(self, components: VideoComponents,
                 attn_impl: str = "auto") -> None:
        self.c = components
        fam = components.family
        if attn_impl not in ("auto", fam.unet.attn_impl):
            components.unet = make_video_unet(fam, attn_impl)
        self.schedule_config = ScheduleConfig(beta_schedule="scaled_linear",
                                              prediction_type="epsilon")
        self.noise_schedule = make_noise_schedule(self.schedule_config)

    def _build_fn(self, *, frames: int, height: int, width: int, steps: int,
                  sampler, use_cfg: bool):
        fam = self.c.family
        te, unet, vae = self.c.text_encoder, self.c.unet, self.c.vae
        sched = make_sampling_schedule(self.noise_schedule, steps, sampler)
        f = fam.vae.downscale
        lh, lw = height // f, width // f
        latent_ch = fam.vae.latent_channels

        def fn(params, ids, neg_ids, key, guidance):
            ctx, _ = te.apply(params["text_encoder"], ids)
            if use_cfg:
                nctx, _ = te.apply(params["text_encoder"], neg_ids)
                ctx = jnp.concatenate([nctx, ctx], axis=0)

            key, nkey = jax.random.split(key)
            x = jax.random.normal(
                nkey, (1, frames, lh, lw, latent_ch), jnp.float32
            ) * sched.sigmas[0]

            def body(carry, i):
                x, state, key = carry
                inp = scale_model_input(sched, x, i)
                if use_cfg:
                    inp2 = jnp.concatenate([inp, inp], axis=0)
                    t2 = sched.timesteps[i][None].repeat(2, axis=0)
                    out = unet.apply(params["unet"], inp2, t2, ctx)
                    e_u, e_c = jnp.split(out, 2, axis=0)
                    eps = e_u + guidance * (e_c - e_u)
                else:
                    t1 = sched.timesteps[i][None]
                    eps = unet.apply(params["unet"], inp, t1, ctx)
                key, skey = jax.random.split(key)
                noise = jax.random.normal(skey, x.shape, jnp.float32)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=noise, start_index=0)
                return (x, state, key), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), key), jnp.arange(steps))

            # decode: frames fold into the VAE batch axis
            img = vae.apply(params["vae"], x[0],
                            method=AutoencoderKL.decode)
            # quantize ON DEVICE: uint8 moves 4x fewer bytes over the
            # host link (pipelines/diffusion.py rationale)
            return (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                    ).astype(jnp.uint8)   # (F, H, W, 3) uint8

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "video", static),
            lambda: self._build_fn(**static))

    def __call__(self, prompt: str, negative_prompt: str = "",
                 num_frames: int | None = None, steps: int = 25,
                 guidance_scale: float = 9.0, height: int | None = None,
                 width: int | None = None, seed: int = 0,
                 scheduler: str | None = None) -> tuple[np.ndarray, dict]:
        """Returns (frames uint8 (F, H, W, 3), config)."""
        fam = self.c.family
        req_height = int(height or fam.default_size)
        req_width = int(width or fam.default_size)
        height, width = bucket_image_size(req_height, req_width)
        requested = max(1, min(int(num_frames or fam.default_frames),
                               fam.max_frames))
        frames = min((requested + 7) // 8 * 8, fam.max_frames)
        sampler = resolve(scheduler, prediction_type="epsilon")
        use_cfg = guidance_scale > 1.0
        tok = self.c.tokenizer
        ids = jnp.asarray(tok.encode_batch([prompt]))
        neg = jnp.asarray(tok.encode_batch([negative_prompt or ""]))

        fn = self._get_fn(frames=frames, height=height, width=width,
                          steps=int(steps), sampler=sampler, use_cfg=use_cfg)
        img = fn(self.c.params, ids, neg, key_for_seed(seed),
                 jnp.float32(guidance_scale))
        img_u8 = np.asarray(jax.device_get(img))  # uint8 off-chip
        img_u8 = _unbucket_frames(img_u8, req_height, req_width,
                                  height, width)
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "mode": "txt2vid",
            "frames": requested,
            "steps": int(steps),
            "guidance_scale": float(guidance_scale),
            "size": [req_height, req_width],
            "compiled_size": [height, width],
            "scheduler": sampler.kind,
        }
        return img_u8[:requested], config


class Img2VidPipeline:
    """Resident compile-cached SVD-class img2vid executor.

    ONE jitted program per (frames, size, steps) bucket runs: CLIP-image
    encode (the ViT-H tower, a single cross-attention token) -> VAE encode
    of the noise-augmented conditioning frame (un-scaled mode latents,
    broadcast to every frame and channel-concatenated onto the noise
    latents) -> lax.scan denoise through the spatio-temporal UNet with
    (fps, motion bucket, noise-aug) micro-conditioning -> per-frame VAE
    decode -> on-device uint8. Classifier-free guidance follows the
    SVD serving recipe: the unconditional branch zeroes BOTH the image
    embedding and the conditioning latents, and the guidance scale ramps
    linearly from ``min_guidance_scale`` at frame 0 to
    ``max_guidance_scale`` at the last frame.

    Goes beyond the reference (which serves only text-to-video,
    swarm/video/tx2vid.py) to cover BASELINE.json config #5's named
    model class.
    """

    def __init__(self, components: VideoComponents,
                 attn_impl: str = "auto") -> None:
        if not components.family.image_conditioned:
            raise ValueError("Img2VidPipeline requires an image-conditioned "
                             "family (svd_img2vid/tiny_svd)")
        if components.family.edm_sigma_range is None:
            raise ValueError("image-conditioned families denoise on the "
                             "EDM schedule; set edm_sigma_range")
        self.c = components
        fam = components.family
        if attn_impl not in ("auto", fam.unet.attn_impl):
            components.unet = make_video_unet(fam, attn_impl)

    def _build_fn(self, *, frames: int, height: int, width: int, steps: int,
                  sampler, use_cfg: bool):
        fam = self.c.family
        vision, unet, vae = (self.c.image_encoder, self.c.unet, self.c.vae)
        # the published SVD schedule (see make_edm_schedule); the
        # v-prediction preconditioning and 1/sqrt(sigma^2+1) input
        # scaling are the framework's existing sigma-space math
        smin, smax = fam.edm_sigma_range
        sched = make_edm_schedule(smin, smax, steps)
        f = fam.vae.downscale
        lh, lw = height // f, width // f
        latent_ch = fam.vae.latent_channels

        def fn(params, pixels, image, added_ids, key, g_min, g_max):
            # pixels: (1, 224, 224, 3) CLIP-preprocessed; image: (1, H, W, 3)
            # in [-1, 1]; added_ids: (1, 3) = (fps-1, motion_bucket, aug)
            emb = vision.apply(params["image_encoder"], pixels)
            ctx = emb[:, None, :].astype(jnp.float32)

            key, akey, nkey = jax.random.split(key, 3)
            aug = added_ids[0, 2]
            image_aug = image + aug * jax.random.normal(
                akey, image.shape, jnp.float32)
            mean, _ = vae.apply(params["vae"], image_aug,
                                method="encode_moments")
            cond = jnp.broadcast_to(mean[:, None],
                                    (1, frames, lh, lw, latent_ch))

            if use_cfg:
                ctx = jnp.concatenate([jnp.zeros_like(ctx), ctx], axis=0)
                cond2 = jnp.concatenate([jnp.zeros_like(cond), cond], axis=0)
                ids2 = added_ids.repeat(2, axis=0)
            else:
                cond2, ids2 = cond, added_ids
            # per-frame guidance ramp (1, F, 1, 1, 1)
            ramp = jnp.linspace(0.0, 1.0, frames)[None, :, None, None, None]
            guidance = g_min + (g_max - g_min) * ramp

            x = jax.random.normal(
                nkey, (1, frames, lh, lw, latent_ch), jnp.float32
            ) * sched.sigmas[0]

            def body(carry, i):
                x, state, key = carry
                inp = scale_model_input(sched, x, i)
                if use_cfg:
                    inp2 = jnp.concatenate([inp, inp], axis=0)
                    t2 = sched.timesteps[i][None].repeat(2, axis=0)
                    out = unet.apply(
                        params["unet"],
                        jnp.concatenate([inp2, cond2], axis=-1), t2, ctx,
                        {"time_ids": ids2})
                    e_u, e_c = jnp.split(out, 2, axis=0)
                    eps = e_u + guidance * (e_c - e_u)
                else:
                    t1 = sched.timesteps[i][None]
                    eps = unet.apply(
                        params["unet"],
                        jnp.concatenate([inp, cond2], axis=-1), t1, ctx,
                        {"time_ids": ids2})
                key, skey = jax.random.split(key)
                noise = jax.random.normal(skey, x.shape, jnp.float32)
                x, state = sampler_step(sampler, sched, i, x, eps, state,
                                        noise=noise, start_index=0)
                return (x, state, key), None

            (x, _, _), _ = jax.lax.scan(
                body, (x, init_sampler_state(x), key), jnp.arange(steps))

            # temporal-decoder VAE: frames stay a real axis so the
            # decoder's frame convs and blends see the whole clip
            img = vae.apply(params["vae"], x, method="decode")[0]
            return (jnp.clip((img + 1.0) * 127.5 + 0.5, 0.0, 255.0)
                    ).astype(jnp.uint8)   # (F, H, W, 3)

        return seq_parallel_wrap(toplevel_jit(fn), self.c.params)

    def _get_fn(self, **static):
        return GLOBAL_CACHE.cached_executable(
            static_cache_key(id(self.c), "img2vid", static),
            lambda: self._build_fn(**static))

    def __call__(self, image: np.ndarray, num_frames: int | None = None,
                 steps: int = 25, fps: int = 7,
                 motion_bucket_id: int = 127,
                 noise_aug_strength: float = 0.02,
                 min_guidance_scale: float = 1.0,
                 max_guidance_scale: float = 3.0,
                 height: int | None = None, width: int | None = None,
                 seed: int = 0,
                 scheduler: str | None = None) -> tuple[np.ndarray, dict]:
        """``image`` uint8 (H, W, 3). Returns (frames uint8, config)."""
        from PIL import Image

        fam = self.c.family
        req_height = int(height or fam.default_size)
        req_width = int(width or fam.default_size)
        height, width = bucket_image_size(req_height, req_width)
        requested = max(1, min(int(num_frames or fam.default_frames),
                               fam.max_frames))
        frames = min((requested + 7) // 8 * 8, fam.max_frames)
        sampler = resolve(scheduler or "EulerDiscreteScheduler",
                          prediction_type=fam.prediction_type)
        use_cfg = max_guidance_scale > 1.0

        pil = Image.fromarray(np.asarray(image, np.uint8))
        # conditioning latents at the generation grid
        cond_img = np.asarray(pil.resize((width, height), Image.LANCZOS),
                              np.float32) / 127.5 - 1.0
        # CLIP tower input — the published CLIPImageProcessor recipe:
        # shortest edge to image_size (bicubic), center crop, then the
        # CLIP mean/std. A plain squash distorts non-square inputs (SVD's
        # native 576x1024) vs the reference embedding (ADVICE r4 #2).
        s = fam.vision.image_size
        w0, h0 = pil.size
        scale = s / min(w0, h0)
        rw, rh = max(s, round(w0 * scale)), max(s, round(h0 * scale))
        resized = pil.resize((rw, rh), Image.BICUBIC)
        x0, y0 = (rw - s) // 2, (rh - s) // 2
        clip_in = np.asarray(resized.crop((x0, y0, x0 + s, y0 + s)),
                             np.float32) / 255.0
        mean = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
        std = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)
        clip_in = (clip_in - mean) / std

        fn = self._get_fn(frames=frames, height=height, width=width,
                          steps=int(steps), sampler=sampler, use_cfg=use_cfg)
        out = fn(self.c.params, clip_in[None], cond_img[None],
                 np.asarray([[float(fps - 1), float(motion_bucket_id),
                              float(noise_aug_strength)]], np.float32),
                 key_for_seed(seed), jnp.float32(min_guidance_scale),
                 jnp.float32(max_guidance_scale))
        img_u8 = np.asarray(jax.device_get(out))
        img_u8 = _unbucket_frames(img_u8, req_height, req_width,
                                  height, width)
        config = {
            "model_name": self.c.model_name,
            "family": fam.name,
            "mode": "img2vid",
            "frames": requested,
            "steps": int(steps),
            "fps": int(fps),
            "motion_bucket_id": int(motion_bucket_id),
            "noise_aug_strength": float(noise_aug_strength),
            "guidance_scale": [float(min_guidance_scale),
                               float(max_guidance_scale)],
            "size": [req_height, req_width],
            "compiled_size": [height, width],
            "scheduler": sampler.kind,
        }
        return img_u8[:requested], config
