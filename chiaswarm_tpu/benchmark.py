"""Benchmark: the five BASELINE.json configs, measured end to end.

Headline is the north-star config — SDXL 1024px txt2img, 30 steps, CFG —
through the jitted pipeline (text encode -> scan denoise -> VAE decode) on
the default backend. The other four configs (SD1.5-512/20-DDIM, SD2.1
img2img + inpaint, ControlNet+SDXL, txt2vid) run the same way. Random
host-materialized bf16 weights (identical FLOPs/memory traffic to
converted checkpoints). On non-TPU hosts the script falls back to the tiny
hermetic family so it stays runnable anywhere.

Prints ONE JSON line: the headline metric fields at the top level
({"metric", "value", "unit", "vs_baseline", ...}, same schema as round 1)
plus a "configs" object with one entry per BASELINE.json config.
`vs_baseline` is vs the driver-set target of 4 images/sec/chip
(BASELINE.json "north_star"; the reference itself publishes no numbers —
BASELINE.md).

Throughput is measured steady-state: jobs are submitted back-to-back via
``DiffusionPipeline.submit`` so job N's device->host uint8 transfer
overlaps job N+1's denoise (serving does the same; the reference's torch
pipelines block per call).

Env knobs: CHIASWARM_BENCH_CONFIGS (comma list or "all" / "headline"),
CHIASWARM_BENCH_ITERS, CHIASWARM_BENCH_ATTN, and for the headline
CHIASWARM_BENCH_FAMILY/SIZE/STEPS/BATCH.
"""

from __future__ import annotations

import json
import os
import time


def _percentile50(times: list[float]) -> float:
    return sorted(times)[len(times) // 2]


def _step_seconds_snapshot() -> dict | None:
    """Percentiles of the process-cumulative lane step-seconds
    histogram (swarmlens, ISSUE 11) — None before any lane stepped."""
    from chiaswarm_tpu.obs.metrics import REGISTRY

    hist = REGISTRY.get("chiaswarm_stepper_step_seconds")
    if hist is None or not hist.count():
        return None
    pct = hist.percentiles((0.5, 0.9, 0.99))
    if pct is None:
        return None
    return dict({k: round(v, 6) for k, v in pct.items()},
                count=hist.count())


def _bench_diffusion(pipe, *, size: int, steps: int, batch: int, iters: int,
                     scheduler: str | None = None, init_image=None,
                     mask=None, controlnet=None, control_image=None,
                     pipelined: bool = False, roofline: bool = True,
                     guidance: float = 7.5, reuse_schedule=None) -> dict:
    """Warm once, then measure. ``pipelined=True`` additionally measures
    steady-state throughput with submit/wait overlap.

    ``roofline=True`` (swarmlens, ISSUE 11) AOT-captures the generate
    program during the warm call and stamps its static roofline model
    (modeled FLOPs/bytes, the compute-vs-memory bound, attainment vs
    the measured p50) into the result — the per-config *where does the
    chip time go* signal the r06+ BENCH trajectory tracks next to
    img/s. Peaks are the TPU defaults, so on CPU hosts the attainment
    percentage is notional while the modeled-work numbers stay exact."""
    import numpy as np

    import chiaswarm_tpu.pipelines.diffusion as diffusion_mod
    from chiaswarm_tpu.obs import hlocost
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest

    def req(seed: int) -> GenerateRequest:
        return GenerateRequest(
            prompt="a photograph of an astronaut riding a horse",
            negative_prompt="blurry", steps=steps, guidance_scale=guidance,
            height=size, width=size, batch=batch, seed=seed,
            scheduler=scheduler, init_image=init_image, strength=0.75,
            mask=mask, controlnet=controlnet, control_image=control_image,
            reuse_schedule=reuse_schedule,
        )

    capture = hlocost.ProgramCapture()
    if roofline:
        # the warm call is where the cold build happens — capture it;
        # later calls ride the same AOT executables, so measurement
        # semantics are unchanged
        with capture.patching(diffusion_mod):
            imgs, config = pipe(req(0))
    else:
        imgs, config = pipe(req(0))
    assert imgs.shape[0] == batch

    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        pipe(req(i + 1))
        times.append(time.perf_counter() - t0)
    p50 = _percentile50(times)
    out = {
        "p50_latency_s": round(p50, 3),
        "images_per_sec": round(batch / p50, 4),
        # step-collapse accounting (ISSUE 12): FULL UNet evals each
        # image pays — the cost term the >=4x reduction gate reads
        "unet_evals_per_image": config.get("unet_evals",
                                           config.get("denoise_steps",
                                                      steps)),
    }
    if roofline:
        hlo = capture.largest_hlo()
        if hlo:
            # fold the while body by the steps the ladder actually ran
            # (img2img strength truncates the ladder — the observable
            # denoise_steps contract)
            out["roofline"] = hlocost.static_program_report(
                hlo, steps=int(config.get("denoise_steps", steps)),
                achieved_s=p50)
            # swarmproof (ISSUE 15): the same captured program's HLO
            # contract facts — collective counts (any collective in a
            # single-chip config is a compiler surprise; an all-reduce
            # in a ring config is the runtime face of R11), matmul
            # dtype census, and what survived of buffer donation —
            # stamped per config so drift across rounds is a BENCH
            # diff, not a TPU postmortem
            from chiaswarm_tpu.analysis import hlocheck

            out["hlo_contract"] = hlocheck.census(hlo)

    if pipelined:
        # steady-state: keep one job in flight while fetching the last
        n = max(4, iters)
        t0 = time.perf_counter()
        pending = pipe.submit(req(100))[0]
        for i in range(1, n):
            nxt = pipe.submit(req(100 + i))[0]
            pending.wait()
            pending = nxt
        pending.wait()
        total = time.perf_counter() - t0
        out["images_per_sec_pipelined"] = round(n * batch / total, 4)
    return out


def _bench_mixed_arrival(*, on_tpu: bool, attn: str) -> dict:
    """Continuous step-level admission (serving/stepper.py) vs burst-only
    coalescing under STAGGERED mixed-steps arrivals — the traffic shape
    the burst path cannot batch at all: jobs arrive in different polls
    and with different step counts, so `synchronous_do_work_batch` runs
    every one as a solo program while the step scheduler splices each
    into the resident lane at the next step boundary.

    Runs on a dp-sharded mesh slot when enough devices exist (the virtual
    8-device CPU mesh in CI): a solo batch-1 program replicates over the
    data axis, wasting (dp-1)/dp of the slot — exactly what lane
    occupancy recovers. Lanes run UNSHARDED here, matching serving: on
    the pinned jax build the row-sharded step program has a known
    numerics divergence (ROADMAP item 2, the GSPMD divergence family),
    so the bench must not publish throughput from a program the serving
    path refuses to run. Re-enable CHIASWARM_STEPPER_SHARD_ROWS in this
    config when ROADMAP item 2 lands."""
    import os
    import time

    import jax

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest
    from chiaswarm_tpu.serving.stepper import StepScheduler

    fam = "sd15" if on_tpu else "tiny"
    size = 512 if on_tpu else 64
    steps_mix = [20, 25, 30] if on_tpu else [6, 8, 10]
    n_dev = len(jax.devices())
    if n_dev >= 8:
        mesh = build_mesh(MeshSpec({"data": 4, "model": 2}))
    elif n_dev >= 2:
        mesh = build_mesh(MeshSpec({"data": n_dev}))
    else:
        mesh = None
    dp = 1 if mesh is None else dict(
        zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)

    saved = {k: os.environ.get(k) for k in
             ("CHIASWARM_STEPPER_LANE_WIDTH", "CHIASWARM_STEPPER_SHARD_ROWS")}
    os.environ["CHIASWARM_STEPPER_LANE_WIDTH"] = str(max(2, dp))
    # ROADMAP item 2: sharded lanes diverge numerically on the pinned
    # build — serving runs lanes unsharded, and so does the bench
    os.environ["CHIASWARM_STEPPER_SHARD_ROWS"] = "0"
    try:
        registry = ModelRegistry(
            catalog=[{"name": fam, "family": fam, "parameters": {}}],
            allow_random=True, attn_impl=attn)
        pipe = registry.pipeline(fam, mesh=mesh)
        jobs = [(f"job {i}", steps_mix[i % len(steps_mix)], 300 + i)
                for i in range(8)]

        def req(prompt, steps, seed):
            return GenerateRequest(prompt=prompt, steps=steps,
                                   guidance_scale=7.5, height=size,
                                   width=size, seed=seed)

        # warm every solo program + the lane executables
        for steps in sorted(set(s for _, s, _ in jobs)):
            pipe(req("warm", steps, 0))
        sched = StepScheduler()
        sched.submit_request(pipe, prompt="warm", steps=max(steps_mix),
                             guidance_scale=7.5, height=size, width=size,
                             rows=1, seed=0).result(timeout=600)[0].wait()
        s0 = dict(sched.stats())
        t0 = time.perf_counter()
        sched.submit_request(pipe, prompt="warm2", steps=max(steps_mix),
                             guidance_scale=7.5, height=size, width=size,
                             rows=1, seed=1).result(timeout=600)[0].wait()
        step_t = (time.perf_counter() - t0) / max(
            1, sched.stats()["steps_executed"] - s0["steps_executed"])
        # arrivals one lane-step apart: several polls' worth of traffic
        # lands while any one job is still denoising — the regime burst
        # coalescing serves as N solo programs
        stagger = step_t

        def arrivals(run_one):
            t_start = time.perf_counter()
            handles = []
            for i, job in enumerate(jobs):
                target = t_start + i * stagger
                now = time.perf_counter()
                if now < target:
                    time.sleep(target - now)
                handles.append(run_one(job))
            return t_start, handles

        # burst-only reality for this arrival stream: one solo program
        # per job (mixed steps never share a _coalesce_key), submit/wait
        # pipelined like the serving slots
        t_start, handles = arrivals(
            lambda job: pipe.submit(req(*job))[0])
        for pending in handles:
            pending.wait()
        burst_total = time.perf_counter() - t_start

        before = dict(sched.stats())
        t_start, handles = arrivals(
            lambda job: sched.submit_request(
                pipe, prompt=job[0], steps=job[1], guidance_scale=7.5,
                height=size, width=size, rows=1, seed=job[2]))
        for fut in handles:
            fut.result(timeout=600)[0].wait()
        cont_total = time.perf_counter() - t_start
        after = dict(sched.stats())
        sched.shutdown()

        active = after["row_steps_active"] - before["row_steps_active"]
        padded = (after.get("row_steps_padded", 0)
                  - before.get("row_steps_padded", 0))
        denom = max(1, active + padded)
        return {
            "jobs": len(jobs),
            "steps_mix": steps_mix,
            "stagger_s": round(stagger, 4),
            # swarmlens (ISSUE 11): the live lane step-latency
            # distribution — the signal the measured hang budget and
            # deadline tables derive from
            "step_seconds": _step_seconds_snapshot(),
            "images_per_sec_continuous": round(len(jobs) / cont_total, 4),
            "images_per_sec_burst_only": round(len(jobs) / burst_total, 4),
            "speedup": round(burst_total / cont_total, 4),
            "lane_occupancy": round(active / denom, 4),
            "padding_waste": round(padded / denom, 4),
            "rows_admitted_midflight": (
                after.get("rows_admitted_midflight", 0)
                - before.get("rows_admitted_midflight", 0)),
            "lane_width": max(2, dp),
            "mesh_data_axis": dp,
            # lanes run unsharded until the ROADMAP-item-2 numerics
            # divergence is debugged (the key stays for r-trajectory
            # continuity in BENCH json diffs)
            "sharded_rows": False,
        }
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def _bench_mixed_workloads(*, on_tpu: bool, attn: str) -> dict:
    """Adaptive-width lanes under a staggered txt2img + img2img + inpaint
    arrival stream (ISSUE 7): the workload mix real hive traffic shows,
    where the burst path cannot coalesce ACROSS workloads at all and the
    static-width lane pays the padding for whichever regime it guessed.

    Two runs over the identical arrival schedule: per-job solo programs
    (submit/wait pipelined — the pre-ISSUE-7 reality for img2img and
    inpaint, which were lane-ineligible) vs adaptive-width lanes
    (CHIASWARM_STEPPER_LANE_WIDTH unset, so the occupancy/arrival-rate
    controller sets capacity). Reported per workload: p50 latency both
    ways plus the lane occupancy, padding-waste, resize-count, and
    per-workload admission counters from the scheduler stats — the r06
    BENCH json trajectory for the adaptive-width win."""
    import os
    import time

    import jax
    import numpy as np

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest
    from chiaswarm_tpu.serving.stepper import StepScheduler

    fam = "sd15" if on_tpu else "tiny"
    size = 512 if on_tpu else 64
    steps_mix = [20, 25, 30] if on_tpu else [6, 8, 10]
    # same slot shape as _bench_mixed_arrival: a dp-sharded mesh when
    # devices allow (the virtual 8-device CPU mesh in CI) — a solo
    # batch-1 program replicates over the data axis, wasting (dp-1)/dp
    # of the slot, which is exactly the capacity lanes pack rows into
    n_dev = len(jax.devices())
    if n_dev >= 8:
        mesh = build_mesh(MeshSpec({"data": 4, "model": 2}))
    elif n_dev >= 2:
        mesh = build_mesh(MeshSpec({"data": n_dev}))
    else:
        mesh = None
    dp = 1 if mesh is None else dict(
        zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)

    rng = np.random.default_rng(7)
    init = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
    half_mask = np.zeros((size, size), np.float32)
    half_mask[size // 2:] = 1.0

    # the arrival stream: workloads interleaved so no two consecutive
    # jobs share a solo program, steps mixed so no two share a burst key
    kinds = ["txt2img", "img2img", "txt2img", "inpaint",
             "img2img", "txt2img", "inpaint", "txt2img",
             "img2img", "inpaint", "txt2img", "img2img"]
    jobs = [(kind, steps_mix[i % len(steps_mix)], 700 + i)
            for i, kind in enumerate(kinds)]

    saved = {k: os.environ.get(k) for k in
             ("CHIASWARM_STEPPER_LANE_WIDTH", "CHIASWARM_STEPPER_SHARD_ROWS",
              "CHIASWARM_STEPPER_ADAPTIVE", "CHIASWARM_STEPPER_MAX_WIDTH")}
    # adaptive width on (the ISSUE-7 default): no pinned width, bounds
    # left to the controller; lanes unsharded per ROADMAP item 2
    os.environ.pop("CHIASWARM_STEPPER_LANE_WIDTH", None)
    os.environ.pop("CHIASWARM_STEPPER_ADAPTIVE", None)
    os.environ["CHIASWARM_STEPPER_SHARD_ROWS"] = "0"
    os.environ["CHIASWARM_STEPPER_MAX_WIDTH"] = "8"
    try:
        registry = ModelRegistry(
            catalog=[{"name": fam, "family": fam, "parameters": {}}],
            allow_random=True, attn_impl=attn)
        pipe = registry.pipeline(fam, mesh=mesh)

        def req(kind: str, steps: int, seed: int) -> GenerateRequest:
            return GenerateRequest(
                prompt=f"{kind} {seed}", steps=steps, guidance_scale=7.5,
                height=size, width=size, seed=seed,
                init_image=init if kind != "txt2img" else None,
                strength=0.6,
                mask=half_mask if kind == "inpaint" else None)

        def lane_submit(sched, kind, steps, seed):
            return sched.submit_request(
                pipe, prompt=f"{kind} {seed}", steps=steps,
                guidance_scale=7.5, height=size, width=size, rows=1,
                seed=seed,
                init_image=init if kind != "txt2img" else None,
                strength=0.6,
                mask=half_mask if kind == "inpaint" else None)

        # warm every solo program and lane executable the stream needs
        for kind in ("txt2img", "img2img", "inpaint"):
            for steps in sorted(set(s for _, s, _ in jobs)):
                pipe(req(kind, steps, 0))
        sched = StepScheduler()
        lane_submit(sched, "inpaint", max(steps_mix), 1).result(
            timeout=600)[0].wait()
        s0 = dict(sched.stats())
        t0 = time.perf_counter()
        lane_submit(sched, "img2img", max(steps_mix), 2).result(
            timeout=600)[0].wait()
        step_t = (time.perf_counter() - t0) / max(
            1, sched.stats()["steps_executed"] - s0["steps_executed"])
        stagger = step_t

        def arrivals(run_one):
            t_start = time.perf_counter()
            handles = []
            for i, job in enumerate(jobs):
                target = t_start + i * stagger
                now = time.perf_counter()
                if now < target:
                    time.sleep(target - now)
                handles.append((job[0], time.perf_counter(), run_one(job)))
            return t_start, handles

        def p50_by_kind(samples: list[tuple[str, float]]) -> dict:
            out = {}
            for kind in ("txt2img", "img2img", "inpaint"):
                lat = sorted(t for k, t in samples if k == kind)
                if lat:
                    out[kind] = round(lat[len(lat) // 2], 4)
            return out

        # per-job reality for this stream: every job its own solo
        # program (img2img/inpaint had NO batched path before ISSUE 7)
        t_start, handles = arrivals(
            lambda job: pipe.submit(req(*job))[0])
        solo_lat = []
        for kind, t_sub, pending in handles:
            pending.wait()
            solo_lat.append((kind, time.perf_counter() - t_sub))
        solo_total = time.perf_counter() - t_start

        before = dict(sched.stats())
        t_start, handles = arrivals(
            lambda job: lane_submit(sched, *job))
        lane_lat = []
        for kind, t_sub, fut in handles:
            fut.result(timeout=600)[0].wait()
            lane_lat.append((kind, time.perf_counter() - t_sub))
        lane_total = time.perf_counter() - t_start
        after = dict(sched.stats())
        sched.shutdown()

        active = after["row_steps_active"] - before["row_steps_active"]
        padded = (after.get("row_steps_padded", 0)
                  - before.get("row_steps_padded", 0))
        denom = max(1, active + padded)
        admitted = {
            kind: (after.get(f"rows_admitted_{kind}", 0)
                   - before.get(f"rows_admitted_{kind}", 0))
            for kind in ("txt2img", "img2img", "inpaint")}
        return {
            "jobs": len(jobs),
            "step_seconds": _step_seconds_snapshot(),
            "workload_mix": {k: kinds.count(k) for k in
                             ("txt2img", "img2img", "inpaint")},
            "steps_mix": steps_mix,
            "stagger_s": round(stagger, 4),
            "images_per_sec_lanes": round(len(jobs) / lane_total, 4),
            "images_per_sec_per_job": round(len(jobs) / solo_total, 4),
            "speedup": round(solo_total / lane_total, 4),
            "p50_latency_s_lanes": p50_by_kind(lane_lat),
            "p50_latency_s_per_job": p50_by_kind(solo_lat),
            "lane_occupancy": round(active / denom, 4),
            "padding_waste": round(padded / denom, 4),
            "lane_resizes": (after.get("lane_resizes", 0)
                             - before.get("lane_resizes", 0)),
            "rows_admitted_by_workload": admitted,
            "rows_admitted_midflight": (
                after.get("rows_admitted_midflight", 0)
                - before.get("rows_admitted_midflight", 0)),
            "adaptive_width": True,
            "mesh_data_axis": dp,
            "sharded_rows": False,
        }
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def _bench_step_collapse(*, on_tpu: bool, attn: str, iters: int) -> dict:
    """ISSUE 12 (swarmturbo): the step-collapse configs — the arc that
    attacks the 15x headline gap where the per-image math itself
    shrinks, not the scheduling around it.

    Two configs, both quality-accounted against the SAME-seed full-step
    reference (the int8 pattern: the trick ships gated, not trusted):

    - ``sdxl_txt2img_1024_4step``: the lcm-kind few-step sampler at 4
      steps, guidance-embedded (CFG-free at guidance 1.0) — collapses
      steps 30 -> 4 (a >=4x per-image UNet-eval reduction by
      construction, stamped and asserted from the measured config).
    - ``sdxl_txt2img_1024_deepcache``: the 30-step ladder with a
      DeepCache ``every:2`` refresh cadence — half the deep-UNet passes
      replay the cached deep features; PSNR/SSIM vs the reuse-off
      reference is the gate (>= 30 dB / >= 0.9).

    On CPU hosts the tiny hermetic family stands in (exactly like the
    headline config) — eval counts and the quality gate are real, the
    img/s notional."""
    import jax

    from chiaswarm_tpu.obs.quality import quality_report
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import (
        DiffusionPipeline,
        GenerateRequest,
    )

    fam = "sdxl" if on_tpu else "tiny"
    size = 1024 if on_tpu else 64
    base_steps = 30  # the headline ladder — the cost term being collapsed
    few_steps = 4
    if on_tpu:
        c = Components.random_host(fam, seed=0)
        c.params = jax.device_put(c.params, jax.devices()[0])
    else:
        c = Components.random(fam, seed=0)
    pipe = DiffusionPipeline(c, attn_impl=attn)

    prompt = "a photograph of an astronaut riding a horse"
    seed = 123

    # full-step reference: the quality-gate anchor and the eval baseline
    ref_imgs, ref_cfg = pipe(GenerateRequest(
        prompt=prompt, steps=base_steps, guidance_scale=7.5,
        height=size, width=size, seed=seed))
    baseline_evals = int(ref_cfg["unet_evals"])

    out: dict[str, dict] = {}

    # ---- few-step family (lcm kind, CFG-free) ----
    fewstep = _bench_diffusion(
        pipe, size=size, steps=few_steps, batch=1, iters=iters,
        scheduler="LCMScheduler", guidance=1.0, pipelined=True)
    few_imgs, few_cfg = pipe(GenerateRequest(
        prompt=prompt, steps=few_steps, guidance_scale=1.0,
        height=size, width=size, seed=seed, scheduler="LCMScheduler"))
    fewstep.update({
        "steps": few_steps,
        "scheduler": "lcm",
        "guidance_scale": 1.0,
        "baseline_unet_evals": baseline_evals,
        "unet_evals_reduction": round(
            baseline_evals / max(1, int(few_cfg["unet_evals"])), 2),
        # informational only: a distilled few-step checkpoint changes
        # the trajectory CLASS, so similarity to the 30-step reference
        # is reported, not gated (random weights make it meaningless
        # anyway; the lcm gate is lane-vs-solo exactness, test_fewstep)
        "quality_vs_reference": dict(
            quality_report(few_imgs, ref_imgs), gated=False),
    })
    out["sdxl_txt2img_1024_4step"] = fewstep

    # ---- DeepCache feature reuse (every:2 cadence) ----
    saved = os.environ.get("CHIASWARM_DEEPCACHE")
    os.environ["CHIASWARM_DEEPCACHE"] = "1"
    try:
        deepcache = _bench_diffusion(
            pipe, size=size, steps=base_steps, batch=1, iters=iters,
            reuse_schedule="every:2", pipelined=True)
        dc_imgs, dc_cfg = pipe(GenerateRequest(
            prompt=prompt, steps=base_steps, guidance_scale=7.5,
            height=size, width=size, seed=seed,
            reuse_schedule="every:2"))
    finally:
        if saved is None:
            os.environ.pop("CHIASWARM_DEEPCACHE", None)
        else:
            os.environ["CHIASWARM_DEEPCACHE"] = saved
    deepcache.update({
        "steps": base_steps,
        "reuse_schedule": "every:2",
        "steps_skipped": int(dc_cfg["steps_skipped"]),
        "baseline_unet_evals": baseline_evals,
        "unet_evals_reduction": round(
            baseline_evals / max(1, int(dc_cfg["unet_evals"])), 2),
        # THE gate (same seed, same sampler, reuse on vs off): ships
        # only while the cached-feature output stays faithful
        "quality_vs_reference": dict(
            quality_report(dc_imgs, ref_imgs), gated=True),
    })
    out["sdxl_txt2img_1024_deepcache"] = deepcache
    del pipe, c
    return out


def _bench_model_churn(*, on_tpu: bool, attn: str) -> dict:
    """ISSUE 8: model-swap latency + resident-model count under a budget
    that cannot hold the catalog — the residency ledger's headline
    numbers (evict-then-load donation, measured footprints), stamped
    into BENCH json. CPU hosts churn the tiny family; TPU churns
    sd15-class checkpoints (random weights — load+convert cost is real,
    weight content does not change it)."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.obs.metrics import Registry as ObsRegistry
    from chiaswarm_tpu.serving.residency import ResidencyManager

    family = "sd15" if on_tpu else "tiny"
    models = [f"bench/churn-{tag}" for tag in "abc"]

    def build(budget_bytes: int | None) -> tuple:
        manager = ResidencyManager(
            budget_bytes=budget_bytes or (1 << 40),
            hard_limit_bytes=(budget_bytes or (1 << 40)) * 8,
            metrics_registry=ObsRegistry(), persist_path=None)
        registry = ModelRegistry(
            catalog=[{"name": name, "family": family} for name in models],
            allow_random=True, residency=manager, attn_impl=attn)
        return manager, registry

    # probe one load for the measured footprint the budget is
    # denominated in (exactly what production learns on load one)
    probe_manager, probe_registry = build(None)
    probe_registry.pipeline(models[0])
    footprint = probe_manager.measured_footprints()[models[0]]

    budget = int(footprint * 1.5)  # one resident at a time: every
    manager, registry = build(budget)  # model switch is a swap
    manager.reset_peak()
    swap_times: list[float] = []
    hit_times: list[float] = []
    for round_i in range(2):
        for name in models:
            before = manager.misses
            t0 = time.perf_counter()
            pipe = registry.pipeline(name)
            # touch the pipeline so lazy placement settles into the time
            del pipe
            elapsed = time.perf_counter() - t0
            (swap_times if manager.misses > before
             else hit_times).append(elapsed)
    snap = manager.snapshot()
    largest = max(manager.measured_footprints().values())
    return {
        "family": family,
        "models": len(models),
        "budget_bytes": budget,
        "footprint_bytes": footprint,
        "swap_p50_s": round(_percentile50(swap_times), 4),
        "swaps": len(swap_times),
        "hit_p50_s": (round(_percentile50(hit_times), 6)
                      if hit_times else 0.0),
        "evictions": snap["evictions"],
        "resident_models": len(snap["resident_models"]),
        "resident_bytes": snap["resident_bytes"],
        "peak_bytes": snap["peak_bytes"],
        # THE no-double-buffer invariant, stamped per run
        "peak_within_budget_plus_one": bool(
            snap["peak_bytes"] <= budget + largest),
        "weights_format": os.environ.get("CHIASWARM_WEIGHTS", "bf16"),
    }


def _bench_load_harness(*, on_tpu: bool, attn: str) -> dict:
    """ISSUE 9: the swarmload capacity model + tuning sweeps, stamped
    into BENCH json. One compact seeded diurnal 10x-overload run with a
    mid-run worker kill through the mini-hive (synthetic overload-
    controlled workers — this config measures the CONTROL plane:
    shed/backpressure/brownout behavior and jobs/s/chip at fleet scale,
    not pipeline FLOPs, so it runs identically on CPU and TPU hosts),
    plus the pure-host controller sweeps whose winners are the shipped
    LaneWidthController gains and residency prefetch-ranking window
    (tests/test_loadgen.py pins defaults == winner)."""
    import asyncio

    from chiaswarm_tpu.node import loadgen

    seed = "swarmload"  # FIXED: BENCH r-trajectories must diff runs,
    # not seeds (the nightly chaos soak explores fresh seeds instead)
    schedule = loadgen.build_scenario(seed=seed, n_users=1000,
                                      duration_s=2.5, rate_jobs_s=120)
    report = asyncio.run(loadgen.run_load(
        schedule, n_workers=3, seed=seed, lease_s=3.0,
        max_jobs_per_poll=4, kill=loadgen.KillPlan(after_frac=0.5),
        settle_timeout_s=180))
    workers = report["workers"]
    return {
        "seed": seed,
        "capacity_model": report["capacity"],
        "offered": report["offered"],
        "outcomes": report["outcomes"],
        "zero_loss": report["reconciliation"]["zero_loss"],
        "admitted_p99_within_deadline":
            report["admitted_deadline"]["p99_within_deadline"],
        "latency_s": report["latency_s"]["end_to_end"],
        "jobs_shed": sum(w["jobs_shed"] for w in workers.values()),
        "polls_backpressured": sum(w["polls_backpressured"]
                                   for w in workers.values()),
        "kill": report["kill"],
        # measured per-family deadline suggestions (ISSUE 10 satellite)
        "suggested_deadlines": report["suggested_deadlines"],
        # swarmsight (ISSUE 13): per-family deadline-budget attribution
        # (where each family's end-to-end seconds went, by phase, with
        # the miss-table argmax) + the /api/fleet aggregate snapshot —
        # the observed data plane the item-5 autoscaler will consume
        "budget_attribution": report["budget_attribution"],
        "fleet": report["fleet"],
        # the satellite's tuning story: sweep tables + the winners the
        # shipped defaults were landed from
        "sweeps": {
            "lane_gains": loadgen.sweep_lane_gains(seed),
            "prefetch_window": loadgen.sweep_prefetch_window(seed),
            # ISSUE 10: the derivation DEFAULT_FAMILY_DEADLINES ships
            # (pinned defaults == winner, tests/test_loadgen.py)
            "deadline_table": loadgen.sweep_deadline_table(seed),
        },
    }


def _bench_ring_flash(*, on_tpu: bool, iters: int) -> dict:
    """ISSUE 18 (swarmkernel): the fused ring-flash attainment row.

    Times the seq-parallel self-attention shard_map both ways — the
    ppermute ring scan (the exactness oracle) and the fused Pallas
    ring-flash kernel — on the same mesh and shapes, and stamps each
    kind's p50, static roofline (attainment vs measured p50) and HLO
    collective census. On a TPU pod the delta IS the DMA/compute
    overlap; on CPU hosts the fused kind rides Pallas interpret mode,
    so the speedup number is notional there while the census (the
    zero-spurious-all-reduce acceptance line) and parity stay exact."""
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    if len(devices) < 2:
        return {"skipped": "needs >= 2 devices for a seq mesh",
                "devices": len(devices)}
    sp = 4 if len(devices) >= 4 else len(devices)

    from functools import partial

    from jax.sharding import PartitionSpec as P

    from chiaswarm_tpu.analysis import hlocheck
    from chiaswarm_tpu.core.compat import shard_map, shard_map_unchecked
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.obs import hlocost
    from chiaswarm_tpu.ops.ring_flash_attention import ring_flash_attention
    from chiaswarm_tpu.parallel.ring_attention import ring_attention

    mesh = build_mesh(MeshSpec({"seq": sp}), devices=devices[:sp])
    # TPU: the SDXL 1024px self-attention class the kernel targets;
    # CPU: the tiny hermetic shape (interpret mode is O(slow))
    b, l, h, d = (2, 4096, 10, 64) if on_tpu else (2, 128, 2, 32)
    spec = P(None, "seq", None, None)
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, l, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, l, h, d), jnp.float32)

    kinds = {
        "ring": shard_map(partial(ring_attention, axis_name="seq"),
                          mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec),
        "ring_flash": shard_map_unchecked(
            partial(ring_flash_attention, axis_name="seq",
                    mesh_axis_names=tuple(mesh.axis_names)),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec),
    }
    out: dict = {"mesh": {"seq": sp}, "shape": [b, l, h, d]}
    for kind, fn in kinds.items():
        jitted = jax.jit(fn)
        compiled = jitted.lower(q, k, v).compile()
        compiled(q, k, v).block_until_ready()  # warm
        times = []
        for _ in range(max(2, iters)):
            t0 = time.perf_counter()
            compiled(q, k, v).block_until_ready()
            times.append(time.perf_counter() - t0)
        p50 = _percentile50(times)
        hlo = hlocost.compiled_hlo_text(compiled)
        row = {"p50_latency_s": round(p50, 5)}
        if hlo:
            row["roofline"] = hlocost.static_program_report(
                hlo, achieved_s=p50)
            # the ISSUE-18 acceptance line: the fused program's census
            # must show the collective-permute ring and ZERO spurious
            # all-reduces (an all-reduce here = the softmax combine
            # leaked out of the carried state — R11's runtime face)
            row["hlo_contract"] = hlocheck.census(hlo)
        out[kind] = row
    out["speedup_ring_flash_vs_ring"] = round(
        out["ring"]["p50_latency_s"]
        / max(out["ring_flash"]["p50_latency_s"], 1e-9), 4)
    return out


def _bench_federated_load(*, on_tpu: bool, attn: str) -> dict:
    """ISSUE 18 satellite: the federated hive (PR 17) under the same
    seeded diurnal overload as ``load_harness``, but sharded across a
    3-shard control plane with multiplexed workers — stamps the
    fleet-wide end-to-end p50/p99 and the cross-shard steal books so
    BENCH rounds track whether work stealing keeps shard queues level
    (steals_total == 0 would mean the empty-poll steal seam went
    dead). Control-plane only: identical on CPU and TPU hosts."""
    import asyncio

    from chiaswarm_tpu.node import loadgen

    seed = "swarmfed"  # FIXED, same stance as load_harness
    schedule = loadgen.build_scenario(seed=seed, n_users=1000,
                                      duration_s=2.5, rate_jobs_s=120)
    report = asyncio.run(loadgen.run_load(
        schedule, n_workers=3, n_shards=3, seed=seed, lease_s=3.0,
        max_jobs_per_poll=4, settle_timeout_s=180))
    hive = report["hive"]
    return {
        "seed": seed,
        "n_shards": hive["n_shards"],
        "offered": report["offered"],
        "outcomes": report["outcomes"],
        "zero_loss": report["reconciliation"]["zero_loss"],
        "admitted_p99_within_deadline":
            report["admitted_deadline"]["p99_within_deadline"],
        # fleet-wide latency: per-workload {p50, p99, n} end-to-end
        "latency_s": report["latency_s"]["end_to_end"],
        # cross-shard steal books, counted once by their owning shard
        "steals_total": hive["aggregate"]["steals_total"],
        "steals": hive["aggregate"]["steals"],
        "forwarded_uploads": hive["aggregate"]["forwarded_uploads"],
        "per_shard_completed": [s["completed"] for s in hive["shards"]],
        "fleet": report["fleet"],
    }


def _bench_autoscaler(*, on_tpu: bool, attn: str) -> dict:
    """ISSUE 19 (swarmplan): THE autoscaler headline — the same seeded
    diurnal curve (one spike window) driven once under the
    capacity-model planner (fleet starts at 1 worker, grows/shrinks per
    planning tick) and once per static roster size, with worker-hours
    accounted identically for both. The stamped claim: the
    planner-tracked fleet holds zero loss and bounded admitted p99 with
    STRICTLY fewer worker-hours than every feasible static roster in
    the swept set. Control-plane only: identical on CPU and TPU hosts."""
    import asyncio

    from chiaswarm_tpu.node import loadgen

    seed = "swarmplan"  # FIXED, same stance as load_harness
    population = loadgen.UserPopulation(n_users=200, seed=seed)
    curve = loadgen.DiurnalCurve(amplitude=0.8, spikes=1,
                                 spike_mult=2.0, seed=seed)
    schedule = loadgen.generate_schedule(
        population, curve, duration_s=12.0, rate_jobs_s=90.0,
        seed=seed, id_prefix="plan")
    plan = loadgen.AutoscalePlan(
        min_workers=1, max_workers=5, tick_every_s=0.2,
        capacity_jobs_s_per_worker=40.0, backlog_drain_s=1.5,
        cooldown_up_s=0.4, cooldown_down_s=2.0, smoothing_window_s=1.5)
    table = asyncio.run(loadgen.autoscale_comparison(
        schedule, autoscale=plan, static_rosters=[1, 2, 3, 4, 5],
        seed=seed, settle_timeout_s=180))
    auto = table["planner_report"]["autoscale"]
    return {
        "seed": seed,
        "offered": table["planner_report"]["offered"],
        "planner": table["planner"],
        "static": table["static"],
        "gate": table["gate"],
        "events": auto["events"],
        "fleet_size_series": auto["sizes"],
        "final_decision": auto["decision"],
        "contention": table["planner_report"]["contention"],
    }


def run_configs(names: list[str], *, on_tpu: bool, iters: int,
                attn: str) -> dict:
    import jax
    import numpy as np

    from chiaswarm_tpu.pipelines.components import Components, ControlNetBundle
    from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline

    device = jax.devices()[0]

    def components(family: str) -> Components:
        c = Components.random_host(family, seed=0)
        c.params = jax.device_put(c.params, device)
        return c

    rng = np.random.default_rng(0)
    results: dict[str, dict] = {}

    if "sd15" in names:
        # BASELINE.json #1: SD 1.5 txt2img, 512x512, 20 DDIM steps
        pipe = DiffusionPipeline(components("sd15" if on_tpu else "tiny"),
                                 attn_impl=attn)
        size = 512 if on_tpu else 64
        results["sd15_txt2img_512_ddim20"] = _bench_diffusion(
            pipe, size=size, steps=20 if on_tpu else 2, batch=1,
            iters=iters, scheduler="ddim", pipelined=True)
        del pipe

    if "sd21" in names:
        # BASELINE.json #2: SD 2.1 img2img + inpainting
        c = components("sd21" if on_tpu else "tiny")
        pipe = DiffusionPipeline(c, attn_impl=attn)
        size = 512 if on_tpu else 64
        steps = 30 if on_tpu else 2
        init = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        results["sd21_img2img_512"] = _bench_diffusion(
            pipe, size=size, steps=steps, batch=1, iters=iters,
            init_image=init, pipelined=True)
        half_mask = np.zeros((size, size), np.float32)
        half_mask[size // 2:] = 1.0
        results["sd21_inpaint_512"] = _bench_diffusion(
            pipe, size=size, steps=steps, batch=1, iters=iters,
            init_image=init, mask=half_mask, pipelined=True)
        if on_tpu:
            # SD 2.1's PUBLISHED serving shape: the 768-v checkpoint is
            # native 768px (the reference serves it there; its 9216-token
            # attention level tiles exactly with the 1536 flash block)
            results["sd21_txt2img_768"] = _bench_diffusion(
                pipe, size=768, steps=steps, batch=1, iters=iters,
                pipelined=True)
        del pipe, c

    if "controlnet" in names:
        # BASELINE.json #4: ControlNet + SDXL
        fam = "sdxl" if on_tpu else "tiny"
        c = components(fam)
        bundle = ControlNetBundle.random_host(fam, seed=1)
        bundle.params = jax.device_put(bundle.params, device)
        pipe = DiffusionPipeline(c, attn_impl=attn)
        size = 1024 if on_tpu else 64
        cond = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        results["controlnet_sdxl_1024"] = _bench_diffusion(
            pipe, size=size, steps=30 if on_tpu else 2, batch=1,
            iters=iters, controlnet=bundle, control_image=cond,
            pipelined=True)
        del pipe, c, bundle

    if "img2vid" in names:
        # BASELINE.json #5 names "Stable Video Diffusion img2vid": the
        # image-conditioned SVD-class family (pipelines/video.py::SVD)
        from chiaswarm_tpu.pipelines.video import (
            Img2VidPipeline,
            VideoComponents,
        )

        fam = "svd_img2vid" if on_tpu else "tiny_svd"
        vc = VideoComponents.random_host(fam, seed=0)
        vc.params = jax.device_put(vc.params, device)
        ipipe = Img2VidPipeline(vc, attn_impl=attn)
        frames = 14 if on_tpu else 8
        steps = 25 if on_tpu else 2
        # recorded shape = the PUBLISHED SVD serving portrait (576x1024,
        # 14 frames, 25 steps — VERDICT r4 #6); the square 512 bucket
        # stays as a secondary entry for cross-round continuity
        shapes = ([("img2vid_svd", 576, 1024),
                   ("img2vid_svd_512", 512, 512)] if on_tpu
                  else [("img2vid_svd", 64, 64)])
        for name, bh, bw in shapes:
            cond = rng.integers(0, 255, (bh, bw, 3), dtype=np.uint8)

            def irun(seed: int) -> float:
                t0 = time.perf_counter()
                out, _ = ipipe(cond, num_frames=frames, steps=steps,
                               height=bh, width=bw, seed=seed)
                assert out.shape[0] == frames
                return time.perf_counter() - t0

            irun(0)
            times = [irun(i + 1) for i in range(iters)]
            p50 = _percentile50(times)
            results[name] = {
                "p50_latency_s": round(p50, 3),
                "frames": frames,
                "steps": steps,
                "size": [bh, bw],
                "frames_per_sec": round(frames / p50, 4),
            }
        del ipipe, vc

    if "stepper" in names:
        # ISSUE 3: steady-state throughput under staggered mixed-steps
        # arrivals — continuous step-level admission vs the burst path
        results["stepper_mixed_arrival"] = _bench_mixed_arrival(
            on_tpu=on_tpu, attn=attn)

    if "stepper_mixed_workloads" in names:
        # ISSUE 7: adaptive-width lanes under a staggered txt2img +
        # img2img + inpaint stream vs those jobs' per-job solo paths
        results["stepper_mixed_workloads"] = _bench_mixed_workloads(
            on_tpu=on_tpu, attn=attn)

    if "step_collapse" in names:
        # ISSUE 12 (swarmturbo): few-step sampling + DeepCache feature
        # reuse — the per-image-math configs of the 15x-gap arc, with
        # UNet-eval reductions and the PSNR/SSIM quality gate stamped
        results.update(_bench_step_collapse(on_tpu=on_tpu, attn=attn,
                                            iters=iters))

    if "txt2vid" in names:
        # the model class the reference actually serves for video
        # (ModelScope-class temporal UNet, swarm/video/tx2vid.py)
        from chiaswarm_tpu.pipelines.video import (
            VideoComponents,
            VideoPipeline,
        )

        fam = "modelscope_t2v" if on_tpu else "tiny_vid"
        vc = VideoComponents.random_host(fam, seed=0)
        vc.params = jax.device_put(vc.params, device)
        vpipe = VideoPipeline(vc, attn_impl=attn)
        frames = 16 if on_tpu else 8
        steps = 25 if on_tpu else 2
        size = 256 if on_tpu else 64

        def vrun(seed: int) -> float:
            t0 = time.perf_counter()
            out, _ = vpipe("a paper boat drifting", num_frames=frames,
                           steps=steps, height=size, width=size, seed=seed)
            assert out.shape[0] == frames
            return time.perf_counter() - t0

        vrun(0)
        times = [vrun(i + 1) for i in range(iters)]
        p50 = _percentile50(times)
        results["txt2vid_modelscope"] = {
            "p50_latency_s": round(p50, 3),
            "frames": frames,
            "steps": steps,
            "size": size,
            "frames_per_sec": round(frames / p50, 4),
        }
        del vpipe, vc

    if "model_churn" in names:
        # ISSUE 8: swap latency + resident-model count under a tight
        # residency budget (the ledger's BENCH headline)
        results["model_churn"] = _bench_model_churn(on_tpu=on_tpu,
                                                    attn=attn)

    if "load_harness" in names:
        # ISSUE 9: the swarmload capacity model (jobs/s/chip per
        # workload mix), overload-control outcomes under scripted 10x
        # + worker kill, and the gain/prefetch sweep tables
        results["load_harness"] = _bench_load_harness(on_tpu=on_tpu,
                                                      attn=attn)

    if "ring_flash" in names:
        # ISSUE 18 (swarmkernel): fused ring-flash vs ppermute ring —
        # per-kind p50, roofline attainment, HLO collective census
        results["ring_flash"] = _bench_ring_flash(on_tpu=on_tpu,
                                                  iters=iters)

    if "federated_load" in names:
        # ISSUE 18 satellite: the 3-shard federated hive under the
        # seeded overload — fleet p50/p99 + cross-shard steal books
        results["federated_load"] = _bench_federated_load(on_tpu=on_tpu,
                                                          attn=attn)

    if "autoscaler" in names:
        # ISSUE 19 (swarmplan): planner-tracked fleet vs the static
        # roster sweep — worker-hours at equal-or-better service
        results["autoscaler"] = _bench_autoscaler(on_tpu=on_tpu,
                                                  attn=attn)

    return results


def main() -> None:
    import jax

    from chiaswarm_tpu.core.compile_cache import (
        enable_persistent_compilation_cache,
    )

    # SDXL-1024 first-compile is minutes on a tunneled chip; cached
    # recompiles are seconds (shared with the worker runtime)
    enable_persistent_compilation_cache()
    # the worker's startup knob (node/worker.py startup) — bench must
    # measure the same numerics the serving path runs
    jax.config.update("jax_default_matmul_precision", "bfloat16")

    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline

    on_tpu = jax.default_backend() == "tpu"
    family = os.environ.get(
        "CHIASWARM_BENCH_FAMILY", "sdxl" if on_tpu else "tiny"
    )
    size = int(os.environ.get("CHIASWARM_BENCH_SIZE",
                              "1024" if on_tpu else "64"))
    steps = int(os.environ.get("CHIASWARM_BENCH_STEPS",
                               "30" if on_tpu else "4"))
    batch = int(os.environ.get("CHIASWARM_BENCH_BATCH", "1"))
    iters = int(os.environ.get("CHIASWARM_BENCH_ITERS", "3"))
    attn = os.environ.get("CHIASWARM_BENCH_ATTN", "auto")
    which = os.environ.get("CHIASWARM_BENCH_CONFIGS", "all")

    # ---- headline: the north-star config ----
    if on_tpu:
        # host-side param materialization (no init program, no fp32 copy):
        # on-device fp32 init of SDXL-class weights OOMs a single chip and
        # the init graph alone takes minutes to compile
        c = Components.random_host(family, seed=0)
        c.params = jax.device_put(c.params, jax.devices()[0])
    else:
        c = Components.random(family, seed=0)
    pipe = DiffusionPipeline(c, attn_impl=attn)
    headline = _bench_diffusion(pipe, size=size, steps=steps, batch=batch,
                                iters=iters, pipelined=True)
    del pipe, c

    # steady-state (transfer-overlapped) throughput is the serving number
    imgs_per_sec = headline.get("images_per_sec_pipelined",
                                headline["images_per_sec"])

    configs = {"sdxl_txt2img_1024": headline}
    if which != "headline":
        names = (["sd15", "sd21", "controlnet", "img2vid", "stepper",
                  "stepper_mixed_workloads", "step_collapse", "txt2vid",
                  "model_churn", "load_harness", "ring_flash",
                  "federated_load", "autoscaler"]
                 if which == "all" else which.split(","))
        configs.update(run_configs(names, on_tpu=on_tpu, iters=iters,
                                   attn=attn))

    # swarmscope snapshot (chiaswarm_tpu/obs): compile counts/durations
    # and lane step-latency histograms ride along with every BENCH run,
    # so a perf regression can be split into "got slower" vs "started
    # recompiling" without rerunning anything
    from chiaswarm_tpu.obs.metrics import REGISTRY
    from chiaswarm_tpu.serving.guard import suggest_hang_budget

    target = 4.0  # images/sec/chip, BASELINE.json north star
    print(json.dumps({
        "metric": f"{family} {size}px txt2img {steps} steps, images/sec/chip",
        "value": round(imgs_per_sec, 4),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / target, 4),
        "p50_latency_s": headline["p50_latency_s"],
        "batch": batch,
        "attn": attn,
        "backend": jax.default_backend(),
        "configs": configs,
        # swarmlens (ISSUE 11): whole-run lane step-seconds percentiles
        # + the MEASURED watchdog-budget suggestion they imply — the
        # numbers that graduate the PR-10 hang-budget priors
        "step_seconds_percentiles": _step_seconds_snapshot(),
        "suggested_hang_budget": suggest_hang_budget(),
        "metrics": REGISTRY.snapshot(),
    }))


if __name__ == "__main__":
    main()
