"""Benchmark: SDXL-class 1024px txt2img throughput (images/sec/chip).

Measures the BASELINE.json north-star config — SDXL 1024x1024 txt2img,
30 steps, classifier-free guidance — end to end through the jitted
pipeline (text encode -> scan denoise -> VAE decode) on the default
backend. Random weights (identical FLOPs/memory traffic to converted
checkpoints). On non-TPU hosts it falls back to the tiny hermetic family
so the script stays runnable anywhere.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
`vs_baseline` is vs the driver-set target of 4 images/sec/chip
(BASELINE.json "north_star"; the reference itself publishes no numbers —
BASELINE.md).
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.core.compile_cache import (
        enable_persistent_compilation_cache,
    )

    # SDXL-1024 first-compile is minutes on a tunneled chip; cached
    # recompiles are seconds (shared with the worker runtime)
    enable_persistent_compilation_cache()
    # the worker's startup knob (node/worker.py startup) — bench must
    # measure the same numerics the serving path runs
    jax.config.update("jax_default_matmul_precision", "bfloat16")

    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline, GenerateRequest

    on_tpu = jax.default_backend() == "tpu"
    family = os.environ.get(
        "CHIASWARM_BENCH_FAMILY", "sdxl" if on_tpu else "tiny"
    )
    size = int(os.environ.get("CHIASWARM_BENCH_SIZE",
                              "1024" if on_tpu else "64"))
    steps = int(os.environ.get("CHIASWARM_BENCH_STEPS",
                               "30" if on_tpu else "4"))
    batch = int(os.environ.get("CHIASWARM_BENCH_BATCH", "1"))
    iters = int(os.environ.get("CHIASWARM_BENCH_ITERS", "3"))
    attn = os.environ.get("CHIASWARM_BENCH_ATTN", "auto")

    if on_tpu:
        # host-side param materialization (no init program, no fp32 copy):
        # on-device fp32 init of SDXL-class weights OOMs a single chip and
        # the init graph alone takes minutes to compile
        c = Components.random_host(family, seed=0)
        c.params = jax.device_put(c.params, jax.devices()[0])
    else:
        c = Components.random(family, seed=0)
    pipe = DiffusionPipeline(c, attn_impl=attn)

    def run(seed: int) -> float:
        req = GenerateRequest(
            prompt="a photograph of an astronaut riding a horse",
            negative_prompt="blurry", steps=steps, guidance_scale=7.5,
            height=size, width=size, batch=batch, seed=seed,
        )
        t0 = time.perf_counter()
        imgs, _ = pipe(req)
        assert imgs.shape[0] == batch
        return time.perf_counter() - t0

    run(0)  # compile + warm
    times = [run(i + 1) for i in range(iters)]
    p50 = sorted(times)[len(times) // 2]
    imgs_per_sec = batch / p50

    target = 4.0  # images/sec/chip, BASELINE.json north star
    print(json.dumps({
        "metric": f"{family} {size}px txt2img {steps} steps, images/sec/chip",
        "value": round(imgs_per_sec, 4),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / target, 4),
        "p50_latency_s": round(p50, 3),
        "batch": batch,
        "attn": attn,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
