"""Parallelism layer: tensor-parallel sharding rules, ring attention for
sequence/context parallelism, and multi-host distributed init.

The reference's only parallelism is job-level data parallelism across
isolated GPUs (swarm/worker.py:40-47,113-128; SURVEY.md §2b). On TPU the
pod is one SPMD machine, so this layer adds what the reference never had:

- data parallel: batch sharded on the ``data`` mesh axis (free for inference)
- tensor parallel: attention/MLP weight sharding on ``model`` via GSPMD
  partition rules (parallel/sharding.py)
- sequence/context parallel: ring attention over the ``seq`` axis with
  `ppermute` KV rotation on ICI (parallel/ring_attention.py)
- multi-host: `jax.distributed.initialize` wrapper (parallel/distributed.py)
"""

from chiaswarm_tpu.parallel.context import active_seq_mesh, sequence_parallel
from chiaswarm_tpu.parallel.ring_attention import ring_attention
from chiaswarm_tpu.parallel.sharding import (
    param_partition_specs,
    param_shardings,
    shard_params,
)

__all__ = [
    "active_seq_mesh",
    "ring_attention",
    "param_partition_specs",
    "param_shardings",
    "sequence_parallel",
    "shard_params",
]
