"""Trace-time sequence-parallel context.

The reference has no sequence-parallel serving mode (its long-input answer
is single-GPU attention slicing, swarm/diffusion/diffusion_func.py:85-88).
Here, a pipeline whose params live on a mesh with a ``seq`` axis > 1 routes
its large self-attentions through `parallel.ring_attention` automatically:
the pipeline enters :func:`sequence_parallel` around its jitted program, and
`ops.attention` reads :func:`active_seq_mesh` at TRACE time to decide the
dispatch (a static decision — under `jax.jit` the context only needs to be
live during the first call that traces).

A contextvar (not a global) so hermetic tests can run pipelines on
different meshes in one process without cross-talk.
"""

from __future__ import annotations

import contextlib
import contextvars

from jax.sharding import Mesh

from chiaswarm_tpu.core.mesh import SEQ_AXIS

_seq_mesh: contextvars.ContextVar[Mesh | None] = contextvars.ContextVar(
    "chiaswarm_seq_mesh", default=None)


def active_seq_mesh() -> Mesh | None:
    """The mesh whose ``seq`` axis should shard attention, or None.

    Returns None unless the context is entered AND the mesh actually has a
    ``seq`` axis of size > 1 — callers need no further checks."""
    mesh = _seq_mesh.get()
    if mesh is not None and dict(mesh.shape).get(SEQ_AXIS, 1) > 1:
        return mesh
    return None


@contextlib.contextmanager
def sequence_parallel(mesh: Mesh | None):
    """Route qualifying attention through the ring kernel over ``mesh``.

    Entering with None (or a seq=1 mesh) is a no-op, so pipelines can wrap
    their programs unconditionally."""
    token = _seq_mesh.set(mesh)
    try:
        yield
    finally:
        _seq_mesh.reset(token)


def _seq_mesh_of_params(params) -> Mesh | None:
    """The seq>1 mesh ``params`` are placed on, or None."""
    import jax
    from jax.sharding import NamedSharding

    for leaf in jax.tree.leaves(params):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding) and s.mesh.devices.size > 1:
            if dict(s.mesh.shape).get(SEQ_AXIS, 1) > 1:
                return s.mesh
            return None  # one placement per param tree; first leaf decides
    return None


@contextlib.contextmanager
def capture_ring_calls():
    """Observe ring_attention invocations (dryrun/test instrumentation):
    yields a list that accumulates each call's q shape.

    The package re-exports the function under its own name, so the real
    submodule is fetched via importlib (attribute-style ``import a.b as
    m`` would grab the function) and its attribute is swapped for the
    duration — ops.attention imports it at call time, so the swap is
    always observed."""
    import importlib

    mod = importlib.import_module("chiaswarm_tpu.parallel.ring_attention")
    calls: list = []
    real = mod.ring_attention

    def observing(*args, **kwargs):
        calls.append(args[0].shape)
        return real(*args, **kwargs)

    mod.ring_attention = observing
    try:
        yield calls
    finally:
        mod.ring_attention = real


def seq_parallel_wrap(jitted, params):
    """Wrap a jitted pipeline program so it traces (and re-traces, after
    executable-LRU rebuilds) under :func:`sequence_parallel` whenever
    ``params`` live on a mesh with a ``seq`` axis > 1 — the single hook
    every pipeline uses to make ring attention a serving path rather than
    a demo. No-seq-mesh callers get the jitted fn back untouched (zero
    overhead on the common path)."""
    mesh = _seq_mesh_of_params(params)
    if mesh is None:
        return jitted

    def wrapped(*args):
        with sequence_parallel(mesh):
            return jitted(*args)

    return wrapped
