"""Multi-host distributed runtime init.

The reference's inter-node story is HTTP to the hive only — there is no
collective backend of any kind (SURVEY.md §2c, verified: no
NCCL/MPI/torch.distributed anywhere in the reference). A TPU pod *is* a
collective machine, so this module owns the two deployment modes:

1. **Fleet mode** (default, mirrors the reference): every host runs an
   independent worker polling the hive; jobs are data-parallel across hosts
   with no cross-host collectives. Nothing to initialize.
2. **Pod mode**: one logical worker spans all hosts of a slice
   (`jax.distributed.initialize`); the mesh covers every chip and big-batch
   or model-sharded jobs run as one multi-controller SPMD program with
   XLA collectives riding ICI (and DCN between slices).

Env contract (standard JAX multi-controller): COORDINATOR_ADDRESS,
NUM_PROCESSES, PROCESS_ID — or TPU metadata auto-detection when present.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger("chiaswarm.distributed")

_initialized = False


def init_pod(coordinator: str | None = None, num_processes: int | None = None,
             process_id: int | None = None) -> None:
    """Initialize the multi-controller runtime (idempotent).

    Call before any jax device op when running pod mode. On single-host
    (or under the CPU test platform) this is a no-op fallback.
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("PROCESS_ID")
    try:
        if coordinator:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            # TPU metadata server path (no args) — only meaningful on TPU VMs
            if jax.default_backend() == "tpu":
                jax.distributed.initialize()
        _initialized = True
        log.info("pod mode: process %s/%s, %d global devices",
                 jax.process_index(), jax.process_count(),
                 len(jax.devices()))
    except Exception as exc:  # single host / already-initialized / CPU tests
        log.info("pod init skipped (%s); running single-controller", exc)
        _initialized = True


def _env_int(name: str) -> int | None:
    raw = os.environ.get(name)
    return int(raw) if raw else None


def is_multi_host() -> bool:
    return jax.process_count() > 1


def local_data_shard(global_batch: int) -> tuple[int, int]:
    """(start, size) of this host's slice of a pod-wide batch."""
    per = global_batch // jax.process_count()
    return jax.process_index() * per, per
