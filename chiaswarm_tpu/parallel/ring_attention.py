"""Ring attention: sequence/context-parallel attention over a mesh axis.

The reference has no long-context story at all (SURVEY.md §5: "Long-context
/ sequence parallelism: ABSENT") — its answer to memory pressure is
attention *slicing* on one GPU (swarm/diffusion/diffusion_func.py:85-88).
The TPU-native answer is to shard the sequence across chips and rotate KV
blocks around the ICI ring with `lax.ppermute`, combining partial softmax
results with the flash-attention running-max recurrence. Memory per chip is
O(L/n); the KV rotation overlaps with the local attention compute (XLA
schedules the ppermute DMA asynchronously).

Use inside `shard_map` with q/k/v sharded on the sequence dimension:

    mesh = build_mesh(MeshSpec({"seq": 8}))
    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=P(None, "seq", None, None),
        out_specs=P(None, "seq", None, None),
    )(q, k, v)

Layout is (B, L, H, D), matching chiaswarm_tpu.ops.attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from chiaswarm_tpu.core.compat import axis_size
from chiaswarm_tpu.obs import numerics as _numerics

_NEG_INF = -1e30


def _partial_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       scale: float):
    """Unnormalized attention over one KV block.

    Returns (o, m, l): o = exp(logits - m) @ v, m = rowmax, l = rowsum,
    shapes o:(B,L,H,D) fp32, m/l:(B,H,L) fp32.
    """
    logits = jnp.einsum("blhd,bshd->bhls", q, k,
                        preferred_element_type=jnp.float32) * scale
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhls,bshd->blhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    scale: float | None = None,
) -> jnp.ndarray:
    """Full (non-causal) attention with L and S sharded on ``axis_name``.

    Each device holds a (B, L/n, H, D) query shard and a (B, S/n, H, D)
    KV shard; after n ppermute rotations every query has attended to every
    key. Non-causal because diffusion spatial/video attention is
    bidirectional; a causal variant would skip post-self blocks.
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    n = axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # The zero-init carries must carry the same varying-axes type as the
    # loop-updated values (shard_map's vma system rejects a mismatch), and
    # q/k/v may vary over OTHER mesh axes too (dp x sp x tp serving: batch
    # on 'data', heads on 'model'). Deriving the zeros from q arithmetic
    # inherits the full varying set on any jax version; XLA folds the
    # zero-multiplies away.
    o0 = (q * 0).astype(jnp.float32)                        # (B, L, H, D)
    zrow = jnp.sum(o0, axis=-1).transpose(0, 2, 1)          # (B, H, L) zeros
    m0 = zrow + _NEG_INF
    l0 = zrow

    # swarmlens per-hop probes (ISSUE 11): when enabled at trace time
    # the scan consumes an explicit hop index and each shard emits its
    # partial-softmax summaries per rotation — the drill-down stream for
    # the seq-parallel divergence bisect. Off (default): xs stays None
    # and the lowered scan is byte-identical to the untapped program.
    tap_on = _numerics.enabled_for("ring")

    def body(carry, hop):
        k_blk, v_blk, o_acc, m_acc, l_acc = carry
        o_i, m_i, l_i = _partial_attention(q, k_blk, v_blk, scale)
        if tap_on:
            shard = jax.lax.axis_index(axis_name)
            o_i = _numerics.tap("ring.hop_partial", o_i,
                                step=hop, shard=shard)
            m_i = _numerics.tap("ring.hop_rowmax", m_i,
                                step=hop, shard=shard)
            l_i = _numerics.tap("ring.hop_rowsum", l_i,
                                step=hop, shard=shard)
        m_new = jnp.maximum(m_acc, m_i)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_i - m_new)
        # (B,H,L) -> (B,L,H,1) to scale the (B,L,H,D) partials
        bcast = lambda x: x.transpose(0, 2, 1)[..., None]
        o_acc = o_acc * bcast(a_old) + o_i * bcast(a_new)
        l_acc = l_acc * a_old + l_i * a_new
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o_acc, m_new, l_acc), None

    (_, _, o, m, l), _ = jax.lax.scan(
        body, (k, v, o0, m0, l0),
        jnp.arange(n) if tap_on else None,
        length=None if tap_on else n,
    )
    out = o / l.transpose(0, 2, 1)[..., None]
    if tap_on:
        out = _numerics.tap("ring.out", out,
                            shard=jax.lax.axis_index(axis_name))
    return out.astype(q.dtype)
