"""GSPMD tensor-parallel partition rules for the model zoo.

The reference never shards a model — each job's weights live wholly on one
GPU (`pipeline.to("cuda:N")`, swarm/diffusion/diffusion_func.py:46). For
models larger than one chip's HBM (SDXL at high batch, cascades, video) the
TPU-native answer is Megatron-style tensor parallelism expressed purely as
*weight sharding annotations*: we lay out the attention/MLP projection
matrices over the ``model`` mesh axis and let GSPMD insert the collectives
(all-gather/reduce-scatter over ICI) during compilation.

Column/row pattern per transformer block (so the pair needs only ONE
all-reduce on the residual, not per-matmul gathers):

- q/k/v projections, MLP up-projection: column-parallel — kernel
  P(None, "model"), bias P("model"): each chip computes its head slice.
- output projection, MLP down-projection: row-parallel — kernel
  P("model", None), bias replicated; GSPMD emits the psum.

Resnet conv pairs follow the same pattern on their CHANNEL dims (no halo
needed — a 1x1-style channel split, not spatial): ``conv1`` is
column-parallel on output channels (with ``time_emb_proj`` and ``norm2``
sharded to match, group stats staying shard-local because tp divides the
32 GroupNorm groups), ``conv2`` is row-parallel on input channels, and
GSPMD emits one psum per resnet block on the residual. SD-class UNets are
~65% conv FLOPs (BASELINE.md op profile), so leaving convs replicated made
tp pay 44% over ideal (MULTICHIP_r03); with the resnet pairs sharded the
per-device FLOPs fraction drops to ~1/(dp*tp) + small residue (conv_in/
out, shortcuts, up/downsamples — measured by dryrun_multichip).

Contraction-dim (row-parallel) sharding for the channel-square stragglers
(r5, VERDICT r4 #4): the SpatialTransformer/TemporalTransformer
proj_in/proj_out (linear OR 1x1-conv spelling), resnet shortcut convs,
and the up/downsample resize convs all consume a REPLICATED activation
and feed a norm or residual that needs full channels again — so the
profitable layout is splitting the input-channel contraction across
``model`` and letting GSPMD emit one psum per op: FLOPs/tp at the cost
of a single all-reduce, with no layout change for producers/consumers.

Still replicated: norms on replicated activations, embeddings, time
MLPs, conv_in/conv_out (4-channel ends — nothing to split). This matches
the scaling-book recipe: annotate the big matmuls, let the compiler
place collectives, profile, iterate.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chiaswarm_tpu.core.mesh import MODEL_AXIS

# column-parallel producers (output dim sharded) and row-parallel consumers
# (input dim sharded); names cover the UNet (to_q/.../ff), the CLIP towers
# (q_proj/.../fc1/fc2) and the VAE mid-attention.
_COLUMN = frozenset({"to_q", "to_k", "to_v", "q_proj", "k_proj", "v_proj",
                     "fc1"})
_ROW = frozenset({"to_out", "out_proj", "fc2"})
_MLP_GLU_UP = "proj_in"     # GEGLU up-projection inside FeedForward ("ff")
_MLP_DOWN = "proj_out"


def _in_resnet(path: tuple[str, ...]) -> bool:
    """Inside a UNet/ControlNet ResnetBlock (down_*_resnets_*,
    mid_resnets_*, up_*_resnets_* — models/unet.py naming). VAE resnets
    share those block names but nest under encoder/decoder submodules and
    are excluded: the VAE is a tiny FLOPs fraction and its small channel
    counts don't divide cleanly across model shards."""
    return (any("resnets" in part for part in path)
            and not any(part in ("encoder", "decoder") for part in path))


def _spec_for(path: tuple[str, ...], ndim: int) -> P:
    if ndim == 0 or not path:
        return P()
    leaf = path[-1]
    parent = path[-2] if len(path) >= 2 else ""
    grandparent = path[-3] if len(path) >= 3 else ""
    # "ff" is the TransformerBlock MLP; "ff_in" is the SVD temporal
    # block's input MLP — same GEGLU pair, same column/row layout
    in_ff = (parent in ("ff", "ff_in")
             or grandparent in ("ff", "ff_in"))

    column = parent in _COLUMN or (in_ff and parent == _MLP_GLU_UP)
    row = parent in _ROW or (in_ff and parent == _MLP_DOWN)

    if leaf == "kernel" and ndim == 2:
        if column:
            return P(None, MODEL_AXIS)
        if row:
            return P(MODEL_AXIS, None)
    if leaf == "bias" and ndim == 1 and column:
        return P(MODEL_AXIS)

    # module-level proj_in/proj_out (SpatialTransformer and the video
    # transformers — NOT the FeedForward pair handled above): plain
    # channel matmuls between a replicated activation and a norm/residual
    # that needs full channels — shard the contraction dim, GSPMD emits
    # one psum (r5; the exclusion this replaces was the last double-digit
    # tp residue, MULTICHIP_r04 0.141 vs 0.125 ideal)
    if not in_ff and parent in ("proj_in", "proj_out") and leaf == "kernel":
        if ndim == 2:
            return P(MODEL_AXIS, None)
        if ndim == 4:          # the 1x1-conv spelling (SD1.5-class)
            return P(None, None, MODEL_AXIS, None)

    # up/downsample resize convs (UNet modules wrap the conv in a
    # ``conv`` submodule; the VAE's bare-conv spelling stays replicated)
    if parent == "conv" and leaf == "kernel" and ndim == 4 and \
            ("downsample" in grandparent or "upsample" in grandparent):
        return P(None, None, MODEL_AXIS, None)

    # resnet conv pair: channel-wise Megatron (conv1 output channels /
    # conv2 input channels), with the in-between time projection and
    # GroupNorm sharded to match
    if _in_resnet(path):
        if parent == "conv1":
            if leaf == "kernel" and ndim == 4:   # HWIO, O sharded
                return P(None, None, None, MODEL_AXIS)
            if leaf == "bias" and ndim == 1:
                return P(MODEL_AXIS)
        if parent == "conv2" and leaf == "kernel" and ndim == 4:
            return P(None, None, MODEL_AXIS, None)  # I sharded (row)
        if parent == "time_emb_proj":
            if leaf == "kernel" and ndim == 2:
                return P(None, MODEL_AXIS)
            if leaf == "bias" and ndim == 1:
                return P(MODEL_AXIS)
        if parent == "norm2" and ndim == 1:      # scale/bias over conv1 out
            return P(MODEL_AXIS)
        if parent == "conv_shortcut" and leaf == "kernel" and ndim == 4:
            # 1x1 channel-change conv off the replicated block input:
            # contraction-dim split + psum, like proj_in/proj_out
            return P(None, None, MODEL_AXIS, None)
    return P()  # replicated: norms, embeddings, time MLPs, conv_in/out


def param_partition_specs(params: Any) -> Any:
    """PartitionSpec pytree matching ``params`` (Components.params or any
    sub-tree)."""

    def spec(path, leaf) -> P:
        names = tuple(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        return _spec_for(names, getattr(leaf, "ndim", 0))

    return jax.tree_util.tree_map_with_path(spec, params)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree for ``params`` on ``mesh``."""
    specs = param_partition_specs(params)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place ``params`` onto ``mesh`` according to the partition rules.

    With |model| = 1 every spec degenerates to replication, so single-chip
    and multi-chip share one code path (same stance as
    core/mesh.py:single_device_mesh).
    """
    shardings = param_shardings(params, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
