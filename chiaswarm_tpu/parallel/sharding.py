"""GSPMD tensor-parallel partition rules for the model zoo.

The reference never shards a model — each job's weights live wholly on one
GPU (`pipeline.to("cuda:N")`, swarm/diffusion/diffusion_func.py:46). For
models larger than one chip's HBM (SDXL at high batch, cascades, video) the
TPU-native answer is Megatron-style tensor parallelism expressed purely as
*weight sharding annotations*: we lay out the attention/MLP projection
matrices over the ``model`` mesh axis and let GSPMD insert the collectives
(all-gather/reduce-scatter over ICI) during compilation.

Column/row pattern per transformer block (so the pair needs only ONE
all-reduce on the residual, not per-matmul gathers):

- q/k/v projections, MLP up-projection: column-parallel — kernel
  P(None, "model"), bias P("model"): each chip computes its head slice.
- output projection, MLP down-projection: row-parallel — kernel
  P("model", None), bias replicated; GSPMD emits the psum.

Convolutions and norms stay replicated: for UNet resnet convs the win is
small relative to the halo/collective cost, and batch ("data") parallelism
covers them. This matches the scaling-book recipe: annotate the big
matmuls, let the compiler place collectives, profile, iterate.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chiaswarm_tpu.core.mesh import MODEL_AXIS

# column-parallel producers (output dim sharded) and row-parallel consumers
# (input dim sharded); names cover the UNet (to_q/.../ff), the CLIP towers
# (q_proj/.../fc1/fc2) and the VAE mid-attention.
_COLUMN = frozenset({"to_q", "to_k", "to_v", "q_proj", "k_proj", "v_proj",
                     "fc1"})
_ROW = frozenset({"to_out", "out_proj", "fc2"})
_MLP_GLU_UP = "proj_in"     # GEGLU up-projection inside FeedForward ("ff")
_MLP_DOWN = "proj_out"


def _spec_for(path: tuple[str, ...], ndim: int) -> P:
    if ndim == 0 or not path:
        return P()
    leaf = path[-1]
    parent = path[-2] if len(path) >= 2 else ""
    grandparent = path[-3] if len(path) >= 3 else ""
    in_ff = parent == "ff" or grandparent == "ff"

    column = parent in _COLUMN or (in_ff and parent == _MLP_GLU_UP)
    row = parent in _ROW or (in_ff and parent == _MLP_DOWN)

    if leaf == "kernel" and ndim == 2:
        if column:
            return P(None, MODEL_AXIS)
        if row:
            return P(MODEL_AXIS, None)
    if leaf == "bias" and ndim == 1 and column:
        return P(MODEL_AXIS)
    return P()  # replicated: convs, norms, embeddings, time MLPs


def param_partition_specs(params: Any) -> Any:
    """PartitionSpec pytree matching ``params`` (Components.params or any
    sub-tree)."""

    def spec(path, leaf) -> P:
        names = tuple(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        return _spec_for(names, getattr(leaf, "ndim", 0))

    return jax.tree_util.tree_map_with_path(spec, params)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree for ``params`` on ``mesh``."""
    specs = param_partition_specs(params)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place ``params`` onto ``mesh`` according to the partition rules.

    With |model| = 1 every spec degenerates to replication, so single-chip
    and multi-chip share one code path (same stance as
    core/mesh.py:single_device_mesh).
    """
    shardings = param_shardings(params, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
