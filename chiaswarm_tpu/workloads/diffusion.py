"""Stable-diffusion workload callback: txt2img / img2img / inpaint.

Capability parity with swarm/diffusion/diffusion_func.py:14-124, redesigned
for the TPU runtime: instead of building a diffusers pipeline per job, the
job binds to a resident compile-cached DiffusionPipeline (node/registry.py)
and runs one jitted program. Memory-pressure heuristics (xformers/VAE
slicing/CPU offload, diffusion_func.py:76-94) have no TPU analog — the
equivalents are always on: Pallas flash attention, tiled VAE decode for
large outputs, bf16 weights.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import OutputProcessor
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import phase_checkpoint
from chiaswarm_tpu.obs.trace import span
from chiaswarm_tpu.pipelines.diffusion import GenerateRequest


def diffusion_callback(slot, model_name: str, *, seed: int,
                       registry: ModelRegistry,
                       prompt: str = "",
                       negative_prompt: str = "",
                       num_inference_steps: int = 30,
                       guidance_scale: float = 7.5,
                       height: int | None = None,
                       width: int | None = None,
                       num_images_per_prompt: int = 1,
                       image: np.ndarray | None = None,
                       mask_image: np.ndarray | None = None,
                       strength: float = 0.75,
                       image_guidance_scale: float | None = None,
                       scheduler_type: str | None = None,
                       content_type: str = "image/png",
                       upscale: bool = False,
                       upscaler_model_name: str = (
                           "stabilityai/sd-x2-latent-upscaler"),
                       controlnet_model_name: str | None = None,
                       controlnet_scale: float = 1.0,
                       save_preprocessed_input: bool = False,
                       textual_inversion: str | None = None,
                       lora: str | None = None,
                       cross_attention_scale: float = 1.0,
                       reuse_schedule: Any = None,
                       outputs: tuple[str, ...] = ("primary",),
                       **_ignored: Any):
    # ``lora`` + ``cross_attention_scale`` are the reference's per-job LoRA
    # contract (swarm/diffusion/diffusion_func.py:20-22,58-68); here the
    # scaled deltas merge into a separately-cached param tree at load time
    pipe = registry.pipeline(model_name, textual_inversion=textual_inversion,
                             lora=lora, lora_scale=cross_attention_scale,
                             mesh=getattr(slot, "mesh", None))
    from chiaswarm_tpu.serving.residency import is_transient

    degraded = is_transient(pipe)  # load-per-job rung (serving/residency.py)
    fam = pipe.c.family
    if fam.kind != "sd":
        raise ValueError(
            f"model {model_name!r} is a {fam.kind} model, not a generation "
            f"pipeline; upscalers run via the server's 'upscale' parameter"
        )

    if image is not None:
        height, width = image.shape[:2]
    height = int(height or fam.default_size)
    width = int(width or fam.default_size)

    controlnet = None
    control_image = None
    if controlnet_model_name is not None:
        if fam.image_conditioned:
            raise ValueError(
                "instruct-pix2pix models do not support controlnet; the "
                "input image already conditions generation"
            )
        if mask_image is not None:
            raise ValueError(
                "controlnet jobs cannot also carry a mask_image; the input "
                "image is the conditioning image, not an inpainting source"
            )
        # the fetched input IS the (preprocessed) conditioning image — it
        # steers generation instead of seeding latents
        # (swarm/job_arguments.py:116-124)
        controlnet = registry.controlnet(controlnet_model_name, fam,
                                         mesh=getattr(slot, "mesh", None))
        control_image, image = image, None

    if image_guidance_scale is not None and not fam.image_conditioned:
        # image_guidance on a non-pix2pix checkpoint: honor the user's
        # intent through img2img strength (hive sends strength*5,
        # node/job_args.py remap)
        strength = min(1.0, max(0.05, float(image_guidance_scale) / 5.0))

    mask = None
    if mask_image is not None:
        m = np.asarray(mask_image, dtype=np.float32)
        if m.ndim == 3:
            m = m.mean(axis=-1)
        mask = m / 255.0 if m.max() > 1.0 else m

    req = GenerateRequest(
        prompt=prompt or "",
        negative_prompt=negative_prompt or "",
        steps=int(num_inference_steps),
        guidance_scale=float(guidance_scale),
        height=height,
        width=width,
        batch=max(1, int(num_images_per_prompt)),
        seed=seed,
        scheduler=scheduler_type,
        init_image=image,
        strength=float(strength),
        mask=mask,
        tiled_decode=max(height, width) > 1024,
        controlnet=controlnet,
        control_image=control_image,
        control_scale=float(controlnet_scale),
        image_guidance_scale=float(image_guidance_scale
                                   if image_guidance_scale is not None
                                   else 1.5),
        # DeepCache step-level reuse (ISSUE 12): engages only behind
        # CHIASWARM_DEEPCACHE; the pipeline normalizes and quality-gates
        reuse_schedule=(tuple(reuse_schedule)
                        if isinstance(reuse_schedule, (list, tuple))
                        else reuse_schedule),
    )
    # coarse phase checkpoints (ISSUE 6): the solo program has no step
    # boundary to snapshot at (encode/denoise/decode fuse into one
    # dispatch), so the spool records phase markers instead — "encoded"
    # once the model is bound and inputs are staged, "denoised" once the
    # expensive generation finished. A redelivered solo job restarts its
    # phase; the marker tells the fleet telemetry (and the operator) how
    # much chip time the death cost. Lane-riding jobs get real
    # step-boundary resume instead (serving/stepper.py).
    phase_checkpoint("encoded", model=str(model_name))
    t0 = time.perf_counter()
    images, config = pipe(req)
    elapsed = time.perf_counter() - t0
    phase_checkpoint("denoised", model=str(model_name),
                     generation_s=round(elapsed, 3))

    if upscale:
        # x2 latent upscale pass over the generated images, 20 steps at
        # guidance 0 (swarm/diffusion/upscale.py:6-32)
        upscaler = registry.pipeline(upscaler_model_name,
                                     mesh=getattr(slot, "mesh", None))
        images, up_config = upscaler(images, prompt=prompt or "", seed=seed)
        config.update(up_config)

    # swarmguard post-decode screen (ISSUE 10): a NaN-poisoned
    # trajectory must raise invalid_output here, never upload as a
    # "completed" black frame (serving/guard.py)
    from chiaswarm_tpu.serving.guard import screen_images

    screen_images(images, context="solo decode")

    proc = OutputProcessor(content_type)
    proc.add_images(images)
    if control_image is not None and save_preprocessed_input:
        # echo the preprocessed conditioning image back as an extra
        # artifact (swarm/diffusion/diffusion_func.py:36-39)
        proc.add_images(np.asarray(control_image, dtype=np.uint8),
                        key="preprocessed_input")
    artifacts = proc.get_results()

    if textual_inversion is not None:
        config["textual_inversion"] = textual_inversion
    if lora is not None:
        config["lora"] = lora
        config["cross_attention_scale"] = float(cross_attention_scale)
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(images, model_name)
    config.update(safety_fields)
    config.update({
        "images_per_sec": round(images.shape[0] / max(elapsed, 1e-9), 4),
        "generation_s": round(elapsed, 3),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    if degraded:
        # observable per job: this result paid a load (the model exceeds
        # the residency budget and serves load -> run -> release)
        config["residency"] = "per_job"
    return artifacts, config


# ---- cross-job coalescing (no reference analog) -----------------------
#
# A dp-sharded mesh slot replicates a batch=1 job on every data row —
# (dp-1)/dp of the slot does duplicate work. Compatible txt2img jobs
# (same model/size/steps/guidance/scheduler/adapters, no input images)
# instead ride ONE batched program: per-row prompts and per-row
# (seed, row) noise keys keep every job's images identical to its solo
# run (pipelines/diffusion.py sample_seed_rows). The executor groups
# queue bursts by COALESCE_KEYS (node/executor.py).

COALESCE_KEYS = ("num_inference_steps", "guidance_scale", "height",
                 "width", "scheduler_type", "textual_inversion", "lora",
                 "cross_attention_scale", "strength", "reuse_schedule")
# ControlNet conditions on the image (different program); pix2pix jobs
# carry image_guidance_scale (dual-CFG family, kept solo). Plain img2img
# and inpaint DO coalesce since r5: per-job init stacks + per-job
# VAE-encode seeds keep every job's images equal to its solo run
# (pipelines/diffusion.py GenerateRequest.init_groups).
_UNCOALESCABLE = ("controlnet_model_name", "image_guidance_scale")


def coalescable(kwargs: dict[str, Any]) -> bool:
    # upscale jobs run their x2 pass with the job's OWN prompt/seed —
    # batching them would condition every job on job 0's; keep them solo
    return (not kwargs.get("upscale")
            and all(kwargs.get(k) is None for k in _UNCOALESCABLE))


# ---- continuous step-level batching (serving/stepper.py) ---------------
#
# Lanes are the DEFAULT engine (ISSUE 7; CHIASWARM_STEPPER=0 opts out):
# eligible diffusion jobs skip the burst grouping entirely — each job's
# rows splice into the resident step loop of its lane at the next step
# boundary. Steps, guidance, img2img start indices, inpaint mask/known
# stacks and ControlNet hint embeddings all ride PER ROW, so txt2img,
# img2img and inpaint jobs with different parameters share one program
# (ControlNet rows ride bundle-keyed lanes), and a job arriving one poll
# late no longer waits behind a full solo program. The residue
# (pix2pix/upscale, explicit image_guidance remaps, low guidance,
# oversize, steps beyond the lattice) falls back to the burst/solo
# paths below.

def stepper_eligible(kwargs: dict[str, Any]) -> bool:
    """Can this (formatted) job ride a lane? Conservative pre-filter —
    serving.stepper.StepScheduler.submit_request is the authority and
    raises LaneReject for the residue (steps beyond the capacity
    lattice, rows wider than the lane cap, non-sd / pix2pix families)."""
    from chiaswarm_tpu.serving.stepper import stepper_enabled

    if not stepper_enabled():
        return False
    if kwargs.get("upscale"):
        return False  # the x2 pass chains a second pipeline — solo
    if kwargs.get("image_guidance_scale") is not None:
        return False  # pix2pix dual CFG / strength remap stays solo
    guidance = kwargs.get("guidance_scale")
    if guidance is not None and float(guidance) <= 1.0:
        # few-step kinds (ISSUE 12) are guidance-embedded: their native
        # CFG-free mode still rides lanes — the lane program's per-row
        # combine selects the pure conditional prediction
        from chiaswarm_tpu.schedulers.sampling import (
            FEWSTEP_KINDS,
            SAMPLERS,
        )

        if SAMPLERS.get(kwargs.get("scheduler_type") or "") not in \
                FEWSTEP_KINDS:
            return False  # solo compiles the no-CFG program
    if kwargs.get("mask_image") is not None \
            and kwargs.get("controlnet_model_name") is not None:
        return False  # invalid combination — solo raises the user error
    height = kwargs.get("height")
    width = kwargs.get("width")
    image = kwargs.get("image")
    if image is not None and getattr(image, "ndim", 0) >= 2:
        height, width = int(image.shape[0]), int(image.shape[1])
    if (height and int(height) > 1024) or (width and int(width) > 1024):
        return False  # tiled decode stays solo
    return True


@dataclasses.dataclass
class StepperTicket:
    """A submitted lane job: resolves through ``stepper_finish`` into the
    same (artifacts, config) contract the solo callback returns."""

    future: Any
    model_name: str
    family: str
    sampler_kind: str
    steps: int
    guidance: float
    req_hw: tuple[int, int]
    compiled_hw: tuple[int, int]
    rows: int
    seed: int
    content_type: str
    shared: dict[str, Any]
    slot: Any
    t0: float
    mode: str = "txt2img"
    denoise_steps: int = 0
    controlnet_name: str | None = None
    controlnet_scale: float = 1.0


def stepper_submit(slot, registry: ModelRegistry, kwargs: dict[str, Any],
                   seed: int, job_id: Any = None) -> StepperTicket:
    """Hand one formatted diffusion job (txt2img / img2img / inpaint /
    ControlNet, ISSUE 7) to the slot's step scheduler. Raises
    serving.stepper.LaneReject (or anything else) when the job must run
    through the ordinary path instead."""
    from chiaswarm_tpu.core.compile_cache import bucket_image_size
    from chiaswarm_tpu.schedulers import resolve
    from chiaswarm_tpu.serving.residency import is_transient
    from chiaswarm_tpu.serving.stepper import LaneReject, get_stepper

    model_name = kwargs.get("model_name")
    scale = kwargs.get("cross_attention_scale")
    pipe = registry.pipeline(
        model_name,
        textual_inversion=kwargs.get("textual_inversion"),
        lora=kwargs.get("lora"),
        lora_scale=1.0 if scale is None else float(scale),
        mesh=getattr(slot, "mesh", None))
    if is_transient(pipe):
        # degradation rung (serving/residency.py): a lane would hold the
        # over-budget params resident between jobs — run load-per-job
        # solo instead. The executor's lane_resident_ok pre-check makes
        # this a first-ever-load-only cost.
        raise LaneReject(
            f"model {model_name!r} degraded to load-per-job (residency)")
    fam = pipe.c.family
    image = kwargs.get("image")
    # ControlNet: the fetched input IS the conditioning image (exactly
    # the solo callback's remap); the bundle keys the lane
    controlnet = None
    control_image = None
    controlnet_name = kwargs.get("controlnet_model_name")
    if controlnet_name is not None:
        controlnet = registry.controlnet(controlnet_name, fam,
                                         mesh=getattr(slot, "mesh", None))
        control_image, image = image, None
    if image is not None:
        height, width = int(image.shape[0]), int(image.shape[1])
    else:
        height = int(kwargs.get("height") or fam.default_size)
        width = int(kwargs.get("width") or fam.default_size)
    steps = max(1, int(kwargs.get("num_inference_steps") or 30))
    guidance = kwargs.get("guidance_scale")
    guidance = 7.5 if guidance is None else float(guidance)
    rows = max(1, int(kwargs.get("num_images_per_prompt") or 1))
    # None-check, not `or`: strength=0.0 (near-identity img2img) and
    # controlnet_scale=0.0 (zero conditioning) are valid values the
    # solo callback honors — the lane path must quantize the same way
    strength = kwargs.get("strength")
    strength = 0.75 if strength is None else float(strength)
    cscale = kwargs.get("controlnet_scale")
    cscale = 1.0 if cscale is None else float(cscale)
    mask = None
    if kwargs.get("mask_image") is not None:
        # same normalization the solo callback applies before the
        # pipeline's latent-grid quantization
        m = np.asarray(kwargs["mask_image"], dtype=np.float32)
        if m.ndim == 3:
            m = m.mean(axis=-1)
        mask = m / 255.0 if m.max() > 1.0 else m
    # mode + executed-ladder suffix, mirroring the solo config contract
    # (the strength -> start-index quantization is an observable field)
    mode = ("inpaint" if mask is not None else
            "img2img" if image is not None else "txt2img")
    start_step = 0
    if mode == "img2img":
        from chiaswarm_tpu.pipelines.diffusion import img2img_start_index

        start_step = img2img_start_index(steps, strength)
    # redelivered jobs carry their dead worker's last lane checkpoint
    # (node/minihive.py): the scheduler splices the rows back in at the
    # recorded step instead of restarting at 0. A solo-path PHASE marker
    # (the dead worker ran this job outside a lane) carries no lane
    # state to splice — filter it silently, it is a routine redelivery,
    # not the tamper/corruption signal ResumeReject counts.
    resume = kwargs.get("resume")
    if not (isinstance(resume, dict) and resume.get("kind") == "lane"):
        resume = None
    future = get_stepper(slot).submit_request(
        pipe,
        prompt=str(kwargs.get("prompt") or ""),
        negative_prompt=str(kwargs.get("negative_prompt") or ""),
        steps=steps, guidance_scale=guidance,
        height=height, width=width, rows=rows, seed=int(seed),
        scheduler=kwargs.get("scheduler_type"),
        job_id=job_id,
        resume=resume,
        init_image=image, strength=strength, mask=mask,
        controlnet=controlnet, control_image=control_image,
        control_scale=cscale,
        reuse_schedule=kwargs.get("reuse_schedule"))
    sampler = resolve(kwargs.get("scheduler_type"),
                      prediction_type=fam.prediction_type)
    return StepperTicket(
        future=future, model_name=model_name, family=fam.name,
        sampler_kind=sampler.kind, steps=steps, guidance=guidance,
        req_hw=(height, width),
        compiled_hw=bucket_image_size(height, width),
        rows=rows, seed=int(seed),
        content_type=kwargs.get("content_type", "image/png"),
        shared={k: kwargs.get(k) for k in ("textual_inversion", "lora",
                                           "cross_attention_scale")},
        slot=slot, t0=time.perf_counter(),
        mode=mode, denoise_steps=steps - start_step,
        controlnet_name=controlnet_name,
        controlnet_scale=cscale)


def stepper_finish(ticket: StepperTicket):
    """Block on the lane rows, then postprocess exactly like the solo
    callback (un-bucket crop, safety, artifact encode)."""
    # the job's "step" span: admission wait + its rows' residency in the
    # lane's denoise loop (the lane-side timeline rides in as metadata)
    with span("step", steps=ticket.steps, rows=ticket.rows) as step_span:
        pending, lane_info = ticket.future.result()
        step_span.meta.update(lane_info)
    # the lane decodes at the compiled bucket; un-bucket to the request
    pending.requested_hw = ticket.req_hw
    images = pending.wait()
    # swarmguard post-decode screen (ISSUE 10): rows whose poisoning
    # slipped past the checkpoint-boundary finite-check (e.g. a job
    # retiring between boundaries) are caught here — the envelope says
    # invalid_output, the garbage frame never uploads
    from chiaswarm_tpu.serving.guard import screen_images

    screen_images(images, context="lane decode")
    elapsed = time.perf_counter() - ticket.t0

    proc = OutputProcessor(ticket.content_type)
    proc.add_images(images)
    config = {
        "model_name": ticket.model_name,
        "family": ticket.family,
        "scheduler": ticket.sampler_kind,
        "steps": ticket.steps,
        "denoise_steps": ticket.denoise_steps or ticket.steps,
        "guidance_scale": ticket.guidance,
        "size": list(ticket.req_hw),
        "compiled_size": list(ticket.compiled_hw),
        "batch": ticket.rows,
        "mode": ticket.mode,
        "seed": ticket.seed,
        "stepper": dict(lane_info),
    }
    if ticket.controlnet_name is not None:
        config["controlnet"] = ticket.controlnet_name
        config["controlnet_scale"] = ticket.controlnet_scale
    if ticket.shared.get("textual_inversion") is not None:
        config["textual_inversion"] = ticket.shared["textual_inversion"]
    if ticket.shared.get("lora") is not None:
        config["lora"] = ticket.shared["lora"]
        scale = ticket.shared.get("cross_attention_scale")
        config["cross_attention_scale"] = (1.0 if scale is None
                                           else float(scale))
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(images, ticket.model_name)
    config.update(safety_fields)
    config.update({
        "images_per_sec": round(images.shape[0] / max(elapsed, 1e-9), 4),
        "generation_s": round(elapsed, 3),
        "slot": (ticket.slot.descriptor()
                 if hasattr(ticket.slot, "descriptor")
                 else str(ticket.slot)),
    })
    return proc.get_results(), config


def diffusion_coalesced_callback(slot, model_name: str, *, seed: int,
                                 registry: ModelRegistry,
                                 jobs: list[dict[str, Any]],
                                 **shared: Any):
    """Run several compatible jobs as one batched program.

    ``jobs`` carries each job's per-row fields ({prompt, negative_prompt,
    num_images_per_prompt, seed, content_type}); ``shared`` carries the
    COALESCE_KEYS the executor verified equal. Returns a LIST of
    per-job (artifacts, config) in input order."""
    first = jobs[0]
    prompts: list[str] = []
    negs: list[str] = []
    seed_rows: list[tuple[int, int]] = []
    counts: list[int] = []
    for job in jobs:
        n = max(1, int(job.get("num_images_per_prompt", 1)))
        prompts += [str(job.get("prompt") or "")] * n
        negs += [str(job.get("negative_prompt") or "")] * n
        seed_rows += [(int(job["seed"]), r) for r in range(n)]
        counts.append(n)

    def opt(key: str, default):
        value = shared.get(key)  # present-but-None means "use default"
        return default if value is None else value

    pipe = registry.pipeline(
        model_name,
        textual_inversion=shared.get("textual_inversion"),
        lora=shared.get("lora"),
        lora_scale=opt("cross_attention_scale", 1.0),
        mesh=getattr(slot, "mesh", None))
    fam = pipe.c.family

    # img2img/inpaint: per-JOB init/mask stacks + per-job encode seeds
    # (the executor's coalesce key guarantees uniform image shapes and
    # mask presence across the group)
    has_img = first.get("image") is not None
    init_stack = mask_stack = init_groups = None
    if has_img:
        if fam.image_conditioned:
            # pix2pix-family jobs are excluded upstream; a miss here must
            # fall back to the per-job path, not mis-serve dual CFG
            raise ValueError("image-conditioned (pix2pix) jobs do not "
                             "coalesce")
        init_stack = np.stack([np.asarray(j["image"]) for j in jobs])
        init_groups = tuple((int(j["seed"]), n)
                            for j, n in zip(jobs, counts))
        if first.get("mask_image") is not None:
            masks = []
            for job in jobs:
                m = np.asarray(job["mask_image"], dtype=np.float32)
                if m.ndim == 3:
                    m = m.mean(axis=-1)
                masks.append(m / 255.0 if m.max() > 1.0 else m)
            mask_stack = np.stack(masks)
        height, width = init_stack.shape[1:3]
    else:
        height = int(opt("height", fam.default_size))
        width = int(opt("width", fam.default_size))

    req = GenerateRequest(
        prompt=tuple(prompts),
        negative_prompt=tuple(negs),
        steps=int(opt("num_inference_steps", 30)),
        guidance_scale=float(opt("guidance_scale", 7.5)),
        height=int(height),
        width=int(width),
        batch=len(prompts),
        seed=int(first["seed"]),
        sample_seed_rows=tuple(seed_rows),
        scheduler=shared.get("scheduler_type"),
        init_image=init_stack,
        init_groups=init_groups,
        strength=float(opt("strength", 0.75)),
        mask=mask_stack,
        tiled_decode=max(int(height), int(width)) > 1024,
        # part of the coalesce key, so every member shares one schedule
        reuse_schedule=(tuple(shared["reuse_schedule"])
                        if isinstance(shared.get("reuse_schedule"),
                                      (list, tuple))
                        else shared.get("reuse_schedule")),
    )
    t0 = time.perf_counter()
    images, base_config = pipe(req)
    elapsed = time.perf_counter() - t0

    # swarmguard post-decode screen (ISSUE 10): the invariant — no
    # poisoned frame ever uploads — must hold on the coalesced path
    # too. Raising fails the WHOLE batched run, and the executor's
    # fallback re-runs every member per-job (zero-loss): the poisoned
    # job then gets its precise invalid_output envelope from the solo
    # screen while healthy peers complete.
    from chiaswarm_tpu.serving.guard import screen_images

    screen_images(images, context="coalesced decode")

    from chiaswarm_tpu.workloads.safety import check_images

    results = []
    offset = 0
    for job, n in zip(jobs, counts):
        imgs = images[offset:offset + n]
        offset += n
        proc = OutputProcessor(job.get("content_type", "image/png"))
        proc.add_images(imgs)
        config = dict(base_config)
        config["seed"] = int(job["seed"])
        config["batch"] = n
        # same adapter metadata the solo path records
        if shared.get("textual_inversion") is not None:
            config["textual_inversion"] = shared["textual_inversion"]
        if shared.get("lora") is not None:
            config["lora"] = shared["lora"]
            config["cross_attention_scale"] = float(
                opt("cross_attention_scale", 1.0))
        _, safety_fields = check_images(imgs, model_name)
        config.update(safety_fields)
        config.update({
            "coalesced": len(jobs),
            # per-job number keeps solo semantics (this job's images over
            # this job's wall time); the whole program's throughput is
            # reported separately so aggregators do not k-fold overcount
            "images_per_sec": round(n / max(elapsed, 1e-9), 4),
            "batch_images_per_sec": round(
                images.shape[0] / max(elapsed, 1e-9), 4),
            "generation_s": round(elapsed, 3),
            "slot": (slot.descriptor() if hasattr(slot, "descriptor")
                     else str(slot)),
        })
        results.append((proc.get_results(), config))
    return results
