"""Video workloads: vid2vid (frame-batched img2img) and txt2vid.

vid2vid capability parity with swarm/video/pix2pix.py:14-197 — download
(≤30 MiB guard), normalize to ≤30 fps at 512-height, split frames, diffuse
each frame, reassemble, thumbnail from frame 0, and report the compute-cost
metric (512*512*steps*frames, pix2pix.py:85, the reference's only cost
accounting).

TPU-first redesign of the hot loop: the reference diffuses frames one at a
time in a Python loop (pix2pix.py:53); here frames ride the *batch axis* of
the jitted pipeline (data-parallel across the mesh), so a 16-frame chunk is
one compiled program execution instead of 16 sequential pipeline runs.

Container IO uses OpenCV (no ffmpeg binary in this image): mp4/mp4v or
webm/VP90, matching the reference's format switch (tx2vid.py:59-69).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import make_result
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.pipelines.diffusion import GenerateRequest

MAX_VIDEO_BYTES = 30 * 1048576  # pix2pix.py:100-104
MAX_FRAMES = 100                # pix2pix.py:53
FRAME_HEIGHT = 512              # pix2pix.py:154-170
MAX_FPS = 30.0
FRAME_CHUNK = 8                 # frames per jitted batch


def _download_video(uri: str) -> str:
    import requests

    head = requests.head(uri, allow_redirects=True, timeout=30)
    length = int(head.headers.get("Content-Length", 0) or 0)
    if length > MAX_VIDEO_BYTES:
        raise ValueError(
            f"Input video too large. Max size is {MAX_VIDEO_BYTES} bytes; "
            f"video was {length}."
        )
    response = requests.get(uri, allow_redirects=True, timeout=120)
    response.raise_for_status()
    if len(response.content) > MAX_VIDEO_BYTES:
        raise ValueError("Input video too large.")
    fd, path = tempfile.mkstemp(suffix=".mp4")
    with os.fdopen(fd, "wb") as fh:
        fh.write(response.content)
    return path


def _read_frames(path: str) -> tuple[list[np.ndarray], float]:
    """Decode, downscale to 512-height / even width, cap fps and count.

    High-fps inputs are *subsampled* (every k-th frame), not just relabeled,
    so output timing matches the source."""
    import cv2

    cap = cv2.VideoCapture(path)
    if not cap.isOpened():
        raise ValueError("could not decode input video")
    src_fps = cap.get(cv2.CAP_PROP_FPS) or MAX_FPS
    stride = max(1, int(np.ceil(src_fps / MAX_FPS)))
    fps = src_fps / stride
    frames: list[np.ndarray] = []
    index = 0
    while len(frames) < MAX_FRAMES:
        ok, frame = cap.read()
        if not ok:
            break
        if index % stride:
            index += 1
            continue
        index += 1
        h, w = frame.shape[:2]
        if h > FRAME_HEIGHT:
            new_w = int(w * FRAME_HEIGHT / h) // 2 * 2
            frame = cv2.resize(frame, (new_w, FRAME_HEIGHT),
                               interpolation=cv2.INTER_AREA)
        frames.append(cv2.cvtColor(frame, cv2.COLOR_BGR2RGB))
    cap.release()
    if not frames:
        raise ValueError("input video contained no frames")
    return frames, float(fps)


def _write_video(frames: list[np.ndarray], fps: float,
                 content_type: str) -> bytes:
    import cv2

    suffix, fourcc = ((".webm", "VP90") if "webm" in content_type
                      else (".mp4", "mp4v"))
    fd, path = tempfile.mkstemp(suffix=suffix)
    os.close(fd)
    try:
        h, w = frames[0].shape[:2]
        writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*fourcc),
                                 fps, (w, h))
        if not writer.isOpened():
            raise ValueError(f"cannot encode {content_type} on this node")
        for frame in frames:
            writer.write(cv2.cvtColor(frame, cv2.COLOR_RGB2BGR))
        writer.release()
        with open(path, "rb") as fh:
            return fh.read()
    finally:
        os.unlink(path)


def _video_artifacts(frames: list[np.ndarray], fps: float,
                     content_type: str) -> dict:
    """Shared video artifact packaging: encoded container + frame-0
    thumbnail (tx2vid.py:73's thumbnail behavior, both video workflows)."""
    from PIL import Image

    from chiaswarm_tpu.node.output_processor import encode_image, thumbnail

    blob = _write_video(frames, fps, content_type)
    frame0 = Image.fromarray(frames[0])
    thumb_bytes = thumbnail(frame0)
    return {
        "primary": make_result(blob, content_type, thumb_bytes),
        "thumbnail": make_result(encode_image(frame0, "image/jpeg"),
                                 "image/jpeg", thumb_bytes),
    }


def vid2vid_callback(slot, model_name: str, *, seed: int,
                     registry: ModelRegistry,
                     video_uri: str = "",
                     prompt: str = "",
                     negative_prompt: str = "",
                     num_inference_steps: int = 25,
                     guidance_scale: float = 7.5,
                     strength: float = 0.6,
                     image_guidance_scale: float | None = None,
                     content_type: str = "video/mp4",
                     frames: list[np.ndarray] | None = None,
                     fps: float | None = None,
                     **_ignored: Any):
    """``frames``/``fps`` allow direct injection for hermetic tests."""
    if frames is None:
        if not video_uri:
            raise ValueError("vid2vid requires video_uri")
        path = _download_video(video_uri)
        try:
            frames, fps = _read_frames(path)
        finally:
            os.unlink(path)
    fps = float(fps or 8.0)

    pipe = registry.pipeline(model_name,
                             mesh=getattr(slot, "mesh", None))
    h, w = frames[0].shape[:2]
    if image_guidance_scale is not None:
        # reference remap arrives as image_guidance_scale = strength*5
        strength = min(1.0, max(0.05, image_guidance_scale / 5.0))

    out_frames: list[np.ndarray] = []
    for start in range(0, len(frames), FRAME_CHUNK):
        chunk = frames[start:start + FRAME_CHUNK]
        batch = np.stack(chunk)  # frames ride the batch axis
        req = GenerateRequest(
            prompt=prompt, negative_prompt=negative_prompt,
            steps=int(num_inference_steps),
            guidance_scale=float(guidance_scale),
            height=h, width=w, batch=len(chunk), seed=seed + start,
            init_image=batch, strength=float(strength),
        )
        images, _ = pipe(req)
        out_frames.extend(images)

    artifacts = _video_artifacts(out_frames, fps, content_type)
    from chiaswarm_tpu.workloads.safety import check_images

    # per-frame OR-ing, the reference's vid2vid semantics (pix2pix.py:68,84)
    _, safety_fields = check_images(np.stack(out_frames), model_name)
    config = {
        **safety_fields,
        "model_name": model_name,
        "frames": len(out_frames),
        "fps": fps,
        # the reference's cost model, pix2pix.py:85
        "compute_cost": 512 * 512 * int(num_inference_steps) * len(out_frames),
    }
    return artifacts, config


def txt2vid_callback(slot, model_name: str, *, seed: int,
                     registry: ModelRegistry,
                     prompt: str = "",
                     negative_prompt: str = "",
                     num_frames: int = 25,
                     num_inference_steps: int = 25,
                     guidance_scale: float = 9.0,
                     height: int | None = None,
                     width: int | None = None,
                     fps: float = 8.0,
                     content_type: str = "video/mp4",
                     scheduler_type: str | None = None,
                     **_ignored: Any):
    """Text-to-video (swarm/video/tx2vid.py:17-88 parity: default 25
    frames, mp4/h264-or-webm switch, 8 fps, thumbnail from frame 0). The
    whole denoise runs as ONE jitted program over the (F, lh, lw, C) video
    latent through the temporal UNet — no per-frame Python loop, no memory
    heuristics (tx2vid.py:36-53 has no TPU analog)."""
    import time

    from chiaswarm_tpu.pipelines.video import get_video_family

    if get_video_family(model_name).image_conditioned:
        raise ValueError(
            f"model {model_name!r} is image-conditioned (SVD-class) and "
            f"cannot serve txt2vid; send an img2vid job with a start image")
    pipe = registry.video_pipeline(model_name,
                                   mesh=getattr(slot, "mesh", None))
    t0 = time.perf_counter()
    frames, config = pipe(
        prompt or "",
        negative_prompt=negative_prompt or "",
        num_frames=int(num_frames),
        steps=int(num_inference_steps),
        guidance_scale=float(guidance_scale),
        height=height, width=width,
        seed=seed,
        scheduler=scheduler_type,
    )
    elapsed = time.perf_counter() - t0

    artifacts = _video_artifacts(list(frames), float(fps), content_type)
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(frames, model_name)
    config.update(safety_fields)
    config.update({
        "fps": float(fps),
        "generation_s": round(elapsed, 3),
        "frames_per_sec": round(frames.shape[0] / max(elapsed, 1e-9), 4),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return artifacts, config


def img2vid_callback(slot, model_name: str, *, seed: int,
                     registry: ModelRegistry,
                     image: np.ndarray,
                     num_frames: int | None = None,
                     num_inference_steps: int = 25,
                     fps: float = 7.0,
                     motion_bucket_id: int = 127,
                     noise_aug_strength: float = 0.02,
                     min_guidance_scale: float = 1.0,
                     max_guidance_scale: float = 3.0,
                     height: int | None = None,
                     width: int | None = None,
                     content_type: str = "video/mp4",
                     scheduler_type: str | None = None,
                     **_ignored: Any):
    """Image-to-video (SVD-class; BASELINE.json config #5's model class —
    beyond the reference, which serves only txt2vid/vid2vid). The input
    frame conditions the whole clip through the CLIP-image embedding and
    channel-concatenated VAE latents; the denoise runs as ONE jitted
    program (pipelines/video.py::Img2VidPipeline)."""
    import time

    from chiaswarm_tpu.pipelines.video import get_video_family

    if not get_video_family(model_name).image_conditioned:
        raise ValueError(
            f"model {model_name!r} is a text-to-video family and cannot "
            f"serve img2vid; name an SVD-class model (svd_img2vid)")
    pipe = registry.video_pipeline(model_name,
                                   mesh=getattr(slot, "mesh", None))
    t0 = time.perf_counter()
    frames, config = pipe(
        np.asarray(image),
        num_frames=num_frames,
        steps=int(num_inference_steps),
        fps=int(fps),
        motion_bucket_id=int(motion_bucket_id),
        noise_aug_strength=float(noise_aug_strength),
        min_guidance_scale=float(min_guidance_scale),
        max_guidance_scale=float(max_guidance_scale),
        height=height, width=width,
        seed=seed,
        scheduler=scheduler_type,
    )
    elapsed = time.perf_counter() - t0

    artifacts = _video_artifacts(list(frames), float(fps), content_type)
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(frames, model_name)
    config.update(safety_fields)
    config.update({
        "generation_s": round(elapsed, 3),
        "frames_per_sec": round(frames.shape[0] / max(elapsed, 1e-9), 4),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return artifacts, config
