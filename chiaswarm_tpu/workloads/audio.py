"""Audio workloads: txt2audio (AudioLDM-class) and TTS (bark-class).

Reference capabilities: swarm/audio/audioldm.py:12-36 (AudioLDM pipeline,
default 20 steps / 10 s of 16 kHz audio) and swarm/audio/bark.py:11-38
(suno-bark TTS). txt2audio runs the jitted mel-latent diffusion + HiFiGAN
pipeline (pipelines/audio.py); artifacts are MP3 (``audio/mpeg``) when an
ffmpeg binary is on PATH — the reference's pydub transcode
(audioldm.py:23-33) shells out to ffmpeg the same way, and the Dockerfile
ships it — with an honest WAV (``audio/wav``) fallback via the stdlib
``wave`` module on hosts without ffmpeg.
"""

from __future__ import annotations

import functools
import io
import wave
from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import make_result


def pcm16_wav(samples: np.ndarray, sample_rate: int = 16000) -> bytes:
    """float [-1,1] mono -> WAV bytes (the ffmpeg-less fallback encode)."""
    pcm = (np.clip(samples, -1.0, 1.0) * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as wav:
        wav.setnchannels(1)
        wav.setsampwidth(2)
        wav.setframerate(sample_rate)
        wav.writeframes(pcm.tobytes())
    return buf.getvalue()


@functools.lru_cache(maxsize=1)
def _ffmpeg_path() -> str | None:
    import shutil

    return shutil.which("ffmpeg")


def mp3_bytes(samples: np.ndarray, sample_rate: int = 16000,
              bitrate: str = "128k") -> bytes | None:
    """float [-1,1] mono -> MP3 bytes via the ffmpeg CLI, or None when no
    encoder is available (pydub's export(format="mp3") is the same ffmpeg
    pipe under the hood, swarm/audio/audioldm.py:23-33)."""
    exe = _ffmpeg_path()
    if exe is None:
        return None
    import subprocess

    pcm = (np.clip(samples, -1.0, 1.0) * 32767.0).astype("<i2").tobytes()
    try:
        proc = subprocess.run(
            [exe, "-hide_banner", "-loglevel", "error",
             "-f", "s16le", "-ar", str(sample_rate), "-ac", "1",
             "-i", "pipe:0", "-f", "mp3", "-b:a", bitrate, "pipe:1"],
            input=pcm, capture_output=True, timeout=120, check=True,
        )
    except Exception:
        return None
    return proc.stdout or None


def audio_artifact(samples: np.ndarray, sample_rate: int = 16000) -> dict:
    mp3 = mp3_bytes(samples, sample_rate)
    if mp3 is not None:
        return make_result(mp3, "audio/mpeg")
    return make_result(pcm16_wav(samples, sample_rate), "audio/wav")


def _finalize_audio(slot, t0: float, wav: np.ndarray, sr: int,
                    config: dict) -> tuple[dict, dict]:
    """Shared trailer for every audio workload: timing + slot metadata +
    the WAV artifact envelope."""
    import time

    config.update({
        "nsfw": False,
        "generation_s": round(time.perf_counter() - t0, 3),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return {"primary": audio_artifact(wav[0], sr)}, config


def txt2audio_callback(slot, model_name: str, *, seed: int,
                       registry=None,
                       prompt: str = "",
                       negative_prompt: str = "",
                       num_inference_steps: int = 20,
                       guidance_scale: float = 2.5,
                       audio_length_in_s: float = 10.0,
                       scheduler_type: str | None = None,
                       **_ignored: Any):
    """AudioLDM-class txt2audio (swarm/audio/audioldm.py:12-36: default 20
    steps, 10 s). Emits an audio/wav artifact."""
    import time

    if registry is None:
        raise ValueError("txt2audio requires the model registry")
    pipe = registry.audio_pipeline(model_name)
    t0 = time.perf_counter()
    wav, sr, config = pipe(
        prompt=prompt or "",
        negative_prompt=negative_prompt or "",
        steps=int(num_inference_steps),
        guidance_scale=float(guidance_scale),
        duration_s=float(audio_length_in_s),
        seed=seed,
        scheduler=scheduler_type,
    )
    return _finalize_audio(slot, t0, wav, sr, config)


def tts_callback(slot, model_name: str, *, seed: int,
                 registry=None,
                 prompt: str = "",
                 audio_length_in_s: float = 4.0,
                 temperature: float = 0.7,
                 voice_preset_tokens: list[int] | None = None,
                 parameters: dict | None = None,
                 **_ignored: Any):
    """Bark-class TTS (swarm/audio/bark.py:11-38: generate_audio + wav
    emit). Three GPT stages + codec decode, all on-chip
    (pipelines/tts.py)."""
    import time

    if registry is None:
        raise ValueError("tts requires the model registry")
    parameters = parameters or {}
    pipe = registry.tts_pipeline(model_name)
    t0 = time.perf_counter()
    # full bark voice preset: {semantic_prompt, coarse_prompt,
    # fine_prompt} arrays in job parameters (JSON lists accepted)
    history = parameters.get("history") or parameters.get("voice_preset")
    if isinstance(history, str):
        # upstream bark names presets ("v2/en_speaker_6") resolved from
        # bundled npz files this worker does not ship; a ValueError marks
        # the job fatal/non-retryable (swarm/generator.py:34-41 taxonomy)
        raise ValueError(
            f"named voice preset {history!r} is not available on this "
            "worker; send the preset arrays as parameters.history = "
            "{semantic_prompt, coarse_prompt, fine_prompt}")
    if history is not None:
        history = {k: np.asarray(v) for k, v in history.items()}
    wav, sr, config = pipe(
        prompt or "",
        duration_s=float(audio_length_in_s),
        seed=seed,
        temperature=float(temperature),
        voice_preset_tokens=(voice_preset_tokens
                             or parameters.get("voice_preset_tokens")),
        history=history,
    )
    return _finalize_audio(slot, t0, wav, sr, config)
