"""Audio workloads: txt2audio (AudioLDM-class) and TTS (bark-class).

Reference capabilities: swarm/audio/audioldm.py:12-36 (AudioLDM pipeline,
wav 16 kHz -> mp3) and swarm/audio/bark.py:11-38 (suno-bark TTS). The Flax
audio-latent-diffusion family is not in the model zoo yet; these callbacks
declare the capability seam (dispatched from node/job_args.py) and fail
fatally so the hive stops routing audio jobs to this node.

When the models land: output is WAV via the stdlib ``wave`` module (this
image has no ffmpeg, so mp3 transcode is gated off — content negotiation
reports audio/wav).
"""

from __future__ import annotations

import io
import wave
from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import make_result


def pcm16_wav(samples: np.ndarray, sample_rate: int = 16000) -> bytes:
    """float [-1,1] mono -> WAV bytes (the host-side encode path for when
    the audio model family lands; unit-tested now)."""
    pcm = (np.clip(samples, -1.0, 1.0) * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as wav:
        wav.setnchannels(1)
        wav.setsampwidth(2)
        wav.setframerate(sample_rate)
        wav.writeframes(pcm.tobytes())
    return buf.getvalue()


def audio_artifact(samples: np.ndarray, sample_rate: int = 16000) -> dict:
    return make_result(pcm16_wav(samples, sample_rate), "audio/wav")


def txt2audio_callback(slot, model_name: str, *, seed: int,
                       **kwargs: Any):
    raise ValueError(
        f"txt2audio is not yet supported by this TPU worker "
        f"(requested model {model_name!r})"
    )


def tts_callback(slot, model_name: str, *, seed: int, **kwargs: Any):
    raise ValueError(
        f"text-to-speech is not yet supported by this TPU worker "
        f"(requested model {model_name!r})"
    )
