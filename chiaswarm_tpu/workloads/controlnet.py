"""ControlNet input preprocessors (host-side, CPU).

Capability parity with swarm/controlnet/input_processor.py:17-272: the
conditioning image is computed *before* generation from the user's input
image, dispatched on ``controlnet["type"]``. These are CPU ops (OpenCV /
PIL) by design — the reference keeps them off-GPU and we keep them off-TPU
(SURVEY.md §2: "keep on CPU (host) — not TPU work").

Implemented without controlnet_aux. Exact ports: canny (cv2.Canny with
per-job thresholds), tile (scale min-dim to 1024, round to 64 multiple),
pix2pix (passthrough), shuffle (content shuffle). Every learned mode runs
a NATIVE network when its converted weights are in the model dir
(`swarm-tpu init` provisions all of them): openpose (models/openpose.py,
raises with a fetch hint when absent); scribble/softedge (models/hed.py);
depth/normalbae (models/dpt.py); seg (models/upernet.py); mlsd
(models/mlsd.py); lineart (models/lineart.py). The non-openpose modes
fall back to documented model-free stand-ins on weightless nodes
(blurred Scharr, position-prior pseudo-depth, mean-shift posterization
onto the ADE20K palette, probabilistic Hough segments, dodge-sketch).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np
from PIL import Image

_PREPROCESSORS: dict[str, Callable[..., Image.Image]] = {}
# modes whose function takes the job's controlnet dict as a second
# positional arg (decided ONCE at registration from the signature, so
# new parametrized modes need no dispatcher special case)
_TAKES_PARAMS: set[str] = set()


def _register(name: str):
    def wrap(fn):
        import inspect

        _PREPROCESSORS[name] = fn
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
        if len(params) > 1 and params[1].name == "controlnet":
            _TAKES_PARAMS.add(name)
        return fn
    return wrap


@_register("canny")
def image_to_canny(image: Image.Image,
                   controlnet: dict | None = None) -> Image.Image:
    """Canny edges honoring the job's thresholds
    (input_processor.py:74-84: controlnet.get("low_threshold"/
    "high_threshold") with 100/200 defaults)."""
    import cv2

    controlnet = controlnet or {}
    arr = np.asarray(image)
    edges = cv2.Canny(arr,
                      int(controlnet.get("low_threshold", 100)),
                      int(controlnet.get("high_threshold", 200)))
    return Image.fromarray(np.stack([edges] * 3, axis=-1))


def _lazy_detector(cache: list, local_name: str, loader,
                   fallback_msg: str):
    """Shared weight-gated singleton for the learned preprocessors: load
    the converted checkpoint from the model dir on first use, else cache
    ``None`` (-> caller falls back to its model-free stand-in)."""
    if not cache:
        from chiaswarm_tpu.node.registry import model_dir

        ckpt = model_dir(local_name)
        if ckpt.exists():
            cache.append(loader(ckpt))
        else:
            import logging

            logging.getLogger("chiaswarm.preprocess").info(
                "no %s weights at %s; %s", local_name, ckpt, fallback_msg)
            cache.append(None)
    return cache[0]


_HED: list[Any] = []  # resident detector (lazy; [None] = no weights)


@_register("scribble")
@_register("softedge")
def image_to_soft_edges(image: Image.Image) -> Image.Image:
    """Soft-edge map for the HED/PidiNet modes (input_processor.py:17-60).
    With converted ``ControlNetHED`` weights in the model dir this runs
    the native HED network (models/hed.py); without them it falls back to
    the model-free blurred-Scharr stand-in (logged once)."""
    import cv2

    def _load(ckpt):
        from chiaswarm_tpu.models.hed import HEDDetector

        return HEDDetector.from_checkpoint(ckpt)

    det = _lazy_detector(_HED, "hed", _load,
                         "scribble/softedge use the gradient stand-in")
    if det is not None:
        edge = det(np.asarray(image.convert("RGB")))
        return Image.fromarray(np.stack([edge] * 3, axis=-1))

    gray = cv2.cvtColor(np.asarray(image), cv2.COLOR_RGB2GRAY)
    gray = cv2.GaussianBlur(gray, (5, 5), 0)
    gx = cv2.Scharr(gray, cv2.CV_32F, 1, 0)
    gy = cv2.Scharr(gray, cv2.CV_32F, 0, 1)
    mag = np.sqrt(gx ** 2 + gy ** 2)
    mag = (255.0 * mag / max(float(mag.max()), 1e-6)).astype(np.uint8)
    return Image.fromarray(np.stack([mag] * 3, axis=-1))


@_register("tile")
def image_to_tile(image: Image.Image, resolution: int = 1024) -> Image.Image:
    """Scale so the SHORT side hits ``resolution`` (upscaling small
    inputs — tile conditioning wants detail at output scale), then round
    each side to the nearest 64 multiple (input_processor.py:63-71)."""
    w, h = image.size
    k = float(resolution) / min(h, w)
    w = max(64, int(round(w * k / 64.0)) * 64)
    h = max(64, int(round(h * k / 64.0)) * 64)
    return image.resize((w, h), Image.Resampling.LANCZOS)


@_register("pix2pix")
def image_passthrough(image: Image.Image) -> Image.Image:
    return image


@_register("shuffle")
def image_shuffle(image: Image.Image) -> Image.Image:
    """Content shuffle: coarse spatial scramble of 32px blocks."""
    rng = np.random.default_rng(0)
    arr = np.asarray(image).copy()
    h, w = arr.shape[:2]
    bs = 32
    blocks = [(y, x) for y in range(0, h - bs + 1, bs)
              for x in range(0, w - bs + 1, bs)]
    perm = rng.permutation(len(blocks))
    out = arr.copy()
    for (y, x), p in zip(blocks, perm):
        sy, sx = blocks[p]
        out[y:y + bs, x:x + bs] = arr[sy:sy + bs, sx:sx + bs]
    return Image.fromarray(out)


_MLSD: list[Any] = []  # resident detector (lazy; [None] = no weights)


@_register("mlsd")
def image_to_line_segments(image: Image.Image) -> Image.Image:
    """Wireframe map for the mlsd mode (input_processor.py:17-60). With
    converted ``MobileV2_MLSD_Large`` weights in the model dir this runs
    the native M-LSD network (models/mlsd.py); without them it falls back
    to the model-free Hough stand-in (logged once)."""
    import cv2

    def _load(ckpt):
        from chiaswarm_tpu.models.mlsd import MLSDDetector

        return MLSDDetector.from_checkpoint(ckpt)

    det = _lazy_detector(_MLSD, "mlsd", _load,
                         "mlsd uses the Hough-segments stand-in")
    arr = np.asarray(image)
    if det is not None:
        wire = det(arr)
        return Image.fromarray(np.stack([wire] * 3, axis=-1))

    gray = cv2.cvtColor(arr, cv2.COLOR_RGB2GRAY)
    edges = cv2.Canny(gray, 50, 150)
    lines = cv2.HoughLinesP(edges, 1, np.pi / 180, threshold=40,
                            minLineLength=24, maxLineGap=4)
    out = np.zeros_like(arr)
    if lines is not None:
        for x1, y1, x2, y2 in np.asarray(lines).reshape(-1, 4):
            cv2.line(out, (x1, y1), (x2, y2), (255, 255, 255), 2)
    return Image.fromarray(out)


_LINEART: list[Any] = []  # resident detector (lazy; [None] = no weights)


@_register("lineart")
def image_to_lineart(image: Image.Image) -> Image.Image:
    """Line drawing for the lineart mode (input_processor.py:17-60). With
    converted informative-drawings ``Generator`` weights in the model dir
    this runs the native network (models/lineart.py); without them it
    falls back to the model-free dodge-blend sketch (logged once)."""
    import cv2

    def _load(ckpt):
        from chiaswarm_tpu.models.lineart import LineartDetector

        return LineartDetector.from_checkpoint(ckpt)

    det = _lazy_detector(_LINEART, "lineart", _load,
                         "lineart uses the dodge-sketch stand-in")
    if det is not None:
        lines = det(np.asarray(image.convert("RGB")))
        return Image.fromarray(np.stack([lines] * 3, axis=-1))

    gray = cv2.cvtColor(np.asarray(image), cv2.COLOR_RGB2GRAY)
    blur = cv2.GaussianBlur(gray, (21, 21), 0)
    sketch = cv2.divide(gray, np.maximum(blur, 1), scale=256)
    lines = 255 - sketch  # dark strokes -> bright lines
    lines = cv2.normalize(lines, None, 0, 255, cv2.NORM_MINMAX)
    return Image.fromarray(np.stack([lines.astype(np.uint8)] * 3, axis=-1))


_DPT: list[Any] = []  # resident depth model (lazy; [None] = no weights)


def _pseudo_depth(arr: np.ndarray) -> np.ndarray:
    """Model-free MiDaS stand-in: vertical position prior (lower in frame ~
    nearer) blended with local sharpness (in-focus ~ nearer). float [0,1],
    1 = near."""
    import cv2

    gray = cv2.cvtColor(arr, cv2.COLOR_RGB2GRAY).astype(np.float32) / 255.0
    h, w = gray.shape
    position = np.linspace(0.0, 1.0, h)[:, None].repeat(w, axis=1)
    lap = np.abs(cv2.Laplacian(gray, cv2.CV_32F, ksize=5))
    sharp = cv2.GaussianBlur(lap, (0, 0), sigmaX=max(h, w) / 32.0)
    sharp = sharp / max(float(sharp.max()), 1e-6)
    depth = (0.6 * position + 0.4 * sharp).astype(np.float32)
    return cv2.GaussianBlur(depth, (0, 0), sigmaX=3.0)


def _depth_map(arr: np.ndarray) -> np.ndarray:
    """float depth in [0, 1] (1 = near): the native DPT network
    (models/dpt.py — the same architecture behind the reference's
    transformers depth pipeline, input_processor.py:87-93) when converted
    weights exist in the model dir, else the model-free stand-in."""
    def _load(ckpt):
        from chiaswarm_tpu.models.dpt import DPTDetector

        return DPTDetector.from_checkpoint(ckpt)

    det = _lazy_detector(_DPT, "dpt", _load,
                         "depth/normal use the position-prior stand-in")
    if det is not None:
        d = det.depth(arr)
        lo, hi = float(d.min()), float(d.max())
        return ((d - lo) / max(hi - lo, 1e-6)).astype(np.float32)
    return _pseudo_depth(arr)


@_register("depth")
def image_to_depth(image: Image.Image) -> Image.Image:
    depth = _depth_map(np.asarray(image))
    u8 = (depth * 255.0).clip(0, 255).astype(np.uint8)
    return Image.fromarray(np.stack([u8] * 3, axis=-1))


@_register("normal")
@_register("normalbae")
def image_to_normal(image: Image.Image) -> Image.Image:
    """Surface normals from the pseudo-depth via Sobel gradients, encoded
    in the usual RGB = (x, y, z) * 0.5 + 0.5 convention."""
    import cv2

    depth = _depth_map(np.asarray(image))
    dx = cv2.Sobel(depth, cv2.CV_32F, 1, 0, ksize=5)
    dy = cv2.Sobel(depth, cv2.CV_32F, 0, 1, ksize=5)
    z = np.full_like(depth, 0.1)
    norm = np.sqrt(dx * dx + dy * dy + z * z)
    n = np.stack([-dx / norm, -dy / norm, z / norm], axis=-1)
    return Image.fromarray(((n * 0.5 + 0.5) * 255).clip(0, 255)
                           .astype(np.uint8))


# full ADE20K palette (the 150-class table + background row the reference
# embeds at input_processor.py:118-272), shared with models/upernet.py
from chiaswarm_tpu.workloads.ade_palette import (  # noqa: E402
    ADE20K_PALETTE as _ADE_PALETTE,
)


_SEG: list[Any] = []  # resident segmenter (lazy; [None] = no weights)


@_register("seg")
def image_to_segments(image: Image.Image) -> Image.Image:
    """ADE-colored segmentation map. With converted UperNet-ConvNeXt
    weights in the model dir this runs the native model the reference
    calls through transformers (models/upernet.py,
    input_processor.py:96-115); without them: mean-shift posterization
    with each region color snapped to the nearest ADE-palette entry."""
    import cv2

    def _load(ckpt):
        from chiaswarm_tpu.models.upernet import UperNetDetector

        return UperNetDetector.from_checkpoint(ckpt)

    det = _lazy_detector(_SEG, "upernet", _load,
                         "seg uses the posterization stand-in")
    if det is not None:
        return Image.fromarray(det(np.asarray(image.convert("RGB"))))

    arr = cv2.pyrMeanShiftFiltering(
        cv2.cvtColor(np.asarray(image), cv2.COLOR_RGB2BGR), 12, 24)
    arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
    flat = arr.reshape(-1, 3).astype(np.float32)
    pal = _ADE_PALETTE.astype(np.float32)
    # ||a-b||^2 = ||a||^2 - 2 a.b + ||b||^2: peak extra memory is (N, 32)
    # floats instead of an (N, 32, 3) difference tensor
    dists = ((flat ** 2).sum(1, keepdims=True)
             - 2.0 * flat @ pal.T + (pal ** 2).sum(1)[None])
    return Image.fromarray(
        _ADE_PALETTE[np.argmin(dists, axis=1)].reshape(arr.shape))


_OPENPOSE: list[Any] = []  # resident detector (lazy singleton)


@_register("openpose")
def image_to_openpose(image: Image.Image) -> Image.Image:
    """Native CMU body-pose skeleton (models/openpose.py) — the one
    preprocessor that needs learned weights. Loads ``body_pose_model``
    weights from the node's model dir (fetched by init alongside the
    diffusion checkpoints); without them this raises, matching the
    historical behavior but with an actionable message."""
    if not _OPENPOSE:
        from chiaswarm_tpu.models.openpose import OpenposeDetector
        from chiaswarm_tpu.node.registry import model_dir

        ckpt = model_dir("openpose")
        if not ckpt.exists():
            raise ValueError(
                "openpose preprocessing needs the CMU body_pose_model "
                f"weights at {ckpt}; `swarm-tpu init` fetches them when "
                "the hive catalog lists an openpose model, or place "
                "body_pose_model.pth there manually"
            )
        _OPENPOSE.append(OpenposeDetector.from_checkpoint(ckpt))
    skeleton = _OPENPOSE[0](np.asarray(image.convert("RGB")))
    return Image.fromarray(skeleton)


def preprocess_image(image: Image.Image, controlnet: dict[str, Any]) -> Image.Image:
    """Dispatch on controlnet["type"] (input_processor.py:17-60). Every
    mode has an exact port or a native detector gated on converted
    weights (with a documented model-free stand-in).

    Like the reference (input_processor.py:18), preprocessing is OFF by
    default — the server marks jobs whose input is raw and needs
    annotation; an already-annotated conditioning image passes through."""
    kind = str(controlnet.get("type", "canny")).lower()
    if not controlnet.get("preprocess", False):
        return image
    fn = _PREPROCESSORS.get(kind)
    if fn is None:
        raise ValueError(
            f"controlnet preprocessor {kind!r} is not yet supported on "
            f"this TPU worker (available: {sorted(_PREPROCESSORS)})"
        )
    if kind in _TAKES_PARAMS:
        return fn(image, controlnet)
    return fn(image)
