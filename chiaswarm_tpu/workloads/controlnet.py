"""ControlNet input preprocessors (host-side, CPU).

Capability parity with swarm/controlnet/input_processor.py:17-272: the
conditioning image is computed *before* generation from the user's input
image, dispatched on ``controlnet["type"]``. These are CPU ops (OpenCV /
PIL) by design — the reference keeps them off-GPU and we keep them off-TPU
(SURVEY.md §2: "keep on CPU (host) — not TPU work").

Implemented without auxiliary torch models (this image has no
controlnet_aux): canny (cv2.Canny), tile (64-multiple resize), pix2pix
(passthrough), scribble/softedge (Scharr-gradient sketch — a model-free
stand-in for HED/PidiNet), shuffle (content shuffle), depth/normal/seg/
mlsd/lineart/openpose raise until their Flax estimator models land.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np
from PIL import Image

_PREPROCESSORS: dict[str, Callable[[Image.Image], Image.Image]] = {}


def _register(name: str):
    def wrap(fn):
        _PREPROCESSORS[name] = fn
        return fn
    return wrap


@_register("canny")
def image_to_canny(image: Image.Image) -> Image.Image:
    import cv2

    arr = np.asarray(image)
    edges = cv2.Canny(arr, 100, 200)
    return Image.fromarray(np.stack([edges] * 3, axis=-1))


@_register("scribble")
@_register("softedge")
def image_to_soft_edges(image: Image.Image) -> Image.Image:
    """Model-free soft-edge map: blurred Scharr gradient magnitude (stands
    in for the reference's HED/PidiNet detectors, input_processor.py:17-60)."""
    import cv2

    gray = cv2.cvtColor(np.asarray(image), cv2.COLOR_RGB2GRAY)
    gray = cv2.GaussianBlur(gray, (5, 5), 0)
    gx = cv2.Scharr(gray, cv2.CV_32F, 1, 0)
    gy = cv2.Scharr(gray, cv2.CV_32F, 0, 1)
    mag = np.sqrt(gx ** 2 + gy ** 2)
    mag = (255.0 * mag / max(float(mag.max()), 1e-6)).astype(np.uint8)
    return Image.fromarray(np.stack([mag] * 3, axis=-1))


@_register("tile")
def image_to_tile(image: Image.Image) -> Image.Image:
    """Round size down to a 64 multiple (input_processor.py:63-71)."""
    w, h = image.size
    w, h = max(64, w // 64 * 64), max(64, h // 64 * 64)
    return image.resize((w, h), Image.Resampling.LANCZOS)


@_register("pix2pix")
def image_passthrough(image: Image.Image) -> Image.Image:
    return image


@_register("shuffle")
def image_shuffle(image: Image.Image) -> Image.Image:
    """Content shuffle: coarse spatial scramble of 32px blocks."""
    rng = np.random.default_rng(0)
    arr = np.asarray(image).copy()
    h, w = arr.shape[:2]
    bs = 32
    blocks = [(y, x) for y in range(0, h - bs + 1, bs)
              for x in range(0, w - bs + 1, bs)]
    perm = rng.permutation(len(blocks))
    out = arr.copy()
    for (y, x), p in zip(blocks, perm):
        sy, sx = blocks[p]
        out[y:y + bs, x:x + bs] = arr[sy:sy + bs, sx:sx + bs]
    return Image.fromarray(out)


def preprocess_image(image: Image.Image, controlnet: dict[str, Any]) -> Image.Image:
    """Dispatch on controlnet["type"] (input_processor.py:17-60). Types
    requiring learned estimators raise until those models land."""
    kind = str(controlnet.get("type", "canny")).lower()
    if not controlnet.get("preprocess", True):
        return image
    fn = _PREPROCESSORS.get(kind)
    if fn is None:
        raise ValueError(
            f"controlnet preprocessor {kind!r} is not yet supported on "
            f"this TPU worker (available: {sorted(_PREPROCESSORS)})"
        )
    return fn(image)
