"""NSFW safety checker — the result-trust boundary of an open network.

Capability parity with the reference's reliance on diffusers' built-in
``StableDiffusionSafetyChecker``: any flagged image marks the whole result
``nsfw: True`` (swarm/diffusion/diffusion_func.py:99-111, OR-propagated at
swarm/generator.py:37,76 and per-frame at swarm/video/pix2pix.py:68,84).

Design: the checker is the standard CLIP-vision + concept-embedding
cosine-similarity head. The vision tower is this framework's native Flax
ClipVisionEncoder (models/clip.py, jit-compiled on the chip); the trunk
weights, concept/special-care embeddings and thresholds all convert from
the safety-checker checkpoint in one pass
(``safety_checker/`` subdir of an SD snapshot, or a standalone snapshot
at ``<root>/models/CompVis__stable-diffusion-safety-checker``).

When no checker checkpoint is present on the node the result carries
``nsfw: False`` plus ``safety_checker: "unavailable"`` — an explicit
signal to the hive rather than a silent pass.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import numpy as np

log = logging.getLogger("chiaswarm.safety")

_CACHE: dict[str, Any] = {}

# CLIP preprocessing constants (openai/clip-vit-large-patch14)
_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


def _find_checker_dir(model_name: str | None = None) -> Path | None:
    from chiaswarm_tpu.node.registry import model_dir

    candidates = []
    if model_name:
        candidates.append(model_dir(model_name) / "safety_checker")
    candidates.append(model_dir("CompVis/stable-diffusion-safety-checker"))
    for cand in candidates:
        if cand.is_dir():
            return cand
    return None


def _clip_preprocess(frame: np.ndarray, size: int = 224) -> np.ndarray:
    """CLIP's shortest-edge resize + center crop (NOT a plain squash —
    anisotropic resizing shifts cosine scores near the thresholds on
    non-square video frames)."""
    from PIL import Image

    img = Image.fromarray(frame)
    w, h = img.size
    scale = size / min(w, h)
    img = img.resize((max(size, round(w * scale)),
                      max(size, round(h * scale))), Image.BICUBIC)
    left = (img.width - size) // 2
    top = (img.height - size) // 2
    img = img.crop((left, top, left + size, top + size))
    arr = np.asarray(img, np.float32) / 255.0
    return (arr - _MEAN) / _STD


def _vision_config(checker_dir: Path):
    """VisionConfig from the checkpoint's own ``config.json`` (HF
    safety-checker snapshots carry the CLIPConfig with a vision section);
    defaults are the production ViT-L/14 shape. Reading the config rather
    than assuming it lets tiny test fixtures and any future checker
    variant load through the same path."""
    from chiaswarm_tpu.models.clip import VisionConfig

    base = VisionConfig()
    cfg_file = checker_dir / "config.json"
    if not cfg_file.is_file():
        return base
    import json

    try:
        raw = json.loads(cfg_file.read_text())
    except (OSError, ValueError):
        return base
    vis = raw.get("vision_config") or raw.get("vision_config_dict") or {}
    return VisionConfig(
        hidden_size=int(vis.get("hidden_size", base.hidden_size)),
        intermediate_size=int(vis.get("intermediate_size",
                                      base.intermediate_size)),
        num_layers=int(vis.get("num_hidden_layers", base.num_layers)),
        num_heads=int(vis.get("num_attention_heads", base.num_heads)),
        image_size=int(vis.get("image_size", base.image_size)),
        patch_size=int(vis.get("patch_size", base.patch_size)),
        projection_dim=int(vis.get("projection_dim", base.projection_dim)),
    )


class SafetyChecker:
    """Native CLIP-vision tower + concept-cosine head (models/clip.py
    ClipVisionEncoder), converted from the torch checker in ONE file pass.
    """

    _image_size = 224  # overwritten from the checkpoint config on load

    def __init__(self, checker_dir: Path) -> None:
        from chiaswarm_tpu.core.compile_cache import toplevel_jit
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_safety_checker,
            read_torch_weights,
        )
        from chiaswarm_tpu.models.clip import ClipVisionEncoder

        params, buffers = convert_safety_checker(
            read_torch_weights(checker_dir))
        self.concept_embeds = np.asarray(buffers["concept_embeds"])
        self.concept_thresholds = np.asarray(
            buffers["concept_embeds_weights"])
        self.special_embeds = np.asarray(buffers["special_care_embeds"])
        self.special_thresholds = np.asarray(
            buffers["special_care_embeds_weights"])
        cfg = _vision_config(checker_dir)
        self._image_size = cfg.image_size
        vision = ClipVisionEncoder(cfg)
        self._jit_embed = toplevel_jit(
            lambda pixel_values: vision.apply(params, pixel_values))

    def __call__(self, images: np.ndarray) -> list[bool]:
        """uint8 (B, H, W, 3) -> per-image nsfw flags."""
        pixel_values = np.stack(
            [_clip_preprocess(f, size=self._image_size) for f in images])

        embeds = np.asarray(self._jit_embed(pixel_values))
        embeds = embeds / np.linalg.norm(embeds, axis=-1, keepdims=True)

        def cos(a, b):
            bn = b / np.linalg.norm(b, axis=-1, keepdims=True)
            return a @ bn.T

        special = cos(embeds, self.special_embeds)       # (B, n_special)
        concepts = cos(embeds, self.concept_embeds)      # (B, n_concepts)
        flags = []
        for i in range(embeds.shape[0]):
            # special-care hits lower the concept threshold (the standard
            # checker's adjustment semantics)
            adjustment = 0.01 if np.any(
                special[i] > self.special_thresholds) else 0.0
            flags.append(bool(np.any(
                concepts[i] > self.concept_thresholds - adjustment)))
        return flags


def get_checker(model_name: str | None = None) -> SafetyChecker | None:
    """Resident checker, or None when no checkpoint exists on this node."""
    checker_dir = _find_checker_dir(model_name)
    if checker_dir is None:
        return None
    key = str(checker_dir)
    if key not in _CACHE:
        try:
            _CACHE[key] = SafetyChecker(checker_dir)
            log.info("safety checker loaded from %s", checker_dir)
        except Exception as exc:
            log.warning("safety checker at %s failed to load: %s",
                        checker_dir, exc)
            _CACHE[key] = None
    return _CACHE[key]


def check_images(images: np.ndarray,
                 model_name: str | None = None) -> tuple[bool, dict]:
    """OR-reduced nsfw flag + config fields (diffusion_func.py:99-111)."""
    checker = get_checker(model_name)
    if checker is None:
        return False, {"nsfw": False, "safety_checker": "unavailable"}
    flags = checker(np.asarray(images))
    return any(flags), {"nsfw": any(flags), "nsfw_flags": flags}
