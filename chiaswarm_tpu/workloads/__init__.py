"""Workload callbacks — the uniform job-execution seam.

Every workload is a plain function with the signature the dispatcher and
chip pool agree on (the reference's load-bearing invariant,
swarm/generator.py -> swarm/job_arguments.py -> swarm/gpu/device.py:26-47)::

    callback(slot, model_name, *, seed, **kwargs) -> (artifacts, config)

``slot`` is a core.chip_pool.MeshSlot (mesh + rng), artifacts is the
envelope dict from node.output_processor, config is the reproducibility
metadata posted to the hive (model, scheduler, seed, nsfw, timings).
"""

from chiaswarm_tpu.workloads.diffusion import diffusion_callback
from chiaswarm_tpu.workloads.stitch import stitch_callback
from chiaswarm_tpu.workloads.caption import caption_callback

__all__ = ["diffusion_callback", "stitch_callback", "caption_callback"]
