"""Cascaded pixel-space diffusion (DeepFloyd-IF-class models).

Reference capability: swarm/diffusion/diffusion_func_if.py:14-92 — a
three-stage cascade (64px base -> 256px super-res -> 1024px upscale) with
prompt embeds shared across stages. The TPU design runs each stage as its
own jitted program over the same mesh, with the text encoder (T5-class)
evaluated once. The pixel-space UNet family is not in the model zoo yet;
this callback declares the dispatch seam (node/job_args.py routes
``DeepFloyd/`` model names here) and fails fatally until it lands.
"""

from __future__ import annotations

from typing import Any


def cascade_callback(slot, model_name: str, *, seed: int, **kwargs: Any):
    raise ValueError(
        f"cascaded pixel-space diffusion is not yet supported by this TPU "
        f"worker (requested model {model_name!r})"
    )
