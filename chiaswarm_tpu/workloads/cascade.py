"""Cascaded pixel-space diffusion workload (DeepFloyd-IF-class models).

Capability parity with swarm/diffusion/diffusion_func_if.py:14-92 — the
``DeepFloyd/`` model-name prefix routes here (swarm/job_arguments.py:39-40).
Three stages: 64px T5-conditioned base -> 256px super-res (prompt embeds
shared, :45-61) -> the SD-x4-upscaler to 1024px (:31-40 — the same
text-conditioned x4 SR model class the reference runs, pipelines/
upscale.py::Upscale4xPipeline). The whole cascade runs as jitted programs
on the chip (pipelines/cascade.py).
"""

from __future__ import annotations

import time
from typing import Any

from chiaswarm_tpu.node.output_processor import OutputProcessor


def cascade_callback(slot, model_name: str, *, seed: int,
                     registry,
                     prompt: str = "",
                     negative_prompt: str = "",
                     num_inference_steps: int = 50,
                     sr_steps: int = 30,
                     guidance_scale: float = 7.0,
                     num_images_per_prompt: int = 1,
                     scheduler_type: str | None = None,
                     content_type: str = "image/png",
                     upscale: bool = True,
                     upscaler_model_name: str = (
                         "stabilityai/stable-diffusion-x4-upscaler"),
                     final_size: int | None = None,
                     **_ignored: Any):
    pipe = registry.cascade_pipeline(model_name,
                                     mesh=getattr(slot, "mesh", None))
    upscaler = None
    if upscale:
        # stage 3: the SD-x4-upscaler (diffusion_func_if.py:31-40) takes
        # 256 -> 1024 in one text-conditioned pass; the cascade pipeline
        # owns the pass loop (an x2-class name still works, two passes)
        upscaler = registry.pipeline(
            upscaler_model_name, mesh=getattr(slot, "mesh", None))

    t0 = time.perf_counter()
    images, config = pipe(
        prompt=prompt or "",
        negative_prompt=negative_prompt or "",
        steps=int(num_inference_steps),
        sr_steps=int(sr_steps),
        guidance_scale=float(guidance_scale),
        batch=max(1, int(num_images_per_prompt)),
        seed=seed,
        scheduler=scheduler_type,
        upscaler=upscaler,
        final_size=final_size,
    )
    elapsed = time.perf_counter() - t0

    proc = OutputProcessor(content_type)
    proc.add_images(images)
    artifacts = proc.get_results()

    # stage-1's safety modules guard the final output in the reference
    # (diffusion_func_if.py:31-40,70-85); here the shared CLIP-concept
    # checker covers the cascade like every diffusion workload
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(images, model_name)
    config.update(safety_fields)
    config.update({
        "images_per_sec": round(images.shape[0] / max(elapsed, 1e-9), 4),
        "generation_s": round(elapsed, 3),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return artifacts, config
