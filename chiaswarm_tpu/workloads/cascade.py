"""Cascaded pixel-space diffusion workload (DeepFloyd-IF-class models).

Capability parity with swarm/diffusion/diffusion_func_if.py:14-92 — the
``DeepFloyd/`` model-name prefix routes here (swarm/job_arguments.py:39-40).
Three stages: 64px T5-conditioned base -> 256px super-res (prompt embeds
shared, :45-61) -> the SD-x4-upscaler to 1024px (:31-40 — the same
text-conditioned x4 SR model class the reference runs, pipelines/
upscale.py::Upscale4xPipeline). The whole cascade runs as jitted programs
on the chip (pipelines/cascade.py).

Beyond the reference (which runs the stages strictly sequentially on one
GPU): multi-image jobs on a >=2-chip slot run STAGE-PARALLEL — stages
1+2 and stage 3 live on disjoint submeshes and overlap across images
(core/mesh.py::split_mesh + pipelines/cascade.py::generate_stage_parallel).
"""

from __future__ import annotations

import time
from typing import Any

from chiaswarm_tpu.node.output_processor import OutputProcessor


def cascade_callback(slot, model_name: str, *, seed: int,
                     registry,
                     prompt: str = "",
                     negative_prompt: str = "",
                     num_inference_steps: int = 50,
                     sr_steps: int = 30,
                     guidance_scale: float = 7.0,
                     num_images_per_prompt: int = 1,
                     scheduler_type: str | None = None,
                     content_type: str = "image/png",
                     upscale: bool = True,
                     upscaler_model_name: str = (
                         "stabilityai/stable-diffusion-x4-upscaler"),
                     final_size: int | None = None,
                     **_ignored: Any):
    mesh = getattr(slot, "mesh", None)
    n_images = max(1, int(num_images_per_prompt))
    # stage-level pipeline parallelism: with >=2 chips, >=2 images and a
    # stage-3 upscaler, stages 1+2 and stage 3 run on DISJOINT submeshes
    # so image i+1's base/SR denoise overlaps image i's x4 upscale
    # (pipelines/cascade.py::generate_stage_parallel). Anything smaller
    # gains nothing from splitting the chips, so it keeps the whole mesh.
    # Data-only meshes ONLY: split_mesh emits data-axis submeshes, so a
    # tp (model>1) slot — derived precisely because the weights need
    # sharding to fit — would silently replicate full weights per chip
    # (OOM risk), and a seq>1 slot would lose its ring-attention axis.
    axis_sizes = dict(mesh.shape) if mesh is not None else {}
    stage_parallel = (upscale and n_images >= 2 and mesh is not None
                      and mesh.devices.size >= 2
                      and mesh.devices.size % 2 == 0
                      and axis_sizes.get("model", 1) == 1
                      and axis_sizes.get("seq", 1) == 1)
    if stage_parallel:
        from chiaswarm_tpu.core.mesh import split_mesh

        base_mesh, up_mesh = split_mesh(mesh, 2)
    else:
        base_mesh = up_mesh = mesh

    pipe = registry.cascade_pipeline(model_name, mesh=base_mesh)
    upscaler = None
    if upscale:
        # stage 3: the SD-x4-upscaler (diffusion_func_if.py:31-40) takes
        # 256 -> 1024 in one text-conditioned pass; the cascade pipeline
        # owns the pass loop (an x2-class name still works, two passes)
        upscaler = registry.pipeline(upscaler_model_name, mesh=up_mesh)

    t0 = time.perf_counter()
    if stage_parallel:
        from chiaswarm_tpu.pipelines.cascade import generate_stage_parallel

        images, config = generate_stage_parallel(
            pipe, upscaler,
            prompt=prompt or "",
            negative_prompt=negative_prompt or "",
            steps=int(num_inference_steps),
            sr_steps=int(sr_steps),
            guidance_scale=float(guidance_scale),
            n_images=n_images,
            seed=seed,
            scheduler=scheduler_type,
            final_size=final_size,
        )
    else:
        images, config = pipe(
            prompt=prompt or "",
            negative_prompt=negative_prompt or "",
            steps=int(num_inference_steps),
            sr_steps=int(sr_steps),
            guidance_scale=float(guidance_scale),
            batch=n_images,
            seed=seed,
            scheduler=scheduler_type,
            upscaler=upscaler,
            final_size=final_size,
        )
    elapsed = time.perf_counter() - t0

    proc = OutputProcessor(content_type)
    proc.add_images(images)
    artifacts = proc.get_results()

    # stage-1's safety modules guard the final output in the reference
    # (diffusion_func_if.py:31-40,70-85); here the shared CLIP-concept
    # checker covers the cascade like every diffusion workload
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(images, model_name)
    config.update(safety_fields)
    config.update({
        "images_per_sec": round(images.shape[0] / max(elapsed, 1e-9), 4),
        "generation_s": round(elapsed, 3),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return artifacts, config
