"""Cascaded pixel-space diffusion workload (DeepFloyd-IF-class models).

Capability parity with swarm/diffusion/diffusion_func_if.py:14-92 — the
``DeepFloyd/`` model-name prefix routes here (swarm/job_arguments.py:39-40).
Three stages: 64px T5-conditioned base -> 256px super-res (prompt embeds
shared, :45-61) -> upscale toward 1024px (:31-40; here two x2 latent-
upscaler passes instead of the reference's SD-x4-upscaler). The whole
cascade runs as jitted programs on the chip (pipelines/cascade.py).
"""

from __future__ import annotations

import time
from typing import Any

from chiaswarm_tpu.node.output_processor import OutputProcessor


def cascade_callback(slot, model_name: str, *, seed: int,
                     registry,
                     prompt: str = "",
                     negative_prompt: str = "",
                     num_inference_steps: int = 50,
                     sr_steps: int = 30,
                     guidance_scale: float = 7.0,
                     num_images_per_prompt: int = 1,
                     scheduler_type: str | None = None,
                     content_type: str = "image/png",
                     upscale: bool = True,
                     upscaler_model_name: str = (
                         "stabilityai/sd-x2-latent-upscaler"),
                     final_size: int | None = None,
                     **_ignored: Any):
    pipe = registry.cascade_pipeline(model_name,
                                     mesh=getattr(slot, "mesh", None))
    upscaler = None
    if upscale:
        # stage 3: x2 latent-upscale passes to 4 * sr_size (256 -> 1024),
        # replacing diffusion_func_if.py:31-40's SD-x4-upscaler stage;
        # the cascade pipeline owns the pass loop
        upscaler = registry.pipeline(
            upscaler_model_name, mesh=getattr(slot, "mesh", None))

    t0 = time.perf_counter()
    images, config = pipe(
        prompt=prompt or "",
        negative_prompt=negative_prompt or "",
        steps=int(num_inference_steps),
        sr_steps=int(sr_steps),
        guidance_scale=float(guidance_scale),
        batch=max(1, int(num_images_per_prompt)),
        seed=seed,
        scheduler=scheduler_type,
        upscaler=upscaler,
        final_size=final_size,
    )
    elapsed = time.perf_counter() - t0

    proc = OutputProcessor(content_type)
    proc.add_images(images)
    artifacts = proc.get_results()

    # stage-1's safety modules guard the final output in the reference
    # (diffusion_func_if.py:31-40,70-85); here the shared CLIP-concept
    # checker covers the cascade like every diffusion workload
    from chiaswarm_tpu.workloads.safety import check_images

    _, safety_fields = check_images(images, model_name)
    config.update(safety_fields)
    config.update({
        "images_per_sec": round(images.shape[0] / max(elapsed, 1e-9), 4),
        "generation_s": round(elapsed, 3),
        "slot": slot.descriptor() if hasattr(slot, "descriptor") else str(slot),
    })
    return artifacts, config
