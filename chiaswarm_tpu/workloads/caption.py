"""Image captioning / VQA workload (img2txt).

Capability parity with swarm/captioning/caption_image.py:6-40: the server
names a processor + model class (BLIP-style) via job ``parameters``; a
prompt makes it VQA, no prompt makes it unconditional captioning; output is
a JSON text artifact. Errors are swallowed into an error artifact exactly
like the reference (:35-40) — captioning failures should not poison a node.

TPU path: transformers' Flax BLIP classes run under jit on the chip. The
torch classes the hive may name are mapped to their Flax equivalents.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import make_text_result

# hive-sent torch class names -> Flax equivalents
_FLAX_CLASS = {
    "BlipForConditionalGeneration": "FlaxBlipForConditionalGeneration",
    "BlipForQuestionAnswering": "FlaxBlipForQuestionAnswering",
}


def caption_callback(slot, model_name: str, *, seed: int,
                     image: np.ndarray | None = None,
                     prompt: str = "",
                     parameters: dict[str, Any] | None = None,
                     **_ignored: Any):
    config: dict[str, Any] = {"model_name": model_name}
    try:
        if image is None:
            raise ValueError("img2txt requires start_image_uri")
        parameters = parameters or {}
        import transformers

        processor_name = parameters.get("processor_type", "BlipProcessor")
        model_cls_name = parameters.get(
            "model_type", "BlipForConditionalGeneration"
        )
        model_cls_name = _FLAX_CLASS.get(model_cls_name, model_cls_name)
        if not model_cls_name.startswith("Flax"):
            model_cls_name = "Flax" + model_cls_name

        import os

        offline = not os.environ.get("CHIASWARM_ALLOW_HUB_DOWNLOADS")
        processor = getattr(transformers, processor_name).from_pretrained(
            model_name, local_files_only=offline
        )
        model = getattr(transformers, model_cls_name).from_pretrained(
            model_name, from_pt=True, local_files_only=offline
        )

        from PIL import Image

        pil = Image.fromarray(image) if isinstance(image, np.ndarray) else image
        if prompt:
            inputs = processor(pil, prompt, return_tensors="np")
        else:
            inputs = processor(pil, return_tensors="np")
        out = model.generate(**inputs)
        sequences = getattr(out, "sequences", out)
        caption = processor.decode(
            np.asarray(sequences)[0], skip_special_tokens=True
        )
        config["caption"] = caption
        return {"primary": make_text_result(caption)}, config
    except Exception as exc:  # error artifact, not a failed job (:35-40)
        config["error"] = str(exc)
        return {"primary": make_text_result(str(exc))}, config
