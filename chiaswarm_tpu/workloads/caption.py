"""Image captioning / VQA workload (img2txt).

Capability parity with swarm/captioning/caption_image.py:6-40: a prompt
makes it VQA (when the checkpoint carries a question tower) or conditions
the caption, no prompt means unconditional captioning; output is a JSON
text artifact. Errors are swallowed into an error artifact exactly like
the reference (:35-40) — captioning failures should not poison a node.

TPU path is fully native (no torch at inference): BLIP vision ViT +
cross-attending BERT decoder (models/blip.py), greedy scan decode as one
compiled program, served resident through the registry LRU. The hive's
torch class names (``BlipForConditionalGeneration`` etc.,
caption_image.py:12-13) select behavior, not implementation: a
``*QuestionAnswering`` model type forces the VQA route.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from chiaswarm_tpu.node.output_processor import make_text_result


def caption_callback(slot, model_name: str, *, seed: int,
                     image: np.ndarray | None = None,
                     prompt: str = "",
                     parameters: dict[str, Any] | None = None,
                     registry=None,
                     **_ignored: Any):
    config: dict[str, Any] = {"model_name": model_name}
    try:
        if image is None:
            raise ValueError("img2txt requires start_image_uri")
        if registry is None:
            raise ValueError("img2txt requires a model registry")
        parameters = parameters or {}
        t0 = time.monotonic()
        pipeline = registry.caption_pipeline(
            model_name, mesh=getattr(slot, "mesh", None))
        wants_vqa = "QuestionAnswering" in str(
            parameters.get("model_type", ""))
        caption = pipeline(np.asarray(image), prompt or "", vqa=wants_vqa)
        config["caption"] = caption
        config["elapsed_s"] = round(time.monotonic() - t0, 3)
        return {"primary": make_text_result(caption)}, config
    except Exception as exc:  # error artifact, not a failed job (:35-40)
        config["error"] = str(exc)
        return {"primary": make_text_result(str(exc))}, config
