"""Stitch workload: collage prior job results into one image + HTML map.

Capability parity with swarm/toolbox/stitch.py:10-110 (no accelerator use):
download each job's result image, thumbnail to 144px with a 1-based index
label, paste onto a square grid, and return image-map metadata so the hive
UI can hyperlink each cell back to its source job.
"""

from __future__ import annotations

import math
from typing import Any

from PIL import Image, ImageDraw

from chiaswarm_tpu.node.output_processor import (
    OutputProcessor,
    make_result,
    encode_image,
    thumbnail,
)

THUMB = 144

#: byte cap for fetched RESULT images: stitch pulls the system's OWN
#: outputs, and an upscaled 2048px photographic PNG legitimately
#: exceeds the 3 MiB user-input cap — 32 MiB bounds memory without
#: rejecting real results (the decoded-dimension bomb guard still
#: applies underneath)
MAX_RESULT_BYTES = 32 * 1048576


def _fetch_image(url: str) -> Image.Image:
    # the ISSUE-10 trust-boundary guard set (connect/read timeouts,
    # streamed byte cap, content-type + decoded-dimension caps) —
    # stitch inputs are prior RESULT uris, but the fetch still crosses
    # the open network and deserves the same suspicion
    from chiaswarm_tpu.node.job_args import download_image

    return download_image(url, max_bytes=MAX_RESULT_BYTES)


def _thumb_with_label(image: Image.Image, index: int) -> Image.Image:
    img = image.copy()
    img.thumbnail((THUMB, THUMB), Image.Resampling.LANCZOS)
    draw = ImageDraw.Draw(img)
    draw.text((10, 10), str(index + 1), fill=(255, 255, 255))
    return img


def stitch_callback(slot, model_name: str, *, seed: int,
                    jobs: list[dict] | None = None,
                    images: list[Image.Image] | None = None,
                    **_ignored: Any):
    """``jobs`` carry ``resultUri`` links (hive schema); ``images`` allows
    direct injection for tests."""
    jobs = jobs or []
    if images is None:
        images = [_fetch_image(job["resultUri"]) for job in jobs]
    thumbs = [_thumb_with_label(img, i) for i, img in enumerate(images)]

    per_row = max(1, math.ceil(math.sqrt(len(thumbs))))
    canvas = Image.new("RGB", (per_row * THUMB, per_row * THUMB))
    image_map: list[dict[str, Any]] = []
    for i, img in enumerate(thumbs):
        x, y = (i % per_row) * THUMB, (i // per_row) * THUMB
        canvas.paste(img, (x, y))
        job = jobs[i] if i < len(jobs) else {}
        href = job.get("resultUri", "")
        image_map.append({
            "shape": "rect",
            "coords": f"{x},{y},{x + THUMB},{y + THUMB}",
            "href": href,
            "alt": job.get("model_name", f"Image {i + 1}"),
            "filename": job.get("fileName", href),
        })

    blob = encode_image(canvas, "image/jpeg")
    artifacts = {"primary": make_result(blob, "image/jpeg", thumbnail(canvas))}
    return artifacts, {"model_name": model_name, "image_map": image_map}
