"""Jittable diffusion schedulers.

Pure-function replacements for the diffusers scheduler objects the reference
resolves dynamically by class name at job time (swarm/job_arguments.py:143-148,
swarm/diffusion/diffusion_func.py:71-74 forces DPMSolverMultistep + Karras
sigmas). Every scheduler here is a set of pure functions over immutable
arrays, usable inside ``lax.scan``/``fori_loop`` under ``jit`` — no Python
state, no data-dependent control flow.

Scheduler names accepted by :func:`resolve` mirror the diffusers class names
the hive sends so the job wire format keeps working.
"""

from chiaswarm_tpu.schedulers.common import (
    NoiseSchedule,
    make_noise_schedule,
    add_noise,
    velocity_target,
)
from chiaswarm_tpu.schedulers.sampling import (
    FEWSTEP_KINDS,
    SamplerConfig,
    SamplingSchedule,
    make_sampling_schedule,
    scale_model_input,
    scale_model_input_rows,
    reproject_known,
    reproject_known_rows,
    sampler_step,
    sampler_step_rows,
    init_noise_scale,
    SAMPLERS,
    resolve,
)

__all__ = [
    "FEWSTEP_KINDS",
    "NoiseSchedule",
    "make_noise_schedule",
    "add_noise",
    "velocity_target",
    "SamplerConfig",
    "SamplingSchedule",
    "make_sampling_schedule",
    "scale_model_input",
    "scale_model_input_rows",
    "reproject_known",
    "reproject_known_rows",
    "sampler_step",
    "sampler_step_rows",
    "init_noise_scale",
    "SAMPLERS",
    "resolve",
]
