"""Unified sigma-space samplers: Euler, DDIM, Euler-ancestral, DPM-Solver++ 2M,
Heun — all as pure, scan-compatible step functions.

Design note (TPU-first): every sampler operates on latents in k-diffusion
coordinates ``x = x0 + sigma * eps`` with a precomputed sigma ladder, so the
whole denoise loop is a single ``lax.scan`` over a step index — no
data-dependent shapes, one compiled executable per (model, shape, N-steps).
Deterministic DDIM is the sigma-space Euler step evaluated on discrete-
timestep sigmas (they are algebraically identical under the change of
variables x_kd = x_vp / sqrt(alpha_bar)), which is why one framework covers
every scheduler class name the hive can send (swarm/job_arguments.py:143-148);
the reference's forced DPMSolverMultistep+Karras combination
(swarm/diffusion/diffusion_func.py:71-74) is ``dpmpp_2m`` with
``use_karras_sigmas=True``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from chiaswarm_tpu.schedulers.common import (
    NoiseSchedule,
    ScheduleConfig,
    denoised_from_model_output,
    karras_sigmas,
    make_noise_schedule,
    sigma_to_timestep,
)


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Static sampler selection — part of the jit cache key."""

    kind: str = "dpmpp_2m"  # "euler" | "ddim" | "euler_ancestral" | "dpmpp_2m" | "heun" | "lcm"
    use_karras_sigmas: bool = True
    timestep_spacing: str = "leading"  # "leading" | "trailing" | "linspace"
    steps_offset: int = 1
    prediction_type: str = "epsilon"


#: sampler kinds whose contract is the FEW-STEP regime (2–8 steps,
#: guidance embedded by distillation — CFG optional, guidance <= 1 is
#: the native mode). Lane eligibility consults this set: a guidance<=1
#: job of one of these kinds still rides a lane (workloads/diffusion.py)
#: instead of falling to the solo no-CFG program.
FEWSTEP_KINDS = frozenset({"lcm"})

#: LCM boundary-condition constants (Luo et al. 2023): sigma_data is the
#: consistency-model data scale, timestep_scaling the distillation pin —
#: both fixed by the published LCM/LCM-LoRA training recipe, not tunables.
LCM_SIGMA_DATA = 0.5
LCM_TIMESTEP_SCALING = 10.0


class SamplingSchedule(NamedTuple):
    sigmas: jnp.ndarray     # (N+1,), descending, sigmas[N] == 0
    timesteps: jnp.ndarray  # (N,) float32 model-conditioning timesteps


class SamplerState(NamedTuple):
    """Cross-step carry for multistep methods (scan-friendly)."""

    old_denoised: jnp.ndarray  # previous denoised estimate (zeros at step 0)


def _inference_timesteps(config: SamplerConfig, num_train: int, n: int) -> jnp.ndarray:
    if config.timestep_spacing == "leading":
        step = num_train // n
        ts = (jnp.arange(n, dtype=jnp.float32) * step) + config.steps_offset
    elif config.timestep_spacing == "trailing":
        ts = jnp.round(
            jnp.arange(num_train, 0, -num_train / n, dtype=jnp.float32)
        ) - 1.0
        ts = ts[::-1]
    elif config.timestep_spacing == "linspace":
        ts = jnp.linspace(0.0, num_train - 1, n, dtype=jnp.float32)
    else:
        raise ValueError(f"unknown timestep spacing {config.timestep_spacing!r}")
    return jnp.clip(ts, 0, num_train - 1)


def make_sampling_schedule(
    schedule: NoiseSchedule,
    num_steps: int,
    config: SamplerConfig,
) -> SamplingSchedule:
    """Build the descending sigma ladder + conditioning timesteps."""
    num_train = schedule.sigmas.shape[0]
    ts = _inference_timesteps(config, num_train, num_steps)  # ascending
    sigmas = jnp.interp(ts, jnp.arange(num_train, dtype=jnp.float32), schedule.sigmas)
    if config.use_karras_sigmas:
        sigmas = karras_sigmas(sigmas[0], sigmas[-1], num_steps)
        timesteps = sigma_to_timestep(schedule, sigmas)
    else:
        sigmas = sigmas[::-1]  # descending
        timesteps = ts[::-1]
    sigmas = jnp.concatenate([sigmas, jnp.zeros((1,), sigmas.dtype)])
    return SamplingSchedule(sigmas=sigmas.astype(jnp.float32),
                            timesteps=timesteps.astype(jnp.float32))


def make_edm_schedule(sigma_min: float, sigma_max: float,
                      num_steps: int) -> SamplingSchedule:
    """EDM continuous-sigma schedule (SVD-class): karras ladder over
    (sigma_min, sigma_max) with ``0.25 * log(sigma)`` conditioning —
    diffusers EulerDiscrete with ``timestep_type="continuous"``. The
    trailing zero sigma and the framework's v-prediction/input-scaling
    sigma-space math apply unchanged."""
    sig = karras_sigmas(jnp.float32(sigma_min), jnp.float32(sigma_max),
                        num_steps)
    return SamplingSchedule(
        sigmas=jnp.concatenate([sig, jnp.zeros((1,))]).astype(jnp.float32),
        timesteps=(0.25 * jnp.log(sig)).astype(jnp.float32))


def init_noise_scale(sched: SamplingSchedule) -> jnp.ndarray:
    """Initial latents = N(0,1) * sigma_max (k-diffusion convention)."""
    return sched.sigmas[0]


def scale_model_input(sched: SamplingSchedule, sample: jnp.ndarray,
                      i: jnp.ndarray) -> jnp.ndarray:
    """Pre-scale the model input: x / sqrt(sigma^2 + 1) maps k-diffusion
    coordinates back to the VP coordinates the UNet was trained in."""
    sigma = sched.sigmas[i]
    return (sample / jnp.sqrt(sigma ** 2 + 1.0)).astype(sample.dtype)


def init_sampler_state(sample: jnp.ndarray) -> SamplerState:
    return SamplerState(old_denoised=jnp.zeros_like(sample))


def _sigma_t(sched: SamplingSchedule, i) -> tuple[jnp.ndarray, jnp.ndarray]:
    return sched.sigmas[i], sched.sigmas[i + 1]


def sampler_step(
    config: SamplerConfig,
    sched: SamplingSchedule,
    i: jnp.ndarray,
    sample: jnp.ndarray,
    model_output: jnp.ndarray,
    state: SamplerState,
    noise: jnp.ndarray | None = None,
    start_index: int = 0,
) -> tuple[jnp.ndarray, SamplerState]:
    """One denoise step. ``i`` is the (traced) step index, 0..N-1.

    ``noise`` (same shape as sample) is consumed only by ancestral samplers;
    deterministic samplers ignore it. ``start_index`` is the first index the
    loop actually executes (img2img starts partway down the ladder) — the
    multistep history fallback keys off it, not off absolute 0.
    """
    sigma, sigma_next = _sigma_t(sched, i)
    compute = jnp.float32
    x = sample.astype(compute)
    denoised = denoised_from_model_output(
        model_output.astype(compute), x, sigma, config.prediction_type
    )

    if config.kind in ("euler", "ddim", "heun"):
        # (heun's corrector needs a second model eval per step; the predictor
        # alone is the euler step — the pipeline loop upgrades it when it
        # supplies the second eval. Kept as euler here.)
        d = (x - denoised) / sigma
        x_next = x + (sigma_next - sigma) * d
    elif config.kind == "euler_ancestral":
        if noise is None:
            raise ValueError("euler_ancestral requires noise")
        var = sigma_next ** 2 * (sigma ** 2 - sigma_next ** 2) / sigma ** 2
        sigma_up = jnp.sqrt(jnp.maximum(var, 0.0))
        sigma_down = jnp.sqrt(jnp.maximum(sigma_next ** 2 - sigma_up ** 2, 0.0))
        d = (x - denoised) / sigma
        x_next = x + (sigma_down - sigma) * d + noise.astype(compute) * sigma_up
    elif config.kind == "lcm":
        # Latent Consistency Model multistep (Luo et al. 2023): the
        # boundary condition blends the VP-space sample with the x0
        # estimate via c_skip/c_out (exact identity at sigma -> 0), then
        # the sampler re-noises FULLY onto the next ladder level — not
        # ancestral's partial sigma_up. Each step lands on a
        # self-consistent x0 estimate, which is why 2–8 steps suffice
        # for a distilled checkpoint. ``denoised`` is rebound to the
        # boundary-conditioned value so the final-step override below
        # returns it (LCMScheduler's last step emits denoised, no noise).
        if noise is None:
            raise ValueError("lcm requires noise")
        ts = sched.timesteps[i] * LCM_TIMESTEP_SCALING
        c_skip = LCM_SIGMA_DATA ** 2 / (ts ** 2 + LCM_SIGMA_DATA ** 2)
        c_out = ts / jnp.sqrt(ts ** 2 + LCM_SIGMA_DATA ** 2)
        sample_vp = x / jnp.sqrt(sigma ** 2 + 1.0)
        denoised = c_skip * sample_vp + c_out * denoised
        x_next = denoised + noise.astype(compute) * sigma_next
    elif config.kind == "dpmpp_2m":
        # DPM-Solver++(2M), data-prediction multistep, sigma domain.
        t_fn = lambda s: -jnp.log(jnp.maximum(s, 1e-10))
        t, t_next = t_fn(sigma), t_fn(sigma_next)
        h = t_next - t
        sigma_prev = sched.sigmas[jnp.maximum(i - 1, 0)]
        h_last = t - t_fn(sigma_prev)
        r = h_last / h
        old = state.old_denoised.astype(compute)
        denoised_d = (1.0 + 1.0 / (2.0 * r)) * denoised - (1.0 / (2.0 * r)) * old
        # first executed step (no history) and final step (sigma_next==0)
        # fall back to the first-order update.
        first_or_last = jnp.logical_or(i == start_index, sigma_next == 0.0)
        use_d = jnp.where(first_or_last, denoised, denoised_d)
        x_next = (sigma_next / sigma) * x - jnp.expm1(-h) * use_d
    else:
        raise ValueError(f"unknown sampler kind {config.kind!r}")

    x_next = jnp.where(sigma_next == 0.0, denoised, x_next)
    return x_next.astype(sample.dtype), SamplerState(old_denoised=denoised.astype(sample.dtype))


def reproject_known(sched: SamplingSchedule, i: jnp.ndarray,
                    sample: jnp.ndarray, known: jnp.ndarray,
                    mask: jnp.ndarray, renoise: jnp.ndarray) -> jnp.ndarray:
    """Model-agnostic ("legacy") inpainting step: after the sampler step
    to noise level ``i+1``, re-noise the clean source latents onto that
    level and paste them into the kept (mask == 0) region. ``mask`` is 1
    where the model regenerates. One function shared by the solo denoise
    scan (pipelines/diffusion.py) and the per-row lane step below, so an
    inpaint row's trajectory in a lane is the solo math by construction."""
    known_t = known + renoise * sched.sigmas[i + 1]
    return sample * mask + known_t * (1.0 - mask)


def reproject_known_rows(sched: SamplingSchedule, i: jnp.ndarray,
                         sample: jnp.ndarray, known: jnp.ndarray,
                         mask: jnp.ndarray,
                         renoise: jnp.ndarray) -> jnp.ndarray:
    """Per-row :func:`reproject_known`: each row carries its own sigma
    ladder (B, S+1) and step index (B,) — inpaint rows at different
    ladder positions coexist in one lane program (serving/stepper.py)."""
    return jax.vmap(reproject_known)(sched, i, sample, known, mask, renoise)


def scale_model_input_rows(sched: SamplingSchedule, sample: jnp.ndarray,
                           i: jnp.ndarray) -> jnp.ndarray:
    """Per-row :func:`scale_model_input`: every array in ``sched`` carries
    a leading batch dim (each row owns its own sigma ladder) and ``i`` is
    a (B,) vector of per-row step indices — rows at different ladder
    positions coexist in one batched program (serving/stepper.py)."""
    return jax.vmap(scale_model_input)(sched, sample, i)


def sampler_step_rows(
    config: SamplerConfig,
    sched: SamplingSchedule,
    i: jnp.ndarray,
    sample: jnp.ndarray,
    model_output: jnp.ndarray,
    state: SamplerState,
    noise: jnp.ndarray,
    start_index: jnp.ndarray,
) -> tuple[jnp.ndarray, SamplerState]:
    """Per-row :func:`sampler_step` — the continuous-batching quantum.

    ``sched.sigmas`` is (B, S+1) and ``sched.timesteps`` (B, S): each row
    carries its OWN ladder (different jobs may run different step counts),
    ``i``/``start_index`` are (B,) per-row positions. Implemented as a
    ``vmap`` of the scalar step so the math — and therefore every row's
    trajectory — is identical to the solo scan path by construction.
    """
    def one(sched_b, i_b, x_b, eps_b, state_b, noise_b, start_b):
        return sampler_step(config, sched_b, i_b, x_b, eps_b, state_b,
                            noise=noise_b, start_index=start_b)

    return jax.vmap(one)(sched, i, sample, model_output, state, noise,
                         start_index)


# diffusers class name (as sent by the hive) -> sampler kind
SAMPLERS: dict[str, str] = {
    "DDIMScheduler": "ddim",
    "PNDMScheduler": "dpmpp_2m",  # nearest deterministic multistep equivalent
    "EulerDiscreteScheduler": "euler",
    "EulerAncestralDiscreteScheduler": "euler_ancestral",
    "DPMSolverMultistepScheduler": "dpmpp_2m",
    "DPMSolverSinglestepScheduler": "dpmpp_2m",
    "UniPCMultistepScheduler": "dpmpp_2m",
    "HeunDiscreteScheduler": "heun",
    "KDPM2DiscreteScheduler": "dpmpp_2m",
    "LMSDiscreteScheduler": "euler",
    "DDPMScheduler": "euler_ancestral",
    # few-step family (ISSUE 12): LCM-distilled checkpoints and the
    # trajectory-consistency variant resolve onto the lcm boundary-
    # condition step — the hive requests them by class name exactly
    # like every other scheduler
    "LCMScheduler": "lcm",
    "TCDScheduler": "lcm",
}


def resolve(name: str | None, *, prediction_type: str = "epsilon",
            use_karras_sigmas: bool = True) -> SamplerConfig:
    """Map a hive-supplied diffusers scheduler class name to a SamplerConfig
    (parity with get_type-based resolution at swarm/job_arguments.py:143-148)."""
    kind = SAMPLERS.get(name or "", "dpmpp_2m")
    if kind == "lcm":
        # the timestep-SHIFTED few-step ladder: trailing spacing lands
        # the first step at t=999 (the distillation boundary) and the
        # last near the data end — LCMScheduler's lcm-origin ladder
        # selects the same suffix. Karras respacing would move the
        # boundary timesteps the distillation pinned, so it is forced
        # off for this kind regardless of the caller's default.
        return SamplerConfig(
            kind=kind,
            use_karras_sigmas=False,
            timestep_spacing="trailing",
            prediction_type=prediction_type,
        )
    return SamplerConfig(
        kind=kind,
        use_karras_sigmas=use_karras_sigmas,
        prediction_type=prediction_type,
    )


def default_schedule_config(model_family: str = "sd") -> ScheduleConfig:
    if model_family in ("sd", "sdxl"):
        return ScheduleConfig()
    if model_family == "sd2":
        return ScheduleConfig(prediction_type="v_prediction")
    if model_family == "if":
        return ScheduleConfig(beta_schedule="squaredcos_cap_v2",
                              beta_start=0.0001, beta_end=0.02)
    raise ValueError(f"unknown model family {model_family!r}")


def make_for(model_family: str, num_steps: int, sampler: SamplerConfig):
    """Convenience: (NoiseSchedule, SamplingSchedule) for a model family."""
    cfg = default_schedule_config(model_family)
    cfg = dataclasses.replace(cfg, prediction_type=sampler.prediction_type)
    ns = make_noise_schedule(cfg)
    return ns, make_sampling_schedule(ns, num_steps, sampler)
