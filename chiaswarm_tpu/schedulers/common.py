"""Shared noise-schedule math (beta ladders, alpha-bar, Karras sigmas).

Replaces the numerical core of the diffusers schedulers the reference uses
(resolved by name at swarm/job_arguments.py:143-148). Everything is a pure
function of arrays; nothing here holds state.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ScheduleConfig:
    """Static description of a model's training noise schedule."""

    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"  # "linear" | "scaled_linear" | "squaredcos_cap_v2"
    prediction_type: str = "epsilon"      # "epsilon" | "v_prediction" | "sample"


class NoiseSchedule(NamedTuple):
    """Precomputed per-train-timestep tables."""

    betas: jnp.ndarray            # (T_train,)
    alphas_cumprod: jnp.ndarray   # (T_train,)
    sigmas: jnp.ndarray           # (T_train,) k-diffusion sigma(t) = sqrt((1-a)/a)


def make_betas(config: ScheduleConfig) -> jnp.ndarray:
    T = config.num_train_timesteps
    if config.beta_schedule == "linear":
        return jnp.linspace(config.beta_start, config.beta_end, T, dtype=jnp.float32)
    if config.beta_schedule == "scaled_linear":
        return jnp.linspace(
            config.beta_start ** 0.5, config.beta_end ** 0.5, T, dtype=jnp.float32
        ) ** 2
    if config.beta_schedule == "squaredcos_cap_v2":
        # cosine schedule (used by the DeepFloyd-IF family)
        def alpha_bar(t):
            return jnp.cos((t + 0.008) / 1.008 * jnp.pi / 2) ** 2

        t1 = jnp.arange(T, dtype=jnp.float32) / T
        t2 = (jnp.arange(T, dtype=jnp.float32) + 1) / T
        return jnp.clip(1.0 - alpha_bar(t2) / alpha_bar(t1), 0.0, 0.999)
    raise ValueError(f"unknown beta schedule {config.beta_schedule!r}")


def make_noise_schedule(config: ScheduleConfig) -> NoiseSchedule:
    betas = make_betas(config)
    alphas_cumprod = jnp.cumprod(1.0 - betas)
    sigmas = jnp.sqrt((1.0 - alphas_cumprod) / alphas_cumprod)
    return NoiseSchedule(betas=betas, alphas_cumprod=alphas_cumprod, sigmas=sigmas)


def karras_sigmas(sigma_min: jnp.ndarray, sigma_max: jnp.ndarray, n: int,
                  rho: float = 7.0) -> jnp.ndarray:
    """Karras et al. (2022) sigma ladder, high to low, length n."""
    ramp = jnp.linspace(0.0, 1.0, n)
    min_inv_rho = sigma_min ** (1.0 / rho)
    max_inv_rho = sigma_max ** (1.0 / rho)
    return (max_inv_rho + ramp * (min_inv_rho - max_inv_rho)) ** rho


def sigma_to_timestep(schedule: NoiseSchedule, sigma: jnp.ndarray) -> jnp.ndarray:
    """Map sigma -> (fractional) train timestep by log-sigma interpolation,
    so models conditioned on discrete timesteps accept Karras sigmas."""
    log_sigma = jnp.log(jnp.maximum(sigma, 1e-10))
    log_table = jnp.log(schedule.sigmas)
    return jnp.interp(log_sigma, log_table, jnp.arange(log_table.shape[0], dtype=jnp.float32))


def add_noise(schedule: NoiseSchedule, x0: jnp.ndarray, noise: jnp.ndarray,
              t: jnp.ndarray) -> jnp.ndarray:
    """Forward process q(x_t | x_0) — used by img2img/inpaint init and by the
    training loss."""
    a = schedule.alphas_cumprod[t].astype(x0.dtype)
    a = a.reshape(a.shape + (1,) * (x0.ndim - a.ndim))
    return jnp.sqrt(a) * x0 + jnp.sqrt(1.0 - a) * noise


def velocity_target(schedule: NoiseSchedule, x0: jnp.ndarray, noise: jnp.ndarray,
                    t: jnp.ndarray) -> jnp.ndarray:
    """v-prediction target (SD 2.1-style): v = sqrt(a) eps - sqrt(1-a) x0."""
    a = schedule.alphas_cumprod[t].astype(x0.dtype)
    a = a.reshape(a.shape + (1,) * (x0.ndim - a.ndim))
    return jnp.sqrt(a) * noise - jnp.sqrt(1.0 - a) * x0


def denoised_from_model_output(model_output: jnp.ndarray, sample: jnp.ndarray,
                               sigma: jnp.ndarray, prediction_type: str) -> jnp.ndarray:
    """Convert a model output at noise level ``sigma`` into a denoised (x0)
    estimate, for samples living in k-diffusion space x = x0 + sigma * eps.

    ``sigma`` broadcasts over the sample's trailing dims.
    """
    sigma = jnp.asarray(sigma, dtype=jnp.float32)
    sigma = sigma.reshape(sigma.shape + (1,) * (sample.ndim - sigma.ndim))
    if prediction_type == "epsilon":
        return sample - sigma * model_output
    if prediction_type == "v_prediction":
        s2 = sigma ** 2
        return sample / (s2 + 1.0) - model_output * sigma / jnp.sqrt(s2 + 1.0)
    if prediction_type == "sample":
        return model_output
    raise ValueError(f"unknown prediction type {prediction_type!r}")
