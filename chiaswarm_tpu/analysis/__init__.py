"""swarmlint — AST-based static analysis for the repo's TPU invariants.

TPU throughput lives or dies on invariants the CUDA reference never
needed: no recompilation in the job loop, no host<->device sync inside
jitted code, stateless PRNG discipline, and survival across JAX's API
churn on the pinned version (``core/compat.py``). The runtime modules
document these in prose; this package enforces them at zero runtime cost.

Stdlib-only (``ast`` + ``json``): the linter must run in CI images and
pre-commit hooks that have no jax installed.

Entry points:

- ``python -m chiaswarm_tpu.analysis [paths...]`` — CLI (see __main__.py)
- :func:`run` — programmatic entry used by ``tests/test_lint.py``
- :func:`analyze_source` — lint one source string (rule fixture tests)

Rules (registered in ``chiaswarm_tpu.analysis.rules``):

====  ======================  ===============================================
code  name                    invariant
====  ======================  ===============================================
R1    host-sync-in-jit        no .item()/device_get/np.asarray/... reachable
                              from jitted or traced code
R2    prng-key-reuse          a PRNG key feeds at most one jax.random draw
                              before a split/fold_in rebinds it
R3    compat-import           jax API churn goes through core/compat.py,
                              never direct imports of shimmed symbols
R4    import-time-device-init no jax.devices()/device_count() at module
                              scope (breaks JAX_PLATFORMS selection & makes
                              imports backend-dependent)
R5    jit-hygiene             serving-path jits use compile_cache.toplevel_jit
                              (CHIASWARM_XLA_OPTIONS) and never donate the
                              cache-resident param tree
R6    recompile-hazard        raw request shapes reach compiled code only
                              through the shape-bucketing helpers
R7    scan-carry-dtype        mixed-precision scan/loop bodies pin the carry
                              dtype before returning it
R8    wallclock-duration      durations come from perf_counter/monotonic,
                              never time.time() subtraction
R9    host-sync-reachability  R1 across module boundaries: host syncs
                              reachable from jit through the whole-program
                              call graph (full chain in the finding)
R10   sharding-spec-drift     PartitionSpec/shard_map/collective axis names
                              bound by THE mesh instance the site runs on
                              (per-mesh-instance universes); in_specs arity
                              matches the callee signature
R11   replicated-psum         no psum/psum_scatter over an axis the operand
                              is provably replicated on (the product is
                              complete on every shard — the all-reduce
                              multiplies by the axis size)
R12   unreduced-out-spec      shard_map out_specs never claims replication
                              over an axis the returned value still varies
                              on (partial sums don't escape mislabeled)
R13   donation-drift          a buffer donated to a jitted wrapper is never
                              read after the call (compiled half: the HLO
                              alias table kept the donation — shard_audit)
R14   cross-thread-device-    a jit-produced (async-dispatched) value is
      handoff                 synchronized (block_until_ready/.copy()) before
                              it is published to state another execution
                              root consumes
R15   unguarded-shared-       state written under a lock somewhere is never
      mutation                mutated lock-free on a concurrent root (the
                              RacerD mostly-locked discipline)
R16   lock-order-inversion    no two concurrent roots take the same lock
                              pair in opposite (ABBA) order
R17   await-or-blocking-      no await while holding a threading lock; no
      under-lock              time.sleep/socket/subprocess on the event loop
                              (executor-dispatched helpers exempt)
R18   unkeyed-trace-input     every trace-affecting env knob (read at trace
                              time, or frozen into a module constant a
                              traced body loads) is folded into the
                              executable-cache key
R19   frozen-env-reread       no env read inside a build/traced scope — it
                              executes once per cache slot, not per call
R20   unstable-key-component  no id()/hash()/repr() in the persistent key
                              surface (cache_fingerprint/artifact_cache_key);
                              in-process static_cache_key owners may keep id()
R21   cache-tag-collision     no two distinct build callables share one
                              (owner, tag, statics) cache vocabulary
====  ======================  ===============================================

**The project index** (``analysis/project.py``, "swarmflow"): R1-R8 are
single-file AST passes sharing a per-file :class:`ModuleContext`; R9/R10
subclass :class:`ProjectRule` and run once per lint against a
:class:`~.project.ProjectIndex` built over every linted file — module
graph with relative imports resolved, top-level symbol resolution
following ``from x import y`` re-export chains (the ``core/compat``
shims), string-constant resolution (mesh axis names), and a conservative
call graph keyed by ``(module, qualname)`` (edges only where the callee
resolves statically: import aliases, dotted paths, ``self.``/``cls.``
methods, ``functools.partial`` unwrapped). Per-file summaries are plain
JSON dicts cached in ``.swarmflow-cache.json`` keyed on content hashes,
so a warm lint re-summarizes only edited files. Interprocedural findings
carry a ``chain:`` trace (entry point -> ... -> sink) in text, ``--json``
and ``--sarif`` output; the baseline key deliberately excludes the chain
so grandfathered entries survive unrelated reroutes of intermediate hops.

**The shardflow layer** (``analysis/shardflow.py``, "swarmproof"):
R11/R12/R13 go one level deeper than the index's *facts* — an abstract
interpreter over the summaries' flow IR tracks, per value, the set of
mesh axes it varies over vs is replicated over (the vma lattice jax's
``shard_map`` checker enforces at trace time), entering at every
``shard_map`` site, binding ``in_specs`` to parameter abstractions,
descending through the R9 call machinery (named callees, lambdas,
``functools.partial``, ``lax.scan``/``while``/``fori``/``cond`` bodies,
nested closures) with memoized per-context summaries, applying
collective transfer functions (``psum``/``all_gather`` remove the axis;
``ppermute`` keeps it; ``axis_index`` introduces it), and checking
``out_specs`` claims on the way out. Mesh instances are resolved per
site (``project.py`` records ``Mesh(...)`` literals as *closed*
universes, ``MeshSpec``-built meshes as *open*), so distinct meshes are
distinct domains. The analysis is two-sided (``may`` ⊇ ``must``) and
conservative: anything unresolvable is silent. The compiled-side twin
(``analysis/hlocheck.py`` + ``tools/shard_audit.py``) audits what XLA
actually lowered — collective census, matmul dtype census, donation
aliasing — against pinned per-program contracts
(``tools/contracts/tiny.json`` in CI).

**The raceflow layer** (``analysis/raceflow.py``, "swarmrace"): R14-R17
are the third interpreter over the same index — where shardflow asks
*what axes a value varies over*, raceflow asks *which execution roots a
statement runs under and which locks it holds*. A thread-topology pass
roots the call graph at every statically resolvable spawn site
(``threading.Thread``/``Timer``, ``run_in_executor``,
``asyncio.create_task`` and every coroutine sharing one event-loop
root, ``io_callback``/``weakref.finalize`` registrations); a
lock-discipline pass models ``with lock:`` regions (instance-attribute,
module-global and parameter-passed locks), computes per-access guard
sets with RacerD-style entry-held credit (a ``*_locked`` helper whose
every recorded call site holds the lock counts as guarded), and builds
the lock-order graph; a handoff pass taints jit-wrapper results flowing
into shared containers.

**The keyflow layer** (``analysis/keyflow.py``, "swarmkey"): R18-R21
are the fourth interpreter — where raceflow asks *which execution roots
a statement runs under*, keyflow asks *which inputs the traced program
consumed and whether the executable-cache key knows*. A keyed-set pass
BFSes the call graph from the key builders (``static_cache_key``/
``cache_fingerprint``/``artifact_cache_key``) collecting every env-var
name that reaches the key; a traced-reach pass roots at the jit entry
points (an env read there is baked into the executable) and a
build-scope pass marks factory closures and jit roots (a read there
runs once per cache slot). The compiled-side twin
(``tools/key_audit.py``) builds the real tiny programs under each knob
and asserts executable identity changes iff the key changes. The four
project interpreters are deliberately layered on ONE summary extraction
(``project.py``, ``SCHEMA``-versioned cache): swarmflow resolves *names
and calls*, shardflow adds *value semantics*, raceflow adds *execution
context*, keyflow adds *input provenance* — each reuses the call-graph
machinery, chain rendering, and the baseline/marker conventions of the
layer below.

Baseline workflow: first adoption of a rule grandfathers existing findings
into ``.swarmlint-baseline.json`` (``--write-baseline``). New findings fail;
fixing a baselined finding makes its entry stale, which fails under
``--strict`` until the entry is deleted — the baseline can only shrink.
``--changed-only`` lints just the files changed vs the merge base with
origin/main plus their reverse-dependency closure from the import graph
(pre-commit; editing a mesh-defining module additionally re-lints every
sharding consumer — axes travel through parameters, not imports —
editing a module that defines an execution root or lock re-lints every
module with concurrency facts, since roots and guards cross module
boundaries without import edges too, and editing a key-builder or
knob-defining module re-lints every compile-cached program site — the
keyed set and the traced reach are both global properties);
``--sarif FILE`` exports new
findings for GitHub code scanning with chains as codeFlows.
"""

from chiaswarm_tpu.analysis.core import (
    Finding,
    ModuleContext,
    ProjectRule,
    Rule,
    all_rules,
    analyze_paths,
    analyze_source,
    get_rule,
)
from chiaswarm_tpu.analysis.baseline import (
    Baseline,
    DEFAULT_BASELINE_NAME,
    load_baseline,
    write_baseline,
)
from chiaswarm_tpu.analysis.project import ProjectIndex
from chiaswarm_tpu.analysis.runner import run

__all__ = [
    "Baseline",
    "DEFAULT_BASELINE_NAME",
    "Finding",
    "ModuleContext",
    "ProjectIndex",
    "ProjectRule",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "get_rule",
    "load_baseline",
    "run",
    "write_baseline",
]
