"""shardflow — abstract sharding interpreter over the swarmflow index.

The GSPMD divergence family (ROADMAP item 1) is a *value-semantics* bug
class: a replicated operand crosses a two-axis ``shard_map`` boundary,
gets multiplied into a product that is already complete on every shard,
and an ``all-reduce`` over the second axis then multiplies the result by
the axis size (the r06 bisect's exact ``seq``× K/V blow-up). R10 checks
axis-name *spelling*; nothing checked axis *semantics*. This module runs
the same varying-axes discipline jax's own shard_map vma checker applies
at trace time — as a whole-program static pass over the swarmflow
project index, no jax import, no tracing.

**The abstract domain.** Every value is abstracted to the set of mesh
axes it *varies over* (distinct per-shard content) vs is *replicated
over* (identical on every shard along that axis) — the vma lattice. The
analysis is may/must two-sided so one-sided conclusions stay sound under
conditional specs (``P(DATA if b % dp == 0 else None, SEQ, …)``):

- ``may``: upper bound — axes the value *can* vary over. An axis outside
  ``may`` is **provably replicated**: summing it with ``psum`` multiplies
  by the axis size (rule R11 ``replicated-psum``).
- ``must``: lower bound — axes the value *definitely* varies over. An
  axis inside ``must`` that the site's ``out_specs`` claims replicated,
  with no collective having reduced it, escapes as a partial sum /
  per-shard value mislabeled replicated (rule R12 ``unreduced-out-spec``).

**Transfer functions** (mirroring shard_map's vma rules):

- ``in_specs`` bind a parameter's axes: mentioned axes → varying,
  unmentioned mesh axes → replicated. Conditional dims contribute to
  ``may`` only.
- arithmetic / unknown ops: union (varying is infectious).
- ``psum``/``pmean``/``pmax``/``pmin``/``all_gather``/``psum_scatter``
  over axis *a*: *a* leaves the varying sets (the result is identical on
  every shard along *a*).
- ``ppermute``/``pshuffle``/``all_to_all``: varying sets unchanged.
- ``axis_index(a)``: introduces {*a*}.
- either/or joins (``IfExp``): ``may`` unions, ``must`` intersects.
- closures and ``functools.partial``-bound operands: replicated (shard_map
  broadcasts captured values — which is exactly why a psum over them is
  the 4.000× mislabel).

**Per-mesh-instance universes** (the carried R10 extension): each
``Mesh(…)`` literal / ``build_mesh(MeshSpec({…}))`` assignment is its own
axis universe, resolved per shard_map site through locals, module
constants and re-exports — a ``data``×``seq`` mesh and a pure-``seq``
mesh are distinct domains, so the family signature "one sharded axis
fine, two axes wrong" is expressible, and axis names from unrelated
meshes no longer pool into one global soup. ``MeshSpec``-derived meshes
are *open* (core/mesh.py materializes every vocabulary axis at size ≥ 1);
raw ``Mesh`` literals are *closed*.

Interpretation enters at every ``shard_map`` site, descends through the
R9 call-graph machinery (named callees, lambdas, ``functools.partial``,
``jax.lax.scan``/``while_loop``/``fori_loop``/``cond`` bodies, nested
closures) with memoized per-context summaries, and reports findings with
full entry → sink chains. ``custom_vjp``/``custom_jvp`` primals carry
their registered fwd/bwd/jvp companions along: jax dispatches those
bodies inside the same shard_map context with no visible call edge, so
the interpreter explores them whenever the primal is reached, binding
every companion parameter to the combined varying-ness of the primal's
arguments (residuals/cotangents derive from them).

R13 ``donation-drift`` rides the same flow IR: a buffer donated at a
jit-wrapper call site (``donate_argnums``/``donate_argnames``, declared
where the wrapper is built — possibly another module, followed through
re-exports) that the caller READS after the call is garbage on TPU; the
compiled-side twin (``analysis/hlocheck.py``) verifies declared donation
actually materialized in the lowered program's aliasing table.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

from chiaswarm_tpu.analysis.core import Finding
from chiaswarm_tpu.analysis.project import _COLLECTIVES, ProjectIndex
from chiaswarm_tpu.analysis.rules import resolves_to

R11 = "replicated-psum"
R12 = "unreduced-out-spec"
R13 = "donation-drift"

#: collectives whose result is invariant over the named axis
_REMOVES_AXIS = ("jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax",
                 "jax.lax.pmin", "jax.lax.all_gather",
                 "jax.lax.psum_scatter")
#: sum-like reductions where reducing an already-invariant value
#: multiplies it by the axis size — the exact r06 mislabel
_R11_OPS = ("jax.lax.psum", "jax.lax.psum_scatter")
#: collectives that move shards around but keep the value varying
_KEEPS_AXIS = ("jax.lax.ppermute", "jax.lax.pshuffle",
               "jax.lax.all_to_all")

_MAX_DEPTH = 10


# ---------------------------------------------------------------------------
# the abstract domain


@dataclasses.dataclass(frozen=True)
class VMA:
    """Varying-mesh-axes abstraction of one value: ``may`` ⊇ ``must``."""

    may: frozenset[str] = frozenset()
    must: frozenset[str] = frozenset()

    @staticmethod
    def empty() -> "VMA":
        return _EMPTY

    @staticmethod
    def top(universe: Iterable[str]) -> "VMA":
        return VMA(may=frozenset(universe))

    @staticmethod
    def combine(*vmas: "VMA") -> "VMA":
        """Arithmetic/dataflow meet: varying is infectious on both sides
        (if either operand definitely varies, the result does)."""
        may: frozenset[str] = frozenset()
        must: frozenset[str] = frozenset()
        for v in vmas:
            may |= v.may
            must |= v.must
        return VMA(may, must)

    @staticmethod
    def join(a: "VMA", b: "VMA") -> "VMA":
        """Either/or control join: ``may`` unions, ``must`` intersects."""
        return VMA(a.may | b.may, a.must & b.must)

    def remove(self, axis: str) -> "VMA":
        return VMA(self.may - {axis}, self.must - {axis})

    def introduce(self, axis: str) -> "VMA":
        return VMA(self.may | {axis}, self.must | {axis})


_EMPTY = VMA()


class _State:
    """Per-function environment: name → VMA, name → axis string, with an
    outer chain for nested closures (a scan body reading the enclosing
    function's ``q`` / ``axis_name``)."""

    def __init__(self, env: dict[str, VMA], axes: dict[str, str],
                 outer: "_State | None" = None):
        self.env = env
        self.axes = axes
        self.outer = outer

    def lookup(self, name: str) -> VMA | None:
        st: _State | None = self
        while st is not None:
            if name in st.env:
                return st.env[name]
            st = st.outer
        return None

    def axis_of(self, name: str) -> str | None:
        st: _State | None = self
        while st is not None:
            if name in st.axes:
                return st.axes[name]
            st = st.outer
        return None


# ---------------------------------------------------------------------------
# the interpreter


@dataclasses.dataclass
class _SiteCtx:
    """Interpretation context for one function activation."""

    module: str
    qual: str
    rel: str
    universe: frozenset[str]
    chain: tuple[tuple[str, int, str], ...]
    depth: int


class ShardflowAnalysis:
    """One run over the index: interprets every shard_map site and
    collects R11/R12 findings. Rules share a single analysis via
    :func:`results`."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()
        self._memo: dict[tuple, VMA] = {}
        self._active: set[tuple] = set()
        self._global_universe = frozenset(index.axis_universe())
        # custom_vjp/custom_jvp registrations: primal key -> companion
        # (fwd/bwd/jvp) keys + the defvjp site as a chain hop. jax calls
        # the companions, not user code, so the ordinary call graph
        # never reaches their bodies.
        self._customvjp: dict[tuple[str, str],
                              list[tuple[tuple[str, str],
                                         tuple[str, int, str]]]] = {}
        for rel in sorted(index.summaries):
            s = index.summaries[rel]
            module = s["module"]
            for rec in s.get("customvjp", ()):
                ptargets = index.func_targets(module, rec["p"])
                if len(ptargets) != 1:
                    continue
                hop = (rel, rec["ln"], f"{module}.{rec['p']}.defvjp")
                lst = self._customvjp.setdefault(ptargets[0], [])
                for ref in rec["fns"]:
                    for t in index.func_targets(module, ref):
                        if t != ptargets[0]:
                            lst.append((t, hop))

    # -- entry -------------------------------------------------------------
    def run(self) -> "ShardflowAnalysis":
        for rel in sorted(self.index.summaries):
            s = self.index.summaries[rel]
            for rec in s.get("shard_maps", ()):
                self._site(rel, s, rec)
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return self

    def _emit(self, finding: Finding) -> None:
        key = (finding.rule, finding.path, finding.line, finding.col,
               finding.message)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(finding)

    # -- per-site ----------------------------------------------------------
    def _site(self, rel: str, s: dict, rec: dict) -> None:
        module = s["module"]
        inst = self.index.resolve_mesh(module, rec["symbol"],
                                       rec.get("mesh"))
        if inst is None:
            universe = self._global_universe
        elif inst["open"]:
            # open (MeshSpec-built) meshes carry the whole vocabulary;
            # widening to the global universe keeps TOP conservative
            universe = frozenset(inst["axes"]) | self._global_universe
        else:
            universe = frozenset(inst["axes"])
        if not universe:
            return  # no meshes anywhere: nothing to vary over

        callee = self._site_callee(module, rec)
        if callee is None:
            return
        f = self.index.funcs.get(callee)
        arity = len(f["pargs"]) if f else 0
        params = self._bind_params(module, rec, universe, arity)
        if params is None:
            return
        args, axes_kw = params
        site_hop = (rel, rec["line"], f"{module}.{rec['symbol']}")
        ret = self._interpret(callee, args, {}, axes_kw, universe,
                              (site_hop,), 0, outer=None)

        # R12: the site's out_specs claim replication over an axis the
        # returned value still (provably) varies on
        out = rec.get("out_axes")
        if out is None or ret is None:
            return
        out_may: set[str] = set()
        for ref in out["may"]:
            v = self.index.resolve_axis(ref, module)
            if v is None:
                return  # unresolvable out spec: stay silent
            out_may.add(v)
        leaked = sorted((ret.must & universe) - out_may)
        if leaked:
            f = self.index.funcs.get(callee)
            callee_hop = (self.index.modules[callee[0]],
                          f["line"] if f else 0,
                          f"{callee[0]}.{callee[1]}")
            self._emit(Finding(
                rule=R12, path=rel, line=rec["line"], col=rec["col"],
                message=(f"out_specs claims replication over "
                         f"{'/'.join(repr(a) for a in leaked)} but the "
                         f"returned value still varies over "
                         f"{'/'.join(repr(a) for a in leaked)} — a "
                         f"per-shard partial value escapes mislabeled as "
                         f"replicated (reduce it with psum/all_gather or "
                         f"shard the out spec)"),
                symbol=rec["symbol"],
                chain=(site_hop, callee_hop),
            ))

    def _bind_params(self, module: str, rec: dict,
                     universe: frozenset[str], arity: int,
                     ) -> tuple[list[VMA], dict[str, str]] | None:
        """Positional VMAs from in_specs plus axis-string kwargs from
        functools.partial wrapping. None = no spec facts at all."""
        top = VMA.top(universe)

        def of(spec: dict | None) -> VMA:
            if spec is None:
                return top
            may: set[str] = set()
            must: set[str] = set()
            for ref in spec["may"]:
                v = self.index.resolve_axis(ref, module)
                if v is None:
                    return top  # unresolvable axis: assume anything
                may.add(v)
            for ref in spec["must"]:
                v = self.index.resolve_axis(ref, module)
                if v is not None:
                    must.add(v)
            return VMA(frozenset(may) & universe,
                       frozenset(must) & universe)

        args: list[VMA]
        if rec.get("in_axes") is not None:
            args = [of(spec) for spec in rec["in_axes"]]
        elif rec.get("in_single") is not None:
            one = of(rec["in_single"])
            # pytree-prefix spec: applies to every callee parameter
            args = [one] * max(arity, 1)
        else:
            return None
        # partial-bound leading positionals are closures: replicated
        args = [VMA.empty()] * rec.get("pconsumed", 0) + args

        axes_kw: dict[str, str] = {}
        for name, ref in (rec.get("pkw") or {}).items():
            v = self.index.resolve_axis(ref, module) if ref else None
            if v is not None:
                axes_kw[name] = v
        return args, axes_kw

    def _site_callee(self, module: str,
                     rec: dict) -> tuple[str, str] | None:
        if rec.get("callee_lam"):
            key = (module, rec["callee_lam"])
            return key if key in self.index.funcs else None
        if not rec.get("callee"):
            return None
        targets = self.index.func_targets(module, rec["callee"])
        return targets[0] if len(targets) == 1 else None

    # -- function interpretation ------------------------------------------
    def _interpret(self, key: tuple[str, str], args: list[VMA],
                   kwargs: dict[str, VMA], axes_kw: dict[str, str],
                   universe: frozenset[str],
                   chain: tuple[tuple[str, int, str], ...],
                   depth: int, outer: _State | None) -> VMA | None:
        f = self.index.funcs.get(key)
        if f is None or depth > _MAX_DEPTH:
            return None
        memo_key = (
            key,
            tuple((tuple(sorted(v.may)), tuple(sorted(v.must)))
                  for v in args),
            tuple(sorted((k, (tuple(sorted(v.may)), tuple(sorted(v.must))))
                         for k, v in kwargs.items())),
            tuple(sorted(axes_kw.items())),
            tuple(sorted(universe)),
        )
        # closures read the enclosing activation's bindings, which the
        # memo key cannot capture — a cached summary from one shard_map
        # site must never answer for another site's different closure
        # environment, so closure activations are re-interpreted per
        # call (bounded by depth) and only closed functions memoize
        if outer is None and memo_key in self._memo:
            return self._memo[memo_key]
        active_key = memo_key if outer is None else memo_key + (id(outer),)
        if active_key in self._active:
            return VMA.top(universe)  # recursion: unknown but bounded
        self._active.add(active_key)

        env: dict[str, VMA] = {}
        axes: dict[str, str] = {}
        params = list(f["pargs"])
        if f["meth"] and params:
            env[params[0]] = VMA.empty()
            params = params[1:]
        for i, p in enumerate(params):
            env[p] = args[i] if i < len(args) else VMA.empty()
            if p in kwargs:  # passed by keyword to a positional param
                env[p] = kwargs[p]
        for p in f["kwonly"]:
            if p in kwargs:
                env[p] = kwargs[p]
            if p in axes_kw:
                axes[p] = axes_kw[p]
                env.setdefault(p, VMA.empty())
        for p, v in axes_kw.items():
            if p in f["pargs"]:
                axes[p] = v
        # axis strings can also arrive positionally/by-keyword as values
        st = _State(env, axes, outer)

        rel = self.index.modules[key[0]]
        ctx = _SiteCtx(module=key[0], qual=key[1], rel=rel,
                       universe=universe,
                       chain=chain + ((rel, f["line"],
                                       f"{key[0]}.{key[1]}"),),
                       depth=depth)
        self._explore_customvjp(key, args, kwargs, universe, ctx)
        ret: VMA | None = None
        for step in f.get("flow", ()):
            if "r" in step:
                vma, _ = self._eval(step["r"], st, ctx)
                ret = vma if ret is None else VMA.join(ret, vma)
                continue
            targets = step.get("a", ())
            enc = step.get("e")
            if enc is None:
                continue
            # a step inside a conditional arm ("br") may not execute:
            # weak update — JOIN with the prior binding (may unions,
            # must intersects) instead of overwriting, so an if/else
            # can never strong-kill a varying axis from `may`
            cond = bool(step.get("br"))

            def bind(name: str, vma: VMA) -> None:
                if cond:
                    old = st.lookup(name)
                    if old is not None:
                        vma = VMA.join(old, vma)
                st.env[name] = vma

            if not targets:
                self._eval(enc, st, ctx)
                continue
            tt = step.get("tt")
            if tt and isinstance(enc, dict) and "t" in enc \
                    and len(enc["t"]) == len(tt):
                for names, sub in zip(tt, enc["t"]):
                    vma, axis = self._eval(sub, st, ctx)
                    for n in names:
                        bind(n, vma)
                    if axis is not None and len(names) == 1 and (
                            not cond or st.axis_of(names[0])
                            in (None, axis)):
                        st.axes[names[0]] = axis
                continue
            vma, axis = self._eval(enc, st, ctx)
            for n in targets:
                bind(n, vma)
                if not cond:
                    st.axes.pop(n, None)
            if axis is not None and len(targets) == 1:
                if not cond or st.axis_of(targets[0]) in (None, axis):
                    st.axes[targets[0]] = axis
                else:
                    st.axes.pop(targets[0], None)
        result = ret if ret is not None else VMA.empty()
        self._active.discard(active_key)
        if outer is None:
            self._memo[memo_key] = result
        return result

    def _explore_customvjp(self, key: tuple[str, str], args: list[VMA],
                           kwargs: dict[str, VMA],
                           universe: frozenset[str],
                           ctx: _SiteCtx) -> None:
        """When an interpreted function is a ``custom_vjp``/``custom_jvp``
        primal, its fwd/bwd/jvp companions run inside the *same*
        shard_map context — jax dispatches them, so no call edge exists.
        Explore each companion for side effects only (a psum over a
        replicated residual in the bwd body is the same axis-size
        mislabel as in the primal); residual/cotangent plumbing is
        opaque, so every companion parameter gets the combined
        varying-ness of the primal's arguments (sound upper bound for
        ``may``; ``must`` stays whatever definitely varied)."""
        comps = self._customvjp.get(key)
        if not comps:
            return
        vmas = list(args) + list(kwargs.values())
        bound = VMA.combine(*vmas) if vmas else VMA.top(universe)
        for comp_key, hop in comps:
            f = self.index.funcs.get(comp_key)
            if f is None:
                continue
            nparams = max(len(f["pargs"]), 1)
            self._interpret(comp_key, [bound] * nparams, {}, {},
                            universe, ctx.chain + (hop,),
                            ctx.depth + 1, outer=None)

    # -- expression evaluation --------------------------------------------
    def _eval(self, enc: Any, st: _State,
              ctx: _SiteCtx) -> tuple[VMA, str | None]:
        if not isinstance(enc, dict):
            return VMA.empty(), None
        if "k" in enc:
            v = enc["k"]
            return VMA.empty(), v if isinstance(v, str) else None
        if "n" in enc:
            name = enc["n"]
            vma = st.lookup(name)
            axis = st.axis_of(name)
            if axis is None:
                axis = self.index.resolve_axis({"ref": name}, ctx.module)
            return (vma if vma is not None else VMA.empty()), axis
        if "d" in enc:
            return VMA.empty(), self.index.resolve_axis(
                {"ref": enc["d"]}, ctx.module)
        if "t" in enc:
            return VMA.combine(*(self._eval(e, st, ctx)[0]
                                 for e in enc["t"])), None
        if "u" in enc:
            return VMA.combine(*(self._eval(e, st, ctx)[0]
                                 for e in enc["u"])), None
        if "alt" in enc:
            a, ax_a = self._eval(enc["alt"][0], st, ctx)
            b, ax_b = self._eval(enc["alt"][1], st, ctx)
            return VMA.join(a, b), ax_a if ax_a == ax_b else None
        if "c" in enc:
            return self._eval_call(enc, st, ctx)
        return VMA.empty(), None

    def _axis_arg(self, enc: dict, op: str, st: _State,
                  ctx: _SiteCtx) -> str | None:
        got, unresolved = self._axis_args(enc, op, st, ctx)
        return got[0] if len(got) == 1 and not unresolved else None

    def _axis_args(self, enc: dict, op: str, st: _State,
                   ctx: _SiteCtx) -> tuple[list[str], bool]:
        """(resolved axis names, any-unresolved) of a collective's axis
        argument — ``psum(x, ("data", "seq"))`` names several axes."""
        kwx = enc.get("kwx") or {}
        if "axis_name" in kwx:
            arg = kwx["axis_name"]
        else:
            pos = _COLLECTIVES[op]
            x = enc.get("x") or []
            arg = x[pos] if pos < len(x) else None
        if arg is None:
            return [], True
        elems = (arg["t"] if isinstance(arg, dict) and "t" in arg
                 else [arg])
        out: list[str] = []
        unresolved = False
        for el in elems:
            axis = self._eval(el, st, ctx)[1]
            if axis is None:
                unresolved = True
            elif axis not in out:
                out.append(axis)
        return out, unresolved

    def _eval_call(self, enc: dict, st: _State,
                   ctx: _SiteCtx) -> tuple[VMA, str | None]:
        dotted = enc.get("c")
        x = enc.get("x") or []
        kwx = enc.get("kwx") or {}

        op = None
        for cand in _COLLECTIVES:
            if resolves_to(dotted, cand):
                op = cand
                break
        if op is not None:
            return self._collective(enc, op, st, ctx), None

        got = self._control_flow(dotted, enc, st, ctx)
        if got is not None:
            return got, None

        target = self._resolve_callee(dotted, ctx)
        if target is not None:
            return self._project_call(target, enc, st, ctx)

        # unknown op: varying is infectious through every argument
        parts = [self._eval(e, st, ctx)[0] for e in x]
        parts += [self._eval(e, st, ctx)[0] for e in kwx.values()]
        return VMA.combine(*parts), None

    def _collective(self, enc: dict, op: str, st: _State,
                    ctx: _SiteCtx) -> VMA:
        x = enc.get("x") or []
        axes, unresolved = self._axis_args(enc, op, st, ctx)
        if op == "jax.lax.axis_index":
            if (len(axes) == 1 and not unresolved
                    and axes[0] in ctx.universe):
                return VMA(frozenset({axes[0]}), frozenset({axes[0]}))
            return VMA.top(ctx.universe)
        if op == "axis_size":
            return VMA.empty()
        value = self._eval(x[0], st, ctx)[0] if x else VMA.empty()
        targets = [a for a in axes if a in ctx.universe]
        if not targets and not unresolved:
            return value  # foreign axes only: hands off
        short = op.rsplit(".", 1)[-1]
        if op in _R11_OPS:
            for axis in targets:
                if axis in value.may:
                    continue
                self._emit(Finding(
                    rule=R11, path=ctx.rel, line=enc.get("ln", 0), col=0,
                    message=(f"{short} over {axis!r} of a value that is "
                             f"replicated over {axis!r} — the product is "
                             f"already complete on every shard, so this "
                             f"all-reduce multiplies it by the axis size "
                             f"(the GSPMD partial-sum/replication "
                             f"mislabel)"),
                    symbol=ctx.qual,
                    chain=ctx.chain + ((ctx.rel, enc.get("ln", 0),
                                        f"{ctx.module}.{ctx.qual}"),),
                ))
        if op in _REMOVES_AXIS:
            for axis in targets:
                value = value.remove(axis)
            if unresolved:
                # an axis we could not name may ALSO have been reduced:
                # nothing provably still-varies (protects R12), while
                # `may` keeps its upper bound
                value = VMA(value.may, frozenset())
        return value

    def _control_flow(self, dotted: str | None, enc: dict, st: _State,
                      ctx: _SiteCtx) -> VMA | None:
        x = enc.get("x") or []
        kwx = enc.get("kwx") or {}

        def pick(pos: int, name: str):
            """Positional-or-keyword operand of the lax call."""
            if pos < len(x):
                return x[pos]
            return kwx.get(name)

        def val(node) -> VMA:
            return (self._eval(node, st, ctx)[0] if node is not None
                    else VMA.empty())

        def fallback() -> VMA:
            # the operands we cannot structurally place still flow:
            # varying is infectious through every argument (a missing
            # operand must never read as "provably replicated")
            parts = [val(e) for e in x] + [val(e) for e in kwx.values()]
            return VMA.combine(*parts)

        def interp_fn(fn_enc, fn_args: list[VMA]) -> VMA | None:
            key = self._fn_ref(fn_enc, ctx)
            if key is None:
                return None
            nested = key[0] == ctx.module and key[1].startswith(
                ctx.qual + ".")
            return self._interpret(key, fn_args, {}, {}, ctx.universe,
                                   ctx.chain, ctx.depth + 1,
                                   outer=st if nested else None)

        if resolves_to(dotted, "jax.lax.scan"):
            fn = pick(0, "f")
            if fn is None:
                return fallback()
            carry = val(pick(1, "init"))
            xs = val(pick(2, "xs"))
            body = interp_fn(fn, [carry, xs])
            return VMA.combine(carry, body) if body is not None \
                else VMA.combine(carry, xs)
        if resolves_to(dotted, "jax.lax.while_loop"):
            fn = pick(1, "body_fun")
            init = val(pick(2, "init_val"))
            body = interp_fn(fn, [init]) if fn is not None else None
            if fn is None and "init_val" not in kwx and len(x) < 3:
                return fallback()
            return VMA.combine(init, body) if body is not None else init
        if resolves_to(dotted, "jax.lax.fori_loop"):
            fn = pick(2, "body_fun")
            init = val(pick(3, "init_val"))
            body = (interp_fn(fn, [VMA.empty(), init])
                    if fn is not None else None)
            if fn is None and "init_val" not in kwx and len(x) < 4:
                return fallback()
            return VMA.combine(init, body) if body is not None else init
        if resolves_to(dotted, "jax.lax.cond"):
            ops = [self._eval(e, st, ctx)[0] for e in x[3:]]
            t = interp_fn(pick(1, "true_fun"), ops)
            f = interp_fn(pick(2, "false_fun"), ops)
            if t is not None and f is not None:
                return VMA.join(t, f)
            return VMA.combine(*ops) if ops else fallback()
        return None

    def _fn_ref(self, enc: Any, ctx: _SiteCtx) -> tuple[str, str] | None:
        """A function-valued expression to a project function key,
        preferring a nested definition inside the current scope (scan
        bodies are closures)."""
        if not isinstance(enc, dict):
            return None
        if "n" in enc:
            name = enc["n"]
            nested = (ctx.module, f"{ctx.qual}.{name}")
            if nested in self.index.funcs:
                return nested
            targets = self.index.func_targets(ctx.module, name)
            return targets[0] if len(targets) == 1 else None
        if "d" in enc:
            targets = self.index.func_targets(ctx.module, enc["d"])
            return targets[0] if len(targets) == 1 else None
        return None

    def _resolve_callee(self, dotted: str | None,
                        ctx: _SiteCtx) -> tuple[str, str] | None:
        if not dotted:
            return None
        nested = (ctx.module, f"{ctx.qual}.{dotted}")
        if "." not in dotted and nested in self.index.funcs:
            return nested
        targets = self.index.func_targets(ctx.module, dotted)
        return targets[0] if len(targets) == 1 else None

    def _project_call(self, key: tuple[str, str], enc: dict, st: _State,
                      ctx: _SiteCtx) -> tuple[VMA, str | None]:
        x = enc.get("x") or []
        kwx = enc.get("kwx") or {}
        args: list[VMA] = []
        axes_kw: dict[str, str] = {}
        f = self.index.funcs.get(key)
        pargs = f["pargs"] if f else []
        for i, e in enumerate(x):
            vma, axis = self._eval(e, st, ctx)
            args.append(vma)
            if axis is not None and i < len(pargs):
                axes_kw[pargs[i]] = axis
        kwargs: dict[str, VMA] = {}
        for name, e in kwx.items():
            vma, axis = self._eval(e, st, ctx)
            kwargs[name] = vma
            if axis is not None:
                axes_kw[name] = axis
        nested = key[0] == ctx.module and key[1].startswith(ctx.qual + ".")
        ret = self._interpret(key, args, kwargs, axes_kw, ctx.universe,
                              ctx.chain, ctx.depth + 1,
                              outer=st if nested else None)
        if ret is None:
            return VMA.combine(*args, *kwargs.values()), None
        return ret, None


def results(index: ProjectIndex) -> ShardflowAnalysis:
    """The (cached) shardflow analysis for an index — R11 and R12 share
    one interpretation pass per lint run."""
    cached = getattr(index, "_shardflow", None)
    if cached is None:
        cached = ShardflowAnalysis(index).run()
        index._shardflow = cached
    return cached


# ---------------------------------------------------------------------------
# R13 donation-drift (static half): use-after-donate through the flow IR


def _exclusive_arms(a: tuple, b: tuple) -> bool:
    """True when two flow steps sit in arms of the same statement that
    can never BOTH execute in one activation: the two arms of an ``if``
    (numeric ids) or two sibling ``except`` handlers ("h<i>" ids). A
    loop body and its ``else`` ("b"/"e"), or a try body and its handler
    ("b"/"h<i>"), DO both execute — never exclusive."""
    for x, y in zip(a, b):
        if x == y:
            continue
        line_x, _, arm_x = x.partition(":")
        line_y, _, arm_y = y.partition(":")
        if line_x != line_y:
            return False
        return ((arm_x.isdigit() and arm_y.isdigit())
                or (arm_x.startswith("h") and arm_y.startswith("h")))
    return False


def _collect_names(enc: Any, out: set[str]) -> None:
    if not isinstance(enc, dict):
        return
    if "n" in enc:
        out.add(enc["n"])
        return
    for sub in enc.get("x", ()):
        _collect_names(sub, out)
    for sub in (enc.get("kwx") or {}).values():
        _collect_names(sub, out)
    for k in ("t", "u", "alt"):
        for sub in enc.get(k, ()):
            _collect_names(sub, out)


class _DonationPass:
    """Cross-module wrapper table + per-function ordered walk."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.findings: list[Finding] = []
        # (module, var) -> donation record, module-scope wrappers only
        self.wrappers: dict[tuple[str, str], dict] = {}
        for rel in sorted(index.summaries):
            s = index.summaries[rel]
            for d in s.get("donations", ()):
                if d.get("var") and d["symbol"] == "<module>":
                    self.wrappers[(s["module"], d["var"])] = dict(
                        d, rel=rel, module=s["module"])

    def run(self) -> "_DonationPass":
        for rel in sorted(self.index.summaries):
            s = self.index.summaries[rel]
            local = {d["var"]: dict(d, rel=rel, module=s["module"])
                     for d in s.get("donations", ())
                     if d.get("var") and d["symbol"] != "<module>"}
            for qual, f in s["functions"].items():
                self._function(rel, s, qual, f, local)
        self.findings.sort(key=lambda f: (f.path, f.line, f.col))
        return self

    def _wrapper_for(self, module: str, dotted: str,
                     _seen: frozenset = frozenset()) -> dict | None:
        if (module, dotted) in _seen:
            return None
        _seen = _seen | {(module, dotted)}
        if "." not in dotted:
            hit = self.wrappers.get((module, dotted))
            if hit is not None:
                return hit
            rel = self.index.modules.get(module)
            target = (self.index.summaries[rel]["exports"].get(dotted)
                      if rel else None)
            if target and "." in target:
                return self._wrapper_for(module, target, _seen)
            return None
        head, _, tail = dotted.rpartition(".")
        got = self.index.resolve_qual(head)
        if got and got[0] == "module":
            return self._wrapper_for(got[1], tail, _seen)
        return None

    def _donations_in(self, enc: Any, module: str, symbol: str,
                      local: dict) -> Iterable[tuple[dict, set[str], int]]:
        """(wrapper record, donated names, call line) per donating call
        inside one expression."""
        if not isinstance(enc, dict):
            return
        if "c" in enc:
            x = enc.get("x") or []
            kwx = enc.get("kwx") or {}
            rec = None
            if "dn" in enc or "dnn" in enc:  # inline-jitted donation
                rec = {"nums": enc.get("dn", []),
                       "names": enc.get("dnn", []),
                       "rel": self.index.modules.get(module, ""),
                       "module": module, "line": enc.get("ln", 0),
                       "symbol": symbol, "var": enc.get("c") or "<jit>",
                       "fname": enc.get("c")}
            elif enc.get("c"):
                rec = local.get(enc["c"]) if "." not in enc["c"] else None
                if rec is None:
                    rec = self._wrapper_for(module, enc["c"])
            if rec is not None:
                donated: set[str] = set()
                for pos in rec.get("nums", ()):
                    if pos < len(x):
                        _collect_names(x[pos], donated)
                for name in rec.get("names", ()):
                    if name in kwx:
                        _collect_names(kwx[name], donated)
                if donated:
                    yield rec, donated, enc.get("ln", 0)
            for sub in x:
                yield from self._donations_in(sub, module, symbol, local)
            for sub in kwx.values():
                yield from self._donations_in(sub, module, symbol, local)
            return
        for k in ("t", "u", "alt"):
            for sub in enc.get(k, ()):
                yield from self._donations_in(sub, module, symbol, local)

    def _function(self, rel: str, s: dict, qual: str, f: dict,
                  local: dict) -> None:
        module = s["module"]
        pending: dict[str, tuple[dict, int, tuple]] = {}
        for step in f.get("flow", ()):
            enc = step.get("e", step.get("r"))
            if enc is None:
                continue
            br = tuple(step.get("br") or ())
            used: set[str] = set()
            _collect_names(enc, used)
            for name in sorted(used & set(pending)):
                wrec, call_line, donate_br = pending[name]
                if _exclusive_arms(donate_br, br):
                    continue  # an if-arm read never sees the else-arm
                    # donation — the donation stays pending for
                    # compatible later reads
                del pending[name]
                hop_def = (wrec["rel"], wrec["line"],
                           f"{wrec['module']}.{wrec['var']}")
                hop_call = (rel, call_line, f"{module}.{qual}")
                hop_use = (rel, step["ln"], f"{module}.{qual}")
                self.findings.append(Finding(
                    rule=R13, path=rel, line=step["ln"], col=0,
                    message=(f"buffer {name!r} was donated to jitted "
                             f"'{wrec.get('fname') or wrec['var']}' "
                             f"(donate_argnums/argnames declared at "
                             f"{wrec['rel']}:{wrec['line']}) and is read "
                             f"after the call — XLA has reused its "
                             f"memory; rebind the result or drop the "
                             f"donation"),
                    symbol=qual,
                    chain=(hop_def, hop_call, hop_use),
                ))
            for wrec, donated, line in self._donations_in(
                    enc, module, qual, local):
                for name in donated:
                    pending[name] = (wrec, line, br)
            for t in step.get("a", ()):
                pending.pop(t, None)


def donation_findings(index: ProjectIndex) -> list[Finding]:
    cached = getattr(index, "_shardflow_donations", None)
    if cached is None:
        cached = _DonationPass(index).run().findings
        index._shardflow_donations = cached
    return cached
