"""CLI: ``python -m chiaswarm_tpu.analysis [paths...]``.

Exit codes: 0 clean · 1 new findings (or stale baseline under --strict)
· 2 unparseable input.
"""

from __future__ import annotations

import argparse
import json
import sys

from chiaswarm_tpu.analysis.core import all_rules
from chiaswarm_tpu.analysis.runner import DEFAULT_LINT_PATHS, repo_root, run


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m chiaswarm_tpu.analysis",
        description="swarmlint — enforce the repo's TPU compilation/RNG/"
                    "compat/sharding invariants (stdlib-only AST pass; "
                    "R9/R10 run on the swarmflow whole-program index)")
    p.add_argument("paths", nargs="*", default=list(DEFAULT_LINT_PATHS),
                   help="files/directories to lint (default: the package, "
                        "tests, tools and repo-root entry scripts, "
                        "relative to the repo root)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="baseline JSON (default: .swarmlint-baseline.json "
                        "at the repo root; relative paths resolve against "
                        "the repo root, like the lint paths)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather all current findings into the "
                        "baseline and exit 0 (adoption / post-fix shrink)")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries (CI mode — "
                        "the baseline may only shrink)")
    p.add_argument("--select", metavar="RULES", default=None,
                   help="comma-separated rule names or codes to run "
                        "(e.g. R2,compat-import)")
    p.add_argument("--changed-only", action="store_true",
                   help="pre-commit fast path: lint only files changed vs "
                        "the merge base with origin/main, plus every file "
                        "that (transitively) imports one of them")
    p.add_argument("--sarif", metavar="FILE", default=None,
                   help="also write new findings as SARIF 2.1.0 (GitHub "
                        "code scanning; '-' for stdout)")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and do not write the swarmflow project "
                        "cache (.swarmflow-cache.json)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a JSON array instead of text")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    args = p.parse_args(argv)
    if args.sarif == "-" and args.as_json:
        # both would interleave JSON documents on stdout — unparseable
        p.error("--sarif - and --json both write to stdout; give --sarif "
                "a file path (or drop --json)")

    if args.list_rules:
        for r in all_rules():
            print(f"{r.code:4s} {r.name:24s} {r.description}")
        return 0

    import dataclasses
    import os

    root = repo_root()
    # relative paths resolve against the REPO ROOT, matching how findings
    # and baseline entries are keyed — a cwd with its own tests/ subdir
    # must not silently swap the linted tree
    paths = [a if os.path.isabs(a) else os.path.join(root, a)
             for a in args.paths]
    baseline = (args.baseline if args.baseline is None
                or os.path.isabs(args.baseline)
                else os.path.join(root, args.baseline))
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    result = run(paths, baseline_path=baseline, strict=args.strict,
                 select=select, write_baseline=args.write_baseline,
                 root=root, changed_only=args.changed_only,
                 cache=not args.no_cache)
    if args.sarif and result.exit_code != 2:  # bad input: nothing to report
        from chiaswarm_tpu.analysis.core import get_rule
        from chiaswarm_tpu.analysis.sarif import to_sarif

        rules = ([get_rule(s) for s in select] if select else all_rules())
        doc = to_sarif(result.new, rules)
        if args.sarif == "-":
            print(json.dumps(doc, indent=2))
        else:
            sarif_path = (args.sarif if os.path.isabs(args.sarif)
                          else os.path.join(root, args.sarif))
            with open(sarif_path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2)
                fh.write("\n")
    if args.as_json:
        print(json.dumps(
            [dataclasses.asdict(f) for f in result.new], indent=2))
        if result.stale:
            print(json.dumps({"stale": result.stale}), file=sys.stderr)
        for e in result.errors:
            print(f"error: {e}", file=sys.stderr)
    else:
        print(result.report)
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
