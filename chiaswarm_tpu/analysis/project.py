"""swarmflow: the whole-program index under swarmlint's interprocedural
rules (R9 host-sync reachability, R10 sharding-spec drift).

Every rule through R8 is a single-file AST pass — a jitted function that
calls a helper in another module which does ``.item()`` is invisible to
R1, and nothing checks that ``PartitionSpec``/``shard_map`` axis names
agree across ``parallel/``, ``pipelines/`` and ``serving/``. This module
builds the missing layer, still pure stdlib:

- **module graph** — every linted file becomes a module (dotted name
  derived by climbing ``__init__.py`` packages), with absolute import
  edges (relative imports resolved against the module's package);
- **symbol resolution** — top-level functions, classes' methods, string
  constants and ``from x import y`` re-exports resolve by qualified name
  across modules, following re-export chains (the ``core/compat`` shims);
- **conservative call graph** — per-function call targets keyed by
  qualified name. Conservative means *precise*: an edge exists only when
  the callee resolves statically (bare names through import aliases,
  dotted module paths, ``self.``/``cls.`` methods, ``functools.partial``
  unwrapping). Instance-method calls on arbitrary objects are NOT edges —
  a lint must not invent paths it cannot defend;
- **incremental cache** — per-file summaries (everything the
  interprocedural rules consume) persist to ``.swarmflow-cache.json``
  keyed on content hashes, so a warm whole-repo lint re-summarizes only
  edited files and stays inside the seconds-fast budget, jax never
  imported.

The index deliberately stores *summaries*, not ASTs: a summary is a small
JSON-able dict, which makes the cache format trivial and keeps peak
memory flat across ~100 modules.
"""

from __future__ import annotations

import ast
import collections
import hashlib
import json
import os
import re
from typing import Any, Iterable

from chiaswarm_tpu.analysis.core import FunctionInfo, ModuleContext
from chiaswarm_tpu.analysis.rules import (
    CALLBACK_WRAPPERS, JIT_WRAPPERS, TRACED_WRAPPERS, own_nodes, resolves_to,
)

SCHEMA = 6  # v6: keyflow trace-input provenance facts (env reads,
#     env-tainted module constants, cache-key/fingerprint/build sites,
#     env-literal pools) + per-function r6 recompile facts + raw-attr
#     call-argument facts
DEFAULT_CACHE_NAME = ".swarmflow-cache.json"

#: cross-chip collective primitives and the axis-name argument position
#: they read when it is not passed as ``axis_name=``
_COLLECTIVES: dict[str, int] = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmax": 1,
    "jax.lax.pmin": 1, "jax.lax.ppermute": 1, "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1, "jax.lax.psum_scatter": 1,
    "jax.lax.axis_index": 0, "jax.lax.pshuffle": 1,
    "axis_size": 0,  # core/compat shim (jax.lax.axis_size on modern jax)
}

_SPEC_NAMES = ("jax.sharding.PartitionSpec", "PartitionSpec")
_MESH_NAMES = ("jax.sharding.Mesh", "Mesh")
_MESHSPEC_NAMES = ("MeshSpec",)
_BUILD_MESH_NAMES = ("build_mesh",)

# -- raceflow vocabulary ----------------------------------------------------
#
# Lock constructors and their kind: threading kinds participate in every
# rule; "alock" (asyncio primitives) counts as a guard for R15 but never
# as a lock the event loop may park on (R17) — awaiting an asyncio lock
# is its intended use.
_LOCK_CTORS: dict[str, str] = {
    "threading.Lock": "lock", "threading.RLock": "rlock",
    "threading.Condition": "cond", "threading.Semaphore": "sem",
    "threading.BoundedSemaphore": "sem",
    "asyncio.Lock": "alock", "asyncio.Condition": "alock",
    "asyncio.Semaphore": "alock", "asyncio.BoundedSemaphore": "alock",
}

#: module-level container constructors: a global bound to one is shared
#: mutable state the concurrency rules must track
_MUTABLE_CTORS = (
    "dict", "list", "set", "collections.deque", "deque",
    "collections.OrderedDict", "collections.defaultdict",
    "collections.Counter", "queue.Queue", "queue.SimpleQueue",
    "queue.LifoQueue", "queue.PriorityQueue",
)

#: container methods that mutate the receiver (shared-state writes)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "insert", "remove", "discard", "clear", "pop", "popleft",
    "popitem", "setdefault", "put", "put_nowait",
})

#: calls/methods that force a device array resident on host — they END a
#: device-handoff taint chain (ROADMAP: sync at admission, producer-side)
_CONC_SYNCERS = (
    "jax.block_until_ready", "jax.device_get",
    "numpy.asarray", "numpy.array", "numpy.copy",
)
_CONC_SYNC_METHODS = frozenset(
    {"block_until_ready", "copy", "item", "tolist"})

#: calls that block the calling OS thread (R17 vocabulary; exact match —
#: ``Condition.wait`` is deliberately absent, it releases its lock)
_CONC_BLOCKING = frozenset({
    "time.sleep", "socket.create_connection", "urllib.request.urlopen",
    "subprocess.run", "subprocess.check_call", "subprocess.check_output",
    "subprocess.call", "os.system", "select.select",
    "requests.get", "requests.post", "requests.request",
})

#: inline suppressions, same convention as ``swarmlens: allow-host-sync``:
#: the marker covers its own line, or the line below a comment-only line
_CONC_ALLOW_MARKERS = {
    "handoff": "swarmlens: allow-cross-thread-handoff",
    "unguarded": "swarmlens: allow-unguarded-mutation",
    "lockorder": "swarmlens: allow-lock-order",
    "blocking": "swarmlens: allow-blocking-under-lock",
}

# -- keyflow vocabulary -----------------------------------------------------
#
# Trace-input provenance (ISSUE 20): which env knobs / globals flow into
# a traced program, and which of them the executable-cache key folds.

#: the cache-key builder functions; any function whose name matches is a
#: keyed-set root — env names it (or its callees) mention ARE folded
_KEY_BUILDERS = ("static_cache_key",)
_FP_BUILDERS = ("cache_fingerprint", "artifact_cache_key")
#: executable-build registration methods: their factory argument is a
#: build closure a warm cache hit never re-runs
_BUILD_ATTRS = ("cached_executable", "get_or_create")
#: process-unstable builtins: fine in an in-process key, poison in a
#: persistent one
_UNSTABLE_CALLS = ("id", "hash", "repr")
#: raw request attributes whose distinct values explode executable
#: cardinality (the R6 vocabulary; keyflow's interprocedural face)
_RAW_SHAPE_ATTRS = ("height", "width", "batch", "num_frames")
#: SCREAMING_SNAKE string literals that look like env-var names; the
#: keyed set is the union of these over the key builders' call closure
_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9]*(?:_[A-Z0-9]+)+$")

#: inline suppressions for the keyflow rules, same line convention as
#: the conc markers: each states the invariant that makes the site safe
_KEY_ALLOW_MARKERS = {
    "unkeyed": "swarmlens: allow-unkeyed-trace-input",
    "frozen": "swarmlens: allow-frozen-env-reread",
    "unstable": "swarmlens: allow-unstable-key",
    "collision": "swarmlens: allow-tag-collision",
}


def _donate_decl(call: ast.Call) -> tuple[list[int], list[str]]:
    """donate_argnums / donate_argnames literals of a jit-wrapper call."""
    from chiaswarm_tpu.analysis.rules.jit_hygiene import (
        _int_elems, _str_elems,
    )

    nums: list[int] = []
    names: list[str] = []
    for k in call.keywords:
        if k.arg == "donate_argnums":
            nums = _int_elems(k.value)
        elif k.arg == "donate_argnames":
            names = _str_elems(k.value)
    return nums, names


# ---------------------------------------------------------------------------
# module naming


def module_name_for_file(abspath: str) -> tuple[str, bool]:
    """(dotted module name, is_package) for a file on disk, climbing the
    ``__init__.py`` chain so the name matches what ``import`` would use
    regardless of where the lint root sits."""
    dirpath, fname = os.path.split(os.path.abspath(abspath))
    stem = fname[:-3] if fname.endswith(".py") else fname
    is_package = stem == "__init__"
    parts = [] if is_package else [stem]
    while os.path.isfile(os.path.join(dirpath, "__init__.py")):
        dirpath, pkg = os.path.split(dirpath)
        parts.insert(0, pkg)
    if not parts:  # a bare __init__.py with no package parent
        parts = [os.path.basename(dirpath) or stem]
    return ".".join(parts), is_package


def module_name_from_relpath(relpath: str) -> tuple[str, bool]:
    """In-memory variant (fixture sources): every path part is assumed a
    package, so ``pkg/mod.py`` -> ``pkg.mod``."""
    parts = [p for p in relpath.replace(os.sep, "/").split("/")
             if p not in (".", "..")]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    is_package = bool(parts) and parts[-1] == "__init__"
    if is_package:
        parts = parts[:-1]
    return ".".join(parts) or relpath, is_package


# ---------------------------------------------------------------------------
# per-module summary extraction


def _axisref(node: ast.AST, resolve) -> list[dict]:
    """Axis-name references inside one spec/collective argument: string
    literals become ``{"lit": s}``, resolvable names ``{"ref": dotted}``.
    Conditional expressions contribute both VALUE branches (never the
    test — its variables are not axis names); ``None`` (the replicated
    dimension) contributes nothing."""
    out: list[dict] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append({"lit": n.value})
        elif isinstance(n, (ast.Name, ast.Attribute)):
            dotted = resolve(n)
            if dotted and not dotted.startswith(("self.", "cls.")):
                out.append({"ref": dotted})
        elif isinstance(n, ast.IfExp):
            visit(n.body)
            visit(n.orelse)
        elif isinstance(n, (ast.Tuple, ast.List)):
            for e in n.elts:
                visit(e)
        elif isinstance(n, ast.Starred):
            visit(n.value)

    visit(node)
    seen: set[str] = set()
    uniq = []
    for a in out:
        key = json.dumps(a, sort_keys=True)
        if key not in seen:
            seen.add(key)
            uniq.append(a)
    return uniq


class _Summarizer:
    """One module -> one JSON-able summary dict."""

    def __init__(self, relpath: str, source: str, tree: ast.Module,
                 module: str, is_package: bool):
        self.ctx = ModuleContext(relpath, source, tree)
        self.module = module
        self.is_package = is_package
        if is_package:
            self.package = module
        else:
            self.package = module.rsplit(".", 1)[0] if "." in module else ""
        self.aliases: dict[str, str] = {}      # whole-tree, absolute
        self.exports: dict[str, str] = {}      # top-level imports only
        self.deps: list[dict] = []
        self._collect_imports(tree)

    # -- imports ----------------------------------------------------------
    def _abs_from(self, node: ast.ImportFrom) -> str:
        mod = node.module or ""
        if not node.level:
            return mod
        parts = self.package.split(".") if self.package else []
        up = node.level - 1
        if up:
            parts = parts[:-up] if up < len(parts) else []
        if mod:
            parts = parts + mod.split(".")
        return ".".join(parts)

    def _collect_imports(self, tree: ast.Module) -> None:
        top = set(tree.body)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".", 1)[0]
                    target = a.name if a.asname else a.name.split(".", 1)[0]
                    self.aliases[local] = target
                    if node in top:
                        self.exports[local] = target
                    self.deps.append({"m": a.name, "n": None})
            elif isinstance(node, ast.ImportFrom):
                abs_mod = self._abs_from(node)
                for a in node.names:
                    if a.name == "*":
                        self.deps.append({"m": abs_mod, "n": None})
                        continue
                    target = f"{abs_mod}.{a.name}" if abs_mod else a.name
                    self.aliases[a.asname or a.name] = target
                    if node in top:
                        self.exports[a.asname or a.name] = target
                    self.deps.append({"m": abs_mod, "n": a.name})

    # -- expression resolution (absolute aliases) -------------------------
    def resolve(self, node: ast.AST) -> str | None:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def callable_target(self, node: ast.AST) -> tuple[str | None, int]:
        """(dotted target, positional args consumed by partial wrapping)."""
        consumed = 0
        while isinstance(node, ast.Call):
            fn = self.resolve(node.func)
            if resolves_to(fn, "functools.partial", "partial") and node.args:
                consumed += len(node.args) - 1
                node = node.args[0]
                continue
            return fn, consumed
        return self.resolve(node), consumed

    def callee_with_kwargs(self, node: ast.AST
                           ) -> tuple[str | None, int, dict]:
        """Like :meth:`callable_target` but also surfaces the KEYWORD
        axisrefs bound by ``functools.partial`` wrapping —
        ``partial(ring_attention, axis_name=SEQ_AXIS)`` yields
        ``("…ring_attention", 0, {"axis_name": {"ref": …}})`` so the
        shardflow interpreter can bind the callee's axis parameter."""
        consumed = 0
        pkw: dict[str, Any] = {}
        while isinstance(node, ast.Call):
            fn = self.resolve(node.func)
            if resolves_to(fn, "functools.partial", "partial") and node.args:
                consumed += len(node.args) - 1
                for k in node.keywords:
                    if k.arg:
                        refs = _axisref(k.value, self.resolve)
                        pkw.setdefault(k.arg,
                                       refs[0] if len(refs) == 1 else None)
                node = node.args[0]
                continue
            return fn, consumed, pkw
        return self.resolve(node), consumed, pkw

    # -- expression encoding (shardflow flow IR) --------------------------
    #
    # Each function body is summarized as an ordered list of steps over a
    # tiny JSON expression IR, enough for the abstract sharding
    # interpreter (analysis/shardflow.py) to replay dataflow without the
    # AST. Encodings:
    #
    #   {"n": name}              local variable reference
    #   {"d": dotted}            import-resolved non-local reference
    #   {"k": str|None}          constant (string constants kept: axis
    #                            names assigned to locals must resolve)
    #   {"t": [enc, …]}          tuple/list literal (unpack-aware)
    #   {"u": [enc, …]}          union of sub-values (any operator)
    #   {"alt": [enc, enc]}      either/or (IfExp): may=∪, must=∩
    #   {"c": dotted, "x": […], "kwx": {…}, "ln": n[, "dn": […]]}
    #                            call; "dn" = positions donated by an
    #                            inline jit wrapper applied on the spot

    _ENC_DEPTH = 14

    def _enc_names(self, node: ast.AST) -> dict:
        out = []
        seen: set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id not in seen:
                seen.add(n.id)
                out.append({"d": self.aliases[n.id]}
                           if n.id in self.aliases else {"n": n.id})
        return {"u": out}

    def _enc(self, node: ast.AST, depth: int = 0) -> dict:
        if depth > self._ENC_DEPTH:
            return self._enc_names(node)
        e = lambda n: self._enc(n, depth + 1)  # noqa: E731
        if isinstance(node, ast.Constant):
            return {"k": node.value if isinstance(node.value, str) else None}
        if isinstance(node, ast.Name):
            if node.id in self.aliases:
                return {"d": self.aliases[node.id]}
            return {"n": node.id}
        if isinstance(node, ast.Attribute):
            dotted = self.resolve(node)
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id in self.aliases \
                    and dotted:
                return {"d": dotted}
            if isinstance(base, ast.Name):
                # attribute of a local value (x.T, x.shape): the value's
                # varying axes flow through, the attribute name doesn't
                return {"u": [{"n": base.id}]}
            return {"u": [e(base)]}
        if isinstance(node, (ast.Tuple, ast.List)):
            return {"t": [e(x) for x in node.elts]}
        if isinstance(node, ast.Starred):
            return e(node.value)
        if isinstance(node, ast.IfExp):
            return {"alt": [e(node.body), e(node.orelse)]}
        if isinstance(node, ast.Call):
            return self._enc_call(node, depth)
        if isinstance(node, ast.BinOp):
            return {"u": [e(node.left), e(node.right)]}
        if isinstance(node, ast.UnaryOp):
            return {"u": [e(node.operand)]}
        if isinstance(node, ast.BoolOp):
            return {"u": [e(v) for v in node.values]}
        if isinstance(node, ast.Compare):
            return {"u": [e(node.left)] + [e(c) for c in node.comparators]}
        if isinstance(node, ast.Subscript):
            return {"u": [e(node.value), e(node.slice)]}
        if isinstance(node, ast.Dict):
            return {"u": [e(v) for v in node.values if v is not None]}
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return {"k": None}  # a function value carries no varying axes
        return self._enc_names(node)

    def _enc_call(self, node: ast.Call, depth: int) -> dict:
        e = lambda n: self._enc(n, depth + 1)  # noqa: E731
        func = node.func
        # inline donating wrapper: toplevel_jit(f, donate_argnums=…)(x)
        if isinstance(func, ast.Call):
            inner_t = self.resolve(func.func)
            if resolves_to(inner_t, *JIT_WRAPPERS):
                nums, names = _donate_decl(func)
                target = (self.resolve(func.args[0])
                          if func.args else None)
                rec: dict[str, Any] = {
                    "c": target, "x": [e(a) for a in node.args],
                    "kwx": {k.arg: e(k.value) for k in node.keywords
                            if k.arg},
                    "ln": node.lineno,
                }
                if nums or names:
                    rec["dn"] = nums
                    rec["dnn"] = names
                return rec
        target, consumed = self.callable_target(node)
        if target is None or (isinstance(func, ast.Attribute)
                              and not self._import_rooted(func)):
            # method call on a value (x.astype(…)) or unresolvable
            # callee: the result unions the receiver and every argument
            parts = []
            if isinstance(func, ast.Attribute):
                parts.append(e(func))
            elif target is None:
                parts.append(e(func))
            parts += [e(a) for a in node.args]
            parts += [e(k.value) for k in node.keywords]
            return {"u": parts}
        return {
            "c": target,
            "x": [e(a) for a in node.args],
            "kwx": {k.arg: e(k.value) for k in node.keywords if k.arg},
            "ln": node.lineno,
        }

    def _import_rooted(self, node: ast.Attribute) -> bool:
        base = node
        while isinstance(base, ast.Attribute):
            base = base.value
        return isinstance(base, ast.Name) and base.id in self.aliases

    # -- flow steps --------------------------------------------------------
    def _flow(self, info: FunctionInfo) -> list[dict]:
        node = info.node
        if isinstance(node, ast.Lambda):
            return [{"ln": node.lineno, "r": self._enc(node.body)}]
        steps: list[dict] = []

        def stmt_targets(t: ast.AST) -> list[str]:
            if isinstance(t, ast.Name):
                return [t.id]
            if isinstance(t, (ast.Tuple, ast.List)):
                out = []
                for el in t.elts:
                    out.extend(stmt_targets(el))
                return out
            if isinstance(t, ast.Starred):
                return stmt_targets(t.value)
            return []

        def walk(body: list[ast.stmt],
                 branch: tuple[str, ...] = ()) -> None:
            # ``branch`` is the conditional-arm path of every step in
            # this body: one "<line>:<arm>" element per enclosing
            # If/loop/Try arm. Steps inside an arm carry it as "br" —
            # the interpreter weak-updates (join, must cleared against
            # prior bindings) instead of overwriting, and the donation
            # pass refuses to chain across mutually exclusive arms.
            def emit(step: dict) -> None:
                if branch:
                    step["br"] = list(branch)
                steps.append(step)

            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # separate scopes, separate flow entries
                if isinstance(stmt, ast.Assign):
                    tg: list[str] = []
                    struct: ast.AST | None = None
                    for t in stmt.targets:
                        tg.extend(stmt_targets(t))
                        struct = struct or t
                    step = {"ln": stmt.lineno, "a": tg,
                            "e": self._enc(stmt.value)}
                    # remember the (single) target structure so tuple
                    # unpacks can map elementwise
                    if len(stmt.targets) == 1 and isinstance(
                            stmt.targets[0], (ast.Tuple, ast.List)):
                        step["tt"] = [stmt_targets(el) for el in
                                      stmt.targets[0].elts]
                    emit(step)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    emit({"ln": stmt.lineno,
                          "a": stmt_targets(stmt.target),
                          "e": self._enc(stmt.value)})
                elif isinstance(stmt, ast.AugAssign):
                    tg = stmt_targets(stmt.target)
                    emit({"ln": stmt.lineno, "a": tg,
                          "e": {"u": [self._enc(stmt.target),
                                      self._enc(stmt.value)]}})
                elif isinstance(stmt, ast.Return):
                    emit({"ln": stmt.lineno,
                          "r": (self._enc(stmt.value)
                                if stmt.value is not None
                                else {"k": None})})
                elif isinstance(stmt, ast.Expr):
                    emit({"ln": stmt.lineno,
                          "e": self._enc(stmt.value)})
                elif isinstance(stmt, ast.For):
                    # loop body and else BOTH execute on a completed
                    # loop: non-exclusive "b"/"e" arms (still
                    # conditional — zero iterations skip the body)
                    emit({"ln": stmt.lineno,
                          "a": stmt_targets(stmt.target),
                          "e": {"u": [self._enc(stmt.iter)]}})
                    walk(stmt.body, branch + (f"{stmt.lineno}:b",))
                    walk(stmt.orelse, branch + (f"{stmt.lineno}:e",))
                    continue
                elif isinstance(stmt, ast.If):
                    # numeric arms: truly mutually exclusive
                    emit({"ln": stmt.lineno,
                          "e": self._enc(stmt.test)})
                    walk(stmt.body, branch + (f"{stmt.lineno}:0",))
                    walk(stmt.orelse, branch + (f"{stmt.lineno}:1",))
                    continue
                elif isinstance(stmt, ast.While):
                    emit({"ln": stmt.lineno,
                          "e": self._enc(stmt.test)})
                    walk(stmt.body, branch + (f"{stmt.lineno}:b",))
                    walk(stmt.orelse, branch + (f"{stmt.lineno}:e",))
                    continue
                elif isinstance(stmt, ast.With):
                    for item in stmt.items:
                        tg = (stmt_targets(item.optional_vars)
                              if item.optional_vars is not None else [])
                        emit({"ln": stmt.lineno, "a": tg,
                              "e": self._enc(item.context_expr)})
                    walk(stmt.body, branch)
                    continue
                elif isinstance(stmt, ast.Try):
                    # the try body may execute partially and its
                    # handler runs AFTER it — body "b" and handlers
                    # "h<i>" are non-exclusive arms (a donation in the
                    # body is live in the handler); SIBLING handlers
                    # are exclusive with each other; orelse shares the
                    # body's arm; finally always runs
                    walk(stmt.body, branch + (f"{stmt.lineno}:b",))
                    for i, h in enumerate(stmt.handlers):
                        walk(h.body, branch + (f"{stmt.lineno}:h{i}",))
                    walk(stmt.orelse,
                         branch + (f"{stmt.lineno}:b",))
                    walk(stmt.finalbody, branch)
                    continue
                elif isinstance(stmt, (ast.Assert, ast.Raise, ast.Delete)):
                    continue
        walk(node.body)
        return steps

    # -- summary ----------------------------------------------------------
    def summarize(self) -> dict:
        ctx = self.ctx
        from chiaswarm_tpu.analysis.rules.recompile import self_jit_attrs

        self._r6_jattrs = self_jit_attrs(ctx)
        functions: dict[str, dict] = {}
        by_name: dict[str, list[str]] = {}
        for info in ctx.functions:
            functions[info.qualname] = self._func_summary(info)
            name = functions[info.qualname]["name"]
            by_name.setdefault(name, []).append(info.qualname)

        summary = {
            "module": self.module,
            "relpath": ctx.relpath,
            "package": self.is_package,
            "exports": self.exports,
            "deps": self.deps,
            "constants": self._constants(ctx.tree),
            "tables": self._dispatch_tables(ctx.tree),
            "functions": functions,
            "names": by_name,
        }
        summary.update(self._jit_entries(ctx, functions))
        self._collect_spec_vars(ctx.tree)
        summary.update(self._sharding_facts(ctx))
        summary["meshes"] = self._mesh_instances(ctx)
        summary["donations"] = self._donations(ctx)
        summary["conc"] = self._conc_facts(ctx)
        summary["customvjp"] = self._customvjp_facts(ctx)
        summary["keyflow"] = self._keyflow_facts(ctx)
        return summary

    def _func_summary(self, info: FunctionInfo) -> dict:
        node = info.node
        if isinstance(node, ast.Lambda):
            a = node.args
            name = info.qualname.rsplit(".", 1)[-1]
        else:
            a = node.args
            name = node.name
        npos = len(a.posonlyargs) + len(a.args)
        first = ([arg.arg for arg in a.posonlyargs + a.args] or [""])[0]
        calls, methods = self._calls(info)
        from chiaswarm_tpu.analysis.rules.host_sync import sync_sites
        from chiaswarm_tpu.analysis.rules.recompile import recompile_facts

        sync = [{"line": n.lineno, "col": n.col_offset, "what": what}
                for n, what in sync_sites(self.ctx, info)]
        r6 = recompile_facts(self.ctx, info, self._r6_jattrs)
        out = {
            "name": name,
            "line": getattr(node, "lineno", 0),
            "npos": npos,
            "ndef": len(a.defaults),
            "vararg": a.vararg is not None,
            "pargs": [arg.arg for arg in a.posonlyargs + a.args],
            "kwonly": [arg.arg for arg in a.kwonlyargs],
            "kwreq": [arg.arg for arg, d in zip(a.kwonlyargs, a.kw_defaults)
                      if d is None],
            "meth": first in ("self", "cls"),
            "isasync": isinstance(node, ast.AsyncFunctionDef),
            "calls": calls,
            "methods": methods,
            "sync": sync,
            "flow": self._flow(info),
        }
        if r6:
            out["r6"] = r6
        return out

    def _calls(self, info: FunctionInfo) -> tuple[list[dict], list[str]]:
        calls: list[dict] = []
        methods: list[str] = []
        # function-local dispatch dicts: ``handlers = {...}`` followed by
        # ``handlers[k](...)`` expands inline to a call per member
        local_tables: dict[str, list[str]] = {}
        for node in own_nodes(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                entries = self._table_entries(node.value)
                if entries:
                    local_tables[node.targets[0].id] = entries
        for node in own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("self", "cls")):
                methods.append(func.attr)
                continue
            if isinstance(func, ast.Subscript) and isinstance(
                    func.value, (ast.Name, ast.Attribute)):
                # a dispatch-table call: TABLE[key](...) — expand local
                # tables inline; module-level (possibly cross-module)
                # tables defer to the index via an "@table:" target
                dotted = self.resolve(func.value)
                if dotted and not dotted.startswith(("self.", "cls.")):
                    if dotted in local_tables:
                        for t in local_tables[dotted]:
                            calls.append({"t": t, "line": node.lineno,
                                          "np": len(node.args), "kw": {},
                                          "poslits": {}})
                    else:
                        calls.append({"t": "@table:" + dotted,
                                      "line": node.lineno,
                                      "np": len(node.args), "kw": {},
                                      "poslits": {}})
                continue
            target, consumed = self.callable_target(node)
            if target is None:
                continue
            kw: dict[str, Any] = {}
            for k in node.keywords:
                if k.arg is None:
                    continue
                refs = _axisref(k.value, self.resolve) \
                    if not isinstance(k.value, (ast.Lambda, ast.Call)) else []
                kw[k.arg] = refs[0] if len(refs) == 1 else None
            poslits = {str(i): arg.value for i, arg in enumerate(node.args)
                       if isinstance(arg, ast.Constant)
                       and isinstance(arg.value, str)}
            # NB: callable_target already unwraps functools.partial, so a
            # `partial(f, ..., axis_name=X)` expression records as a call
            # to `f` with X among its kwargs — exactly what the R10
            # binding check wants, and a conservative call edge (the
            # partial object exists to be invoked)
            rec = {
                "t": target, "line": node.lineno, "np": len(node.args),
                "kw": kw, "poslits": poslits,
            }
            # raw request attributes passed as arguments (req.height):
            # the flow IR drops attribute names, so R6's interprocedural
            # face needs them recorded at the call site
            rattr = {str(i): a.attr for i, a in enumerate(node.args)
                     if isinstance(a, ast.Attribute)
                     and a.attr in _RAW_SHAPE_ATTRS
                     and isinstance(a.value, ast.Name)}
            rattrk = {k.arg: k.value.attr for k in node.keywords
                      if k.arg and isinstance(k.value, ast.Attribute)
                      and k.value.attr in _RAW_SHAPE_ATTRS
                      and isinstance(k.value.value, ast.Name)}
            if rattr:
                rec["rattr"] = rattr
            if rattrk:
                rec["rattrk"] = rattrk
            calls.append(rec)
        return calls, sorted(set(methods))

    def _table_entries(self, value: ast.AST) -> list[str] | None:
        """Function references in a dict-literal dispatch table, or None
        when ``value`` is not one. A table is a dict whose VALUES are
        (at least one) resolvable callables — keys are routing strings
        and don't matter for reachability."""
        if not isinstance(value, ast.Dict):
            return None
        targets: list[str] = []
        for v in value.values:
            if isinstance(v, (ast.Name, ast.Attribute)):
                dotted = self.resolve(v)
                if dotted and not dotted.startswith(("self.", "cls.")):
                    targets.append(dotted)
        return sorted(set(targets)) or None

    def _dispatch_tables(self, tree: ast.Module) -> dict:
        """Module-level ``TABLE = {"key": fn, ...}`` dispatch dicts:
        name -> resolved function refs. ``TABLE[key](...)`` calls were
        unresolvable edges before (the ROADMAP lint-extension candidate)
        — R9's call graph now expands them to every member."""
        tables: dict[str, list[str]] = {}
        for node in tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            if target is None:
                continue
            entries = self._table_entries(value)
            if entries:
                tables[target] = entries
        return tables

    def _constants(self, tree: ast.Module) -> dict:
        consts: dict[str, Any] = {}
        for node in tree.body:
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            if target is None:
                continue
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                consts[target] = value.value
            elif isinstance(value, (ast.Tuple, ast.List)):
                refs = []
                ok = True
                for elt in value.elts:
                    r = _axisref(elt, self.resolve)
                    if len(r) == 1:
                        refs.append(r[0])
                    else:
                        ok = False
                        break
                if ok and refs:
                    consts[target] = refs
        return consts

    # -- jit entry points --------------------------------------------------
    def _jit_entries(self, ctx: ModuleContext, functions: dict) -> dict:
        wrappers = JIT_WRAPPERS + TRACED_WRAPPERS
        roots: list[str] = []
        refs: list[dict] = []
        by_name: dict[str, list[str]] = {}
        by_node: dict[ast.AST, str] = {}
        for info in ctx.functions:
            by_node[info.node] = info.qualname
            if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(info.node.name, []).append(info.qualname)
                for dec in info.node.decorator_list:
                    t, _ = self.callable_target(dec)
                    if resolves_to(t, *wrappers):
                        roots.append(info.qualname)
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            t, _ = self.callable_target(call)
            if not resolves_to(t, *wrappers):
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Lambda) and arg in by_node:
                    roots.append(by_node[arg])
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    dotted = self.resolve(arg)
                    if dotted is None:
                        continue
                    if dotted.startswith(("self.", "cls.")):
                        roots.extend(by_name.get(dotted.split(".")[1], []))
                    elif "." in dotted:
                        refs.append({"t": dotted, "line": call.lineno,
                                     "symbol": ctx.symbol_for(call)})
                    else:
                        local = by_name.get(dotted, [])
                        roots.extend(local)
        return {"jit_roots": sorted(set(roots)), "jit_refs": refs}

    # -- concurrency facts (raceflow) -------------------------------------
    #
    # One extra summary key, ``conc``, carries everything the raceflow
    # interpreter (analysis/raceflow.py) needs — the flow IR above stays
    # untouched. Lock tokens are strings: ``s:Cls.attr`` (an instance
    # attribute, class resolved at extraction), ``g:NAME`` (module
    # global), ``p:name`` (a lock received as a parameter — only
    # meaningful once a call site substitutes it), ``d:dotted`` (an
    # imported lock, absolute path). Shared-state tokens are ``a:Cls.X``
    # / ``g:NAME``; raceflow prefixes the module to both namespaces.

    def _conc_facts(self, ctx: ModuleContext) -> dict:
        tree = ctx.tree
        classnames = {n.name for n in ast.walk(tree)
                      if isinstance(n, ast.ClassDef)}
        lockdefs = self._lockdefs(ctx, classnames)
        mod_locks = {d["attr"] for d in lockdefs if not d["cls"]}
        cls_locks = {(d["cls"], d["attr"]) for d in lockdefs if d["cls"]}
        jattrs, jitw, jitfuncs = self._jit_values(ctx, classnames)
        gmut = self._mutable_globals(tree)
        spawns = self._spawn_sites(ctx)
        funcs: dict[str, dict] = {}
        for info in ctx.functions:
            facts = self._conc_func(ctx, info, classnames, cls_locks,
                                    mod_locks, gmut, jattrs, jitw, jitfuncs)
            if facts:
                funcs[info.qualname] = facts
        out: dict[str, Any] = {}
        if spawns:
            out["spawns"] = spawns
        if lockdefs:
            out["lockdefs"] = lockdefs
        if funcs:
            out["funcs"] = funcs
        allow = self._allow_lines(ctx)
        if allow:
            out["allow"] = allow
        return out

    def _owning_class(self, ctx: ModuleContext, node: ast.AST,
                      classnames: set[str]) -> str | None:
        head = ctx.symbol_for(node).split(".")[0]
        return head if head in classnames else None

    def _lockdefs(self, ctx: ModuleContext,
                  classnames: set[str]) -> list[dict]:
        out: list[dict] = []
        top = set(ctx.tree.body)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            t, _ = self.callable_target(node.value)
            kind = next((k for name, k in _LOCK_CTORS.items()
                         if resolves_to(t, name)), None)
            if kind is None:
                continue
            alias = None
            args = node.value.args
            if kind in ("cond", "alock") and args:
                # Condition(self._lock) shares its sibling's identity
                a0 = args[0]
                if (isinstance(a0, ast.Attribute)
                        and isinstance(a0.value, ast.Name)
                        and a0.value.id in ("self", "cls")):
                    alias = a0.attr
                elif isinstance(a0, ast.Name):
                    alias = a0.id
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls")):
                c = self._owning_class(ctx, node, classnames)
                if c:
                    out.append({"cls": c, "attr": tgt.attr, "kind": kind,
                                "ln": node.lineno, "alias": alias})
            elif isinstance(tgt, ast.Name) and node in top:
                out.append({"cls": "", "attr": tgt.id, "kind": kind,
                            "ln": node.lineno, "alias": alias})
        return out

    def _jit_values(self, ctx: ModuleContext, classnames: set[str],
                    ) -> tuple[dict[str, set[str]], set[str], set[str]]:
        """Names whose CALL dispatches compiled work: ``self.X = jit(f)``
        attributes per class, module-level ``F = jit(f)`` globals, and
        ``@jit``-decorated function names — the R14 taint producers."""
        jattrs: dict[str, set[str]] = {}
        jitw: set[str] = set()
        top = set(ctx.tree.body)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            t, _ = self.callable_target(node.value)
            if not resolves_to(t, *JIT_WRAPPERS):
                continue
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls")):
                c = self._owning_class(ctx, node, classnames)
                if c:
                    jattrs.setdefault(c, set()).add(tgt.attr)
            elif isinstance(tgt, ast.Name) and node in top:
                jitw.add(tgt.id)
        jitfuncs: set[str] = set()
        for info in ctx.functions:
            if isinstance(info.node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in info.node.decorator_list:
                    t, _ = self.callable_target(dec)
                    if resolves_to(t, *JIT_WRAPPERS):
                        jitfuncs.add(info.node.name)
        return jattrs, jitw, jitfuncs

    def _mutable_globals(self, tree: ast.Module) -> set[str]:
        muts: set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                tgt, val = node.target.id, node.value
            else:
                continue
            if isinstance(val, (ast.Dict, ast.List, ast.Set)):
                muts.add(tgt)
            elif isinstance(val, ast.Call):
                t, _ = self.callable_target(val)
                if t in _MUTABLE_CTORS:
                    muts.add(tgt)
        for node in ast.walk(tree):
            # ``global NAME`` + assignment = shared scalar state
            if isinstance(node, ast.Global):
                muts.update(node.names)
        return muts

    def _spawn_sites(self, ctx: ModuleContext) -> list[dict]:
        out: list[dict] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t = self.resolve(node.func)
            # method-name detection survives unresolvable receivers:
            # ``asyncio.get_running_loop().run_in_executor(...)``
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            kind = tgt = None
            if resolves_to(t, "threading.Thread"):
                kind, tgt = "thread", kw.get("target")
            elif resolves_to(t, "threading.Timer"):
                kind = "thread"
                tgt = (node.args[1] if len(node.args) > 1
                       else kw.get("function"))
            elif attr == "run_in_executor":
                kind = "exec"
                tgt = node.args[1] if len(node.args) > 1 else None
            elif (resolves_to(t, "asyncio.create_task",
                              "asyncio.ensure_future", "asyncio.run")
                  or attr in ("create_task", "ensure_future")):
                kind = "task"
                tgt = node.args[0] if node.args else None
            elif resolves_to(t, *CALLBACK_WRAPPERS):
                kind = "cb"
                tgt = node.args[0] if node.args else kw.get("callback")
            elif resolves_to(t, "weakref.finalize"):
                kind = "fin"
                tgt = node.args[1] if len(node.args) > 1 else None
            if kind is None or tgt is None:
                continue
            while isinstance(tgt, ast.Call):
                # functools.partial(fn, ...) spawns fn; a plain call
                # (create_task(self._poll())) spawns its callee
                inner = self.resolve(tgt.func)
                if resolves_to(inner, "functools.partial", "partial") \
                        and tgt.args:
                    tgt = tgt.args[0]
                else:
                    tgt = tgt.func
            ref = self.resolve(tgt)
            if ref is None:
                continue
            out.append({"k": kind, "t": ref, "ln": node.lineno,
                        "symbol": ctx.symbol_for(node)})
        return out

    def _allow_lines(self, ctx: ModuleContext,
                     markers: dict[str, str] = _CONC_ALLOW_MARKERS,
                     ) -> dict[str, list[int]]:
        out: dict[str, list[int]] = {}
        for i, text in enumerate(ctx.source.splitlines(), start=1):
            for kind, marker in markers.items():
                if marker in text:
                    lines = out.setdefault(kind, [])
                    lines.append(i)
                    if text.lstrip().startswith("#"):
                        lines.append(i + 1)
        return out

    # -- trace-input provenance facts (keyflow) ----------------------------
    #
    # One extra summary key, ``keyflow``, carries everything the keyflow
    # interpreter (analysis/keyflow.py) needs: environment reads (with
    # the enclosing function, so the traced-reach pass can classify them
    # trace-affecting vs host-only), module constants tainted by
    # import-time env reads, cache-key/fingerprint/build-registration
    # call sites, and per-function pools of env-name-shaped string
    # literals (the raw material of the keyed set).

    def _env_read_node(self, node: ast.AST) -> dict | None:
        """{"ln", "var"?|"ref"?} when ``node`` reads the environment —
        ``os.environ.get``/``os.getenv`` calls and ``os.environ[...]``
        subscript loads. ``var`` is a literal env name; ``ref`` a dotted
        constant reference the interpreter resolves; neither when the
        name is dynamic (keyflow stays silent on those)."""
        arg = None
        if isinstance(node, ast.Call):
            t = self.resolve(node.func)
            if t in ("os.environ.get", "os.getenv") and node.args:
                arg = node.args[0]
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            if self.resolve(node.value) == "os.environ":
                arg = node.slice
        if arg is None:
            return None
        rec: dict[str, Any] = {"ln": node.lineno}
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            rec["var"] = arg.value
        elif isinstance(arg, (ast.Name, ast.Attribute)):
            dotted = self.resolve(arg)
            if dotted:
                rec["ref"] = dotted
        return rec

    def _env_reads(self, ctx: ModuleContext) -> list[dict]:
        out: list[dict] = []
        for node in ast.walk(ctx.tree):
            rec = self._env_read_node(node)
            if rec is None:
                continue
            info = ctx.enclosing_function(node)
            rec["fn"] = info.qualname if info else "<module>"
            out.append(rec)
        return out

    def _env_consts(self, tree: ast.Module) -> dict:
        """Module constants tainted by import-time env reads, taint
        propagated through later module-level assignments (the
        flash-attention ``_ENV_BLOCK_Q`` → ``_DEFAULT_BLOCK_Q`` chain):
        name -> {"ln", "vars": [env names], "refs": [dotted]}."""
        tainted: dict[str, dict] = {}
        for node in tree.body:
            target, value = None, None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            if target is None:
                continue
            vars_: set[str] = set()
            refs: set[str] = set()
            for sub in ast.walk(value):
                rec = self._env_read_node(sub)
                if rec is not None:
                    if "var" in rec:
                        vars_.add(rec["var"])
                    if "ref" in rec:
                        refs.add(rec["ref"])
                elif isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load) \
                        and sub.id in tainted:
                    vars_.update(tainted[sub.id]["vars"])
                    refs.update(tainted[sub.id]["refs"])
            if vars_ or refs:
                tainted[target] = {"ln": node.lineno,
                                   "vars": sorted(vars_),
                                   "refs": sorted(refs)}
        return tainted

    def _unstable_parts(self, call: ast.Call) -> list[dict]:
        """Bare ``id()``/``hash()``/``repr()`` calls anywhere in a call's
        argument subtrees — stable within a process, different across
        processes (R20's vocabulary)."""
        out: list[dict] = []
        for sub in ast.walk(call):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id in _UNSTABLE_CALLS \
                    and sub.func.id not in self.aliases:
                rec: dict[str, Any] = {"op": sub.func.id, "ln": sub.lineno}
                if sub.args and isinstance(sub.args[0],
                                           (ast.Name, ast.Attribute)):
                    arg = self.resolve(sub.args[0])
                    if arg:
                        rec["arg"] = arg
                out.append(rec)
        return out

    def _owner_canon(self, node: ast.AST | None) -> dict:
        """Canonical form of a key site's owner argument, for the R21
        collision grouping. ``lit``/``ref`` canons collide globally,
        ``self``/``selfcall`` only within one class (the instance scopes
        them at runtime); ``call``/``other`` never collide — a lint must
        not equate values it cannot prove equal."""
        if node is None:
            return {"k": "none"}
        if isinstance(node, ast.Constant):
            return {"k": "lit", "v": repr(node.value)}
        if isinstance(node, ast.Call):
            t = self.resolve(node.func)
            if t in ("id", "hash") and len(node.args) == 1 \
                    and isinstance(node.args[0], (ast.Name, ast.Attribute)):
                inner = self.resolve(node.args[0])
                if inner and inner.startswith(("self.", "cls.")):
                    return {"k": "selfcall", "v": f"{t}({inner})"}
            return {"k": "call"}
        if isinstance(node, (ast.Name, ast.Attribute)):
            dotted = self.resolve(node)
            if dotted and dotted.startswith(("self.", "cls.")):
                return {"k": "self", "v": dotted}
            if dotted:
                return {"k": "ref", "v": dotted}
        return {"k": "other"}

    def _keysite(self, node: ast.Call, fn: str,
                 info: FunctionInfo | None) -> dict:
        args = list(node.args)
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        owner = args[0] if args else kw.get("owner")
        tagn = args[1] if len(args) > 1 else kw.get("tag")
        static = args[2] if len(args) > 2 else kw.get("static")
        rec: dict[str, Any] = {"ln": node.lineno, "fn": fn,
                               "owner": self._owner_canon(owner)}
        unstable = self._unstable_parts(node)
        if unstable:
            rec["unstable"] = unstable
        if isinstance(tagn, ast.Constant) and isinstance(tagn.value, str):
            rec["tag"] = tagn.value
        if isinstance(static, ast.Dict):
            params: set[str] = set()
            assigned: set[str] = set()
            if info is not None and not isinstance(info.node, ast.Lambda):
                a = info.node.args
                params = {x.arg for x in
                          a.posonlyargs + a.args + a.kwonlyargs}
                assigned = {n.id for n in own_nodes(info.node)
                            if isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Store)}
            skeys: list[str] = []
            svals: list[dict] = []
            for k, v in zip(static.keys, static.values):
                key = (k.value if isinstance(k, ast.Constant)
                       and isinstance(k.value, str) else None)
                if key is not None:
                    skeys.append(key)
                ent: dict[str, Any] = {"k": key}
                if isinstance(v, ast.Constant):
                    ent["t"] = "const"
                elif isinstance(v, ast.Name) and v.id in params \
                        and v.id not in assigned:
                    # a PARAMETER fed straight into the vocabulary: the
                    # caller decides its cardinality (R6's
                    # interprocedural face); a reassigned name stays a
                    # local — the function normalized it itself
                    ent["t"] = "param"
                    ent["p"] = v.id
                elif isinstance(v, (ast.List, ast.Set, ast.Dict,
                                    ast.Tuple)):
                    ent["t"] = "display"
                    ent["h"] = 1 if isinstance(v, ast.Tuple) else 0
                    varying = any(
                        isinstance(x, (ast.Name, ast.Attribute, ast.Call))
                        for x in ast.walk(v) if x is not v)
                    ent["allc"] = 0 if varying else 1
                else:
                    ent["t"] = "other"
                svals.append(ent)
            rec["skeys"] = sorted(skeys)
            rec["svals"] = svals
        return rec

    def _key_sites(self, ctx: ModuleContext,
                   ) -> tuple[list[dict], list[dict], list[dict]]:
        keysites: list[dict] = []
        fpsites: list[dict] = []
        builds: list[dict] = []
        by_node = {i.node: i.qualname for i in ctx.functions}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t, _ = self.callable_target(node)
            info = ctx.enclosing_function(node)
            fn = info.qualname if info else "<module>"
            if resolves_to(t, *_KEY_BUILDERS):
                keysites.append(self._keysite(node, fn, info))
            elif resolves_to(t, *_FP_BUILDERS):
                rec = {"ln": node.lineno, "fn": fn,
                       "b": (t or "").rsplit(".", 1)[-1]}
                unstable = self._unstable_parts(node)
                if unstable:
                    rec["unstable"] = unstable
                fpsites.append(rec)
            elif (resolves_to(t, *_BUILD_ATTRS)
                  or (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _BUILD_ATTRS)):
                kw = {k.arg: k.value for k in node.keywords if k.arg}
                arg = (node.args[1] if len(node.args) > 1
                       else kw.get("factory") or kw.get("builder"))
                while isinstance(arg, ast.Call):
                    inner = self.resolve(arg.func)
                    if resolves_to(inner, "functools.partial",
                                   "partial") and arg.args:
                        arg = arg.args[0]
                    else:
                        arg = arg.func
                b = None
                if isinstance(arg, ast.Lambda):
                    b = "<lambda>:" + by_node.get(arg, "")
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    b = self.resolve(arg)
                if b:
                    builds.append({"ln": node.lineno, "fn": fn, "b": b})
        return keysites, fpsites, builds

    def _env_literals(self, ctx: ModuleContext) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for info in ctx.functions:
            lits = sorted({n.value for n in own_nodes(info.node)
                           if isinstance(n, ast.Constant)
                           and isinstance(n.value, str)
                           and _ENV_NAME_RE.match(n.value)})
            if lits:
                out[info.qualname] = lits
        return out

    def _keyflow_facts(self, ctx: ModuleContext) -> dict:
        out: dict[str, Any] = {}
        keysites, fpsites, builds = self._key_sites(ctx)
        for key, val in (
            ("env", self._env_reads(ctx)),
            ("consts", self._env_consts(ctx.tree)),
            ("keysites", keysites),
            ("fpsites", fpsites),
            ("builds", builds),
            ("lits", self._env_literals(ctx)),
            ("allow", self._allow_lines(ctx, _KEY_ALLOW_MARKERS)),
        ):
            if val:
                out[key] = val
        return out

    def _conc_func(self, ctx: ModuleContext, info: FunctionInfo,
                   classnames: set[str],
                   cls_locks: set[tuple[str, str]], mod_locks: set[str],
                   gmut: set[str], jattrs: dict[str, set[str]],
                   jitw: set[str], jitfuncs: set[str]) -> dict | None:
        """Per-function event stream: lock regions entered (``acq``),
        awaits (``aw``), blocking calls (``bl``), shared-state accesses
        (``at``), device-handoff publishes (``ho``) and lock-relevant
        calls (``cw``) — every event tagged with the held-lock stack."""
        node = info.node
        if isinstance(node, ast.Lambda):
            return None
        qual = info.qualname
        head = qual.split(".")[0]
        cls = head if head in classnames else None
        a = node.args
        params = [arg.arg for arg in a.posonlyargs + a.args + a.kwonlyargs]
        cls_jattrs = jattrs.get(cls, set()) if cls else set()
        localfns = {i.node.name for i in ctx.functions
                    if isinstance(i.node,
                                  (ast.FunctionDef, ast.AsyncFunctionDef))}
        facts: dict[str, list] = {"acq": [], "aw": [], "bl": [],
                                  "at": [], "ho": [], "cw": []}
        held: list[str] = []
        lock_alias: dict[str, str] = {}  # local = self._lock one-hop alias
        local_jitw: set[str] = set()
        tainted: dict[str, str] = {}     # local -> producing dispatch
        g_decl: set[str] = set()
        for n in own_nodes(node):
            if isinstance(n, ast.Global):
                g_decl.update(n.names)

        def lock_token(e: ast.AST) -> str | None:
            if (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id in ("self", "cls")):
                return f"s:{cls}.{e.attr}" if cls else None
            if isinstance(e, ast.Name):
                nid = e.id
                if nid in lock_alias:
                    return lock_alias[nid]
                if nid in mod_locks:
                    return "g:" + nid
                if nid in params:
                    return "p:" + nid
                if nid in self.aliases and "." in self.aliases[nid]:
                    return "d:" + self.aliases[nid]
                return None
            if isinstance(e, ast.Attribute):
                base = e
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in self.aliases:
                    dotted = self.resolve(e)
                    if dotted:
                        return "d:" + dotted
            return None

        def producer_of(call: ast.Call) -> str | None:
            func = call.func
            if isinstance(func, ast.Call):  # inline jit(f)(x)
                inner, _ = self.callable_target(func)
                if resolves_to(inner, *JIT_WRAPPERS):
                    return inner or "jit"
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("self", "cls")
                    and func.attr in cls_jattrs):
                return "self." + func.attr
            if isinstance(func, ast.Name):
                nid = func.id
                if nid in local_jitw or nid in jitw or nid in jitfuncs:
                    return nid
            return None

        def taint_of(e: ast.AST | None) -> str | None:
            if e is None:
                return None
            if isinstance(e, ast.Call):
                t = self.resolve(e.func)
                if t in _CONC_SYNCERS:
                    return None
                if (isinstance(e.func, ast.Attribute)
                        and e.func.attr in _CONC_SYNC_METHODS
                        and not e.args and not e.keywords):
                    return None
                p = producer_of(e)
                if p:
                    return p
                for sub in list(e.args) + [k.value for k in e.keywords]:
                    got = taint_of(sub)
                    if got:
                        return got
                if isinstance(e.func, ast.Attribute):
                    return taint_of(e.func.value)
                return None
            if isinstance(e, ast.Name):
                return tainted.get(e.id)
            if isinstance(e, ast.Await):
                return taint_of(e.value)
            if isinstance(e, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return None
            for child in ast.iter_child_nodes(e):
                got = taint_of(child)
                if got:
                    return got
            return None

        def attr_key(e: ast.AST) -> str | None:
            if (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id in ("self", "cls") and cls):
                return f"a:{cls}.{e.attr}"
            if isinstance(e, ast.Name) and e.id in gmut:
                return "g:" + e.id
            return None

        def rec_at(key: str, w: int, ln: int) -> None:
            facts["at"].append({"n": key, "w": w, "ln": ln,
                                "held": list(held)})

        def do_call(call: ast.Call, ln: int) -> None:
            ln = getattr(call, "lineno", ln)
            t = self.resolve(call.func)
            if t in _CONC_BLOCKING:
                facts["bl"].append({"t": t, "ln": ln, "held": list(held)})
            func = call.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                key = attr_key(func.value)
                if key:
                    rec_at(key, 1, ln)
                    via = None
                    for sub in (list(call.args)
                                + [k.value for k in call.keywords]):
                        via = taint_of(sub)
                        if via:
                            break
                    if via:
                        facts["ho"].append({"n": key, "ln": ln,
                                            "via": via})
            target = None
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("self", "cls")):
                target = "self." + func.attr
            elif t and not t.startswith(("self.", "cls.")):
                target = t
            la = {str(i): tok for i, sub in enumerate(call.args)
                  if (tok := lock_token(sub))}
            # self/local-function calls are recorded even lock-free: the
            # caller-held intersection (raceflow) needs EVERY call site
            # of a ``*_locked``-style helper, not just the guarded ones
            local_call = target is not None and (
                target.startswith(("self.", "cls."))
                or ("." not in target and target in localfns))
            if target and (held or la or local_call):
                facts["cw"].append({"t": target, "ln": ln,
                                    "held": list(held), "la": la})
            if isinstance(func, ast.Attribute):
                scan(func.value, ln)
            for sub in call.args:
                scan(sub, ln)
            for k in call.keywords:
                scan(k.value, ln)

        def scan(e: ast.AST | None, ln: int) -> None:
            if e is None or isinstance(e, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.Lambda, ast.ClassDef)):
                return
            ln = getattr(e, "lineno", ln)
            if isinstance(e, ast.Await):
                facts["aw"].append({"ln": ln, "held": list(held)})
                scan(e.value, ln)
                return
            if isinstance(e, ast.Call):
                do_call(e, ln)
                return
            key = attr_key(e)
            if key is not None and isinstance(getattr(e, "ctx", None),
                                              ast.Load):
                rec_at(key, 0, ln)
                return
            for child in ast.iter_child_nodes(e):
                scan(child, ln)

        def do_store(tgt: ast.AST, via: str | None, ln: int) -> None:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for e in tgt.elts:
                    do_store(e, via, ln)
                return
            if isinstance(tgt, ast.Starred):
                do_store(tgt.value, via, ln)
                return
            if isinstance(tgt, ast.Subscript):
                key = attr_key(tgt.value)
                if key:
                    rec_at(key, 1, ln)
                    if via:
                        facts["ho"].append({"n": key, "ln": ln,
                                            "via": via})
                scan(tgt.slice, ln)
                return
            key = attr_key(tgt)
            if key is None:
                return
            if key.startswith("g:") and isinstance(tgt, ast.Name) \
                    and tgt.id not in g_decl:
                return  # a local shadowing a mutable-global name
            rec_at(key, 1, ln)
            if via:
                facts["ho"].append({"n": key, "ln": ln, "via": via})

        def do_stmts(stmts: list) -> None:
            for st in stmts:
                do_stmt(st)

        def do_stmt(st: ast.stmt) -> None:
            ln = getattr(st, "lineno", 0)
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return
            if isinstance(st, (ast.With, ast.AsyncWith)):
                if isinstance(st, ast.AsyncWith):
                    facts["aw"].append({"ln": ln, "held": list(held)})
                got: list[str] = []
                for item in st.items:
                    ce = item.context_expr
                    tok = (lock_token(ce)
                           if not isinstance(ce, ast.Call) else None)
                    if tok is not None:
                        facts["acq"].append({"l": tok, "ln": ln,
                                             "held": list(held)})
                        held.append(tok)
                        got.append(tok)
                    else:
                        scan(ce, ln)
                do_stmts(st.body)
                for tok in got:
                    held.remove(tok)
                return
            if isinstance(st, ast.Assign):
                via = taint_of(st.value)
                if len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name) \
                        and st.targets[0].id not in g_decl:
                    nid = st.targets[0].id
                    tok = (lock_token(st.value)
                           if not isinstance(st.value, ast.Call) else None)
                    if tok:
                        lock_alias[nid] = tok
                    else:
                        lock_alias.pop(nid, None)
                    if isinstance(st.value, ast.Call):
                        it, _ = self.callable_target(st.value)
                        if resolves_to(it, *JIT_WRAPPERS):
                            local_jitw.add(nid)
                    if via:
                        tainted[nid] = via
                    else:
                        tainted.pop(nid, None)
                for tgt in st.targets:
                    do_store(tgt, via, ln)
                scan(st.value, ln)
                return
            if isinstance(st, ast.AugAssign):
                do_store(st.target, taint_of(st.value), ln)
                scan(st.value, ln)
                return
            if isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    do_store(st.target, taint_of(st.value), ln)
                    scan(st.value, ln)
                return
            if isinstance(st, ast.Expr):
                v = st.value
                if isinstance(v, ast.Call):
                    # a bare sync statement clears the named value:
                    # ``jax.block_until_ready(y)`` / ``y.block_until_ready()``
                    t = self.resolve(v.func)
                    if t in _CONC_SYNCERS and v.args \
                            and isinstance(v.args[0], ast.Name):
                        tainted.pop(v.args[0].id, None)
                    if (isinstance(v.func, ast.Attribute)
                            and v.func.attr in _CONC_SYNC_METHODS
                            and isinstance(v.func.value, ast.Name)):
                        tainted.pop(v.func.value.id, None)
                scan(v, ln)
                return
            if isinstance(st, (ast.If, ast.While)):
                scan(st.test, ln)
                do_stmts(st.body)
                do_stmts(st.orelse)
                return
            if isinstance(st, (ast.For, ast.AsyncFor)):
                if isinstance(st, ast.AsyncFor):
                    facts["aw"].append({"ln": ln, "held": list(held)})
                scan(st.iter, ln)
                do_store(st.target, None, ln)
                do_stmts(st.body)
                do_stmts(st.orelse)
                return
            if isinstance(st, ast.Try):
                do_stmts(st.body)
                for h in st.handlers:
                    do_stmts(h.body)
                do_stmts(st.orelse)
                do_stmts(st.finalbody)
                return
            if isinstance(st, ast.Return):
                scan(st.value, ln)
                return
            if isinstance(st, ast.Delete):
                for tgt in st.targets:
                    key = (attr_key(tgt)
                           or (attr_key(tgt.value)
                               if isinstance(tgt, ast.Subscript) else None))
                    if key:
                        rec_at(key, 1, ln)
                return
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    scan(child, ln)
                elif isinstance(child, ast.stmt):
                    do_stmt(child)
                elif hasattr(child, "body"):  # match_case and friends
                    do_stmts(getattr(child, "body"))

        do_stmts(node.body)
        facts = {k: v for k, v in facts.items() if v}
        return facts or None

    # -- custom_vjp / custom_jvp registrations (shardflow satellite) ------
    def _customvjp_facts(self, ctx: ModuleContext) -> list[dict]:
        """``f.defvjp(fwd, bwd)`` / ``f.defjvp(...)`` sites: the primal
        and its companion functions, so shardflow can explore collective
        use inside custom-derivative bodies the call graph never reaches
        through ordinary calls."""
        out: list[dict] = []
        for call in ast.walk(ctx.tree):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("defvjp", "defjvp", "defjvps")):
                continue
            primal = self.resolve(call.func.value)
            if primal is None or primal.startswith(("self.", "cls.")):
                continue
            fns = []
            for a in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(a, (ast.Name, ast.Attribute)):
                    r = self.resolve(a)
                    if r and not r.startswith(("self.", "cls.")):
                        fns.append(r)
            if fns:
                out.append({"p": primal, "fns": fns, "ln": call.lineno})
        return out

    # -- spec / mesh variable maps (shardflow) ----------------------------
    def _collect_spec_vars(self, tree: ast.Module) -> None:
        """Map (enclosing symbol, var) -> axes facts for local
        ``spec = P(…)`` and ``ms = MeshSpec({…})`` assignments, so
        shard_map sites that pass specs/meshes through variables still
        resolve (ops/attention.py's ``in_specs=(spec, spec, spec)``)."""
        self._spec_vars: dict[tuple[str, str], dict] = {}
        self._meshspec_vars: dict[tuple[str, str], list[dict]] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            key = (self.ctx.symbol_for(node), node.targets[0].id)
            t, _ = self.callable_target(node.value)
            if resolves_to(t, *_SPEC_NAMES):
                self._spec_vars[key] = self._spec_axes(node.value)
            elif resolves_to(t, *_MESHSPEC_NAMES):
                axes = self._meshspec_axes(node.value)
                if axes is not None:
                    self._meshspec_vars[key] = axes

    def _spec_axes(self, call: ast.Call) -> dict:
        """Per-spec {"may": axisrefs, "must": axisrefs}: ``may`` is every
        axis the spec can mention; ``must`` only the unconditional
        dimensions (an ``IfExp`` dim contributes to may alone)."""
        may: list[dict] = []
        must: list[dict] = []

        def add(refs, into):
            for r in refs:
                if r not in into:
                    into.append(r)

        for dim in call.args:
            refs = _axisref(dim, self.resolve)
            add(refs, may)
            if isinstance(dim, (ast.Constant, ast.Name, ast.Attribute,
                                ast.Tuple, ast.List)):
                add(refs, must)
        return {"may": may, "must": must}

    def _spec_axes_of(self, node: ast.AST, symbol: str) -> dict | None:
        """Axes facts of one in_specs element: a P(…) call, a local spec
        variable, or None (replicated). None return = unresolvable."""
        if isinstance(node, ast.Constant) and node.value is None:
            return {"may": [], "must": []}
        if isinstance(node, ast.Call):
            t, _ = self.callable_target(node)
            if resolves_to(t, *_SPEC_NAMES):
                return self._spec_axes(node)
            return None
        if isinstance(node, ast.Name):
            for key in ((symbol, node.id), ("<module>", node.id)):
                if key in self._spec_vars:
                    return self._spec_vars[key]
        return None

    def _out_axes(self, node: ast.AST, symbol: str) -> dict | None:
        """Aggregate {"may": axisrefs} over a whole out_specs expression
        (tuples of specs union). None = some element unresolvable, and
        the unreduced-out-spec check must stay silent."""
        got = self._spec_axes_of(node, symbol)
        if got is not None:
            return {"may": got["may"]}
        if isinstance(node, (ast.Tuple, ast.List)):
            may: list[dict] = []
            for el in node.elts:
                sub = self._out_axes(el, symbol)
                if sub is None:
                    return None
                for r in sub["may"]:
                    if r not in may:
                        may.append(r)
            return {"may": may}
        return None

    def _meshspec_axes(self, call: ast.Call) -> list[dict] | None:
        kwargs = {k.arg: k.value for k in call.keywords if k.arg}
        shape = kwargs.get("shape")
        if shape is None and call.args:
            shape = call.args[0]
        if isinstance(shape, ast.Dict):
            axes: list[dict] = []
            for key in shape.keys:
                if key is not None:
                    axes.extend(_axisref(key, self.resolve))
            return axes
        return None

    def _mesh_axes_from_call(self, call: ast.Call,
                             symbol: str) -> tuple[list[dict], bool] | None:
        """(axes refs, open) of a mesh-producing call, or None.

        ``open`` is True for ``MeshSpec``-derived meshes: core/mesh.py's
        ``build_mesh`` materializes EVERY axis in the (default) axis
        order at size >= 1, so unmentioned vocabulary axes still exist on
        the mesh and must not be flagged against it. A raw
        ``Mesh(devices, axis_names)`` literal is closed — its axis_names
        are exactly the universe."""
        t, _ = self.callable_target(call)
        kwargs = {k.arg: k.value for k in call.keywords if k.arg}
        if resolves_to(t, *_MESH_NAMES):
            ax = kwargs.get("axis_names")
            if ax is None and len(call.args) >= 2:
                ax = call.args[1]
            if ax is not None:
                return _axisref(ax, self.resolve), False
            return None
        if resolves_to(t, *_MESHSPEC_NAMES):
            axes = self._meshspec_axes(call)
            return (axes, True) if axes is not None else None
        if resolves_to(t, *_BUILD_MESH_NAMES):
            spec = kwargs.get("spec")
            if spec is None and call.args:
                spec = call.args[0]
            if isinstance(spec, ast.Call):
                return self._mesh_axes_from_call(spec, symbol)
            if isinstance(spec, ast.Name):
                for key in ((symbol, spec.id), ("<module>", spec.id)):
                    if key in self._meshspec_vars:
                        return self._meshspec_vars[key], True
        return None

    def _mesh_instances(self, ctx: ModuleContext) -> list[dict]:
        """Named mesh constructions: ``mesh = Mesh(…)`` /
        ``mesh = build_mesh(MeshSpec({…}))`` — the per-mesh-instance axis
        universes the R10 extension and the shardflow interpreter bind
        shard_map sites against."""
        out: list[dict] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            symbol = ctx.symbol_for(node)
            got = self._mesh_axes_from_call(node.value, symbol)
            if got is None:
                continue
            axes, open_ = got
            out.append({"var": node.targets[0].id, "symbol": symbol,
                        "line": node.lineno, "axes": axes, "open": open_})
        return out

    def _mesh_ref(self, node: ast.AST | None, symbol: str) -> dict | None:
        """How a shard_map site names its mesh: a local/module variable
        ({"name"}), an import-resolved path ({"ref"}), an inline
        construction ({"axes", "open"}), or None (unresolvable — the
        per-instance checks fall back to the global universe)."""
        if node is None:
            return None
        if isinstance(node, ast.Name):
            if node.id in self.aliases:
                return {"ref": self.aliases[node.id]}
            return {"name": node.id}
        if isinstance(node, ast.Attribute):
            dotted = self.resolve(node)
            if dotted and self._import_rooted(node):
                return {"ref": dotted}
            return None
        if isinstance(node, ast.Call):
            got = self._mesh_axes_from_call(node, symbol)
            if got is not None:
                axes, open_ = got
                return {"axes": axes, "open": open_, "line": node.lineno}
        return None

    # -- donation facts (R13) ---------------------------------------------
    def _donations(self, ctx: ModuleContext) -> list[dict]:
        """jit-wrapper call sites that declare buffer donation, plus the
        variable the wrapper is bound to (``STEP = toplevel_jit(step,
        donate_argnums=(0,))``) so use-after-donate tracks the wrapper
        across modules through exports."""
        out: list[dict] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t, _ = self.callable_target(node)
            if not resolves_to(t, *JIT_WRAPPERS):
                continue
            nums, names = _donate_decl(node)
            if not nums and not names:
                continue
            var = None
            parent = self.ctx.parents.get(node)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                var = parent.targets[0].id
            fname = self.resolve(node.args[0]) if node.args else None
            out.append({"line": node.lineno, "col": node.col_offset,
                        "symbol": ctx.symbol_for(node), "var": var,
                        "fname": fname, "nums": nums, "names": names})
        return out

    # -- sharding facts ----------------------------------------------------
    def _sharding_facts(self, ctx: ModuleContext) -> dict:
        mesh_axes: list[dict] = []
        specs: list[dict] = []
        shard_maps: list[dict] = []
        collectives: list[dict] = []

        for name, value in self._constants(ctx.tree).items():
            if isinstance(value, str) and name.endswith("_AXIS"):
                mesh_axes.append({"lit": value})
            elif isinstance(value, list) and name.endswith("AXES"):
                mesh_axes.extend(value)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t, consumed = self.callable_target(node)
            if t is None:
                continue
            loc = {"line": node.lineno, "col": node.col_offset,
                   "symbol": ctx.symbol_for(node)}
            kwargs = {k.arg: k.value for k in node.keywords if k.arg}

            if resolves_to(t, *_MESH_NAMES):
                ax = kwargs.get("axis_names")
                if ax is None and len(node.args) >= 2:
                    ax = node.args[1]
                if ax is not None:
                    mesh_axes.extend(_axisref(ax, self.resolve))
            elif resolves_to(t, *_MESHSPEC_NAMES):
                shape = kwargs.get("shape")
                if shape is None and node.args:
                    shape = node.args[0]
                if isinstance(shape, ast.Dict):
                    for key in shape.keys:
                        if key is not None:
                            mesh_axes.extend(_axisref(key, self.resolve))
            elif resolves_to(t, *_SPEC_NAMES):
                axes: list[dict] = []
                for arg in node.args:
                    axes.extend(_axisref(arg, self.resolve))
                specs.append({**loc, "arity": len(node.args), "axes": axes})
            elif resolves_to(t, "shard_map"):
                callee = None
                pconsumed = 0
                pkw: dict[str, Any] = {}
                if node.args:
                    callee, pconsumed, pkw = self.callee_with_kwargs(
                        node.args[0])
                rec: dict[str, Any] = {**loc, "callee": callee,
                                       "pconsumed": pconsumed,
                                       "pkw": pkw,
                                       "in_arity": None}
                if node.args and isinstance(node.args[0], ast.Lambda):
                    la = node.args[0].args
                    rec["lam"] = {
                        "npos": len(la.posonlyargs) + len(la.args),
                        "ndef": len(la.defaults),
                        "vararg": la.vararg is not None,
                    }
                    info = ctx._func_by_node.get(node.args[0])
                    if info is not None:
                        rec["callee_lam"] = info.qualname
                symbol = loc["symbol"]
                mesh = kwargs.get("mesh")
                if mesh is None and len(node.args) >= 2:
                    mesh = node.args[1]
                rec["mesh"] = self._mesh_ref(mesh, symbol)
                in_specs = kwargs.get("in_specs")
                if in_specs is None and len(node.args) >= 3:
                    in_specs = node.args[2]
                if isinstance(in_specs, (ast.Tuple, ast.List)):
                    rec["in_arity"] = len(in_specs.elts)
                    rec["in_axes"] = [self._spec_axes_of(el, symbol)
                                      for el in in_specs.elts]
                elif in_specs is not None:
                    # a single spec (pytree prefix): applies to every arg
                    rec["in_single"] = self._spec_axes_of(in_specs, symbol)
                out_specs = kwargs.get("out_specs")
                if out_specs is None and len(node.args) >= 4:
                    out_specs = node.args[3]
                if out_specs is not None:
                    rec["out_axes"] = self._out_axes(out_specs, symbol)
                shard_maps.append(rec)
            else:
                resolved_op = None
                for op in _COLLECTIVES:
                    if resolves_to(t, op):
                        resolved_op = op
                        break
                if resolved_op is None:
                    continue
                ax = kwargs.get("axis_name")
                if ax is None:
                    pos = _COLLECTIVES[resolved_op] - consumed
                    if 0 <= pos < len(node.args):
                        ax = node.args[pos]
                axis: dict | None = None
                if ax is not None:
                    # a Name may be a parameter of any ENCLOSING function
                    # (ring_attention's scan body reads the closure's
                    # axis_name): the binding check targets the owner
                    owner = None
                    if isinstance(ax, ast.Name):
                        info = ctx.enclosing_function(node)
                        while info is not None and owner is None:
                            fnode = info.node
                            a_ = fnode.args
                            names = {arg.arg for arg in a_.posonlyargs
                                     + a_.args + a_.kwonlyargs}
                            if ax.id in names:
                                owner = info.qualname
                            info = info.parent
                    if owner is not None:
                        axis = {"param": ax.id, "owner": owner}
                    else:
                        refs = _axisref(ax, self.resolve)
                        axis = refs[0] if len(refs) == 1 else None
                collectives.append({
                    **loc, "op": resolved_op, "axis": axis,
                    "func": ctx.symbol_for(node),
                })
        return {"mesh_axes": mesh_axes, "specs": specs,
                "shard_maps": shard_maps, "collectives": collectives}


def summarize_module(relpath: str, source: str, tree: ast.Module,
                     module: str, is_package: bool) -> dict:
    return _Summarizer(relpath, source, tree, module, is_package).summarize()


# ---------------------------------------------------------------------------
# the index


class ProjectIndex:
    """Whole-program view over per-module summaries.

    ``funcs`` keys are ``(module, qualname)`` pairs; chains reported by the
    interprocedural rules are lists of ``(relpath, line, dotted-qualname)``
    hops suitable for :attr:`Finding.chain`.
    """

    def __init__(self, summaries: dict[str, dict]):
        self.summaries = summaries              # relpath -> summary
        self.modules: dict[str, str] = {}       # module name -> relpath
        for rel in sorted(summaries):
            mod = summaries[rel]["module"]
            self.modules.setdefault(mod, rel)
        self.funcs: dict[tuple[str, str], dict] = {}
        for rel, s in summaries.items():
            for qual, f in s["functions"].items():
                self.funcs[(s["module"], qual)] = f
        self._edges: dict[tuple[str, str], set[tuple[str, str]]] | None = None
        self._redges: dict[str, set[str]] | None = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_sources(cls, entries: Iterable[tuple[str, str, ast.Module]],
                     ) -> "ProjectIndex":
        summaries = {}
        for relpath, source, tree in entries:
            rel = relpath.replace(os.sep, "/")
            module, is_pkg = module_name_from_relpath(rel)
            summaries[rel] = summarize_module(rel, source, tree, module,
                                              is_pkg)
        return cls(summaries)

    @classmethod
    def build(cls, files: Iterable[tuple[str, str]],
              cache_path: str | None = None) -> "ProjectIndex":
        """Index (abspath, relpath) files, reusing cached summaries for
        files whose content hash is unchanged. Unparseable files are
        skipped here — the per-file driver already reports them."""
        cache: dict[str, Any] = {}
        if cache_path:
            try:
                with open(cache_path, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
                if isinstance(doc, dict) and doc.get("schema") == SCHEMA:
                    cache = doc.get("files", {})
            except (OSError, ValueError):
                cache = {}
        summaries: dict[str, dict] = {}
        fresh: dict[str, Any] = {}
        dirty = False
        for abspath, rel in files:
            rel = rel.replace(os.sep, "/")
            try:
                with open(abspath, "rb") as fh:
                    raw = fh.read()
            except OSError:
                continue
            digest = hashlib.sha256(raw).hexdigest()
            entry = cache.get(rel)
            if entry and entry.get("hash") == digest:
                summaries[rel] = entry["summary"]
                fresh[rel] = entry
                continue
            try:
                source = raw.decode("utf-8")
                tree = ast.parse(source, filename=rel)
            except (SyntaxError, UnicodeDecodeError, ValueError):
                dirty = True
                continue
            module, is_pkg = module_name_for_file(abspath)
            summary = summarize_module(rel, source, tree, module, is_pkg)
            summaries[rel] = summary
            fresh[rel] = {"hash": digest, "summary": summary}
            dirty = True
        if cache_path and dirty:
            # MERGE into the existing cache — a path-subset run must not
            # evict the rest of the repo's warm entries — and drop
            # entries whose files vanished so the cache cannot grow
            # without bound across renames/deletions
            merged = dict(cache)
            merged.update(fresh)
            base = os.path.dirname(os.path.abspath(cache_path))
            merged = {rel: e for rel, e in merged.items()
                      if rel in fresh
                      or os.path.exists(os.path.join(base, rel))}
            try:
                tmp = cache_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"schema": SCHEMA, "files": merged}, fh)
                os.replace(tmp, cache_path)
            except OSError:
                pass  # read-only checkout (CI): the cache is an optimization
        return cls(summaries)

    # -- symbol resolution -------------------------------------------------
    def resolve_qual(self, dotted: str,
                     _seen: frozenset = frozenset()) -> tuple[str, Any] | None:
        """Resolve a dotted name to ("func", (module, qualname)),
        ("const", value), ("tuple", [...]) or ("module", name), following
        top-level re-exports across modules."""
        if dotted in _seen:
            return None
        _seen = _seen | {dotted}
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                rest = parts[i:]
                break
        else:
            return None
        if not rest:
            return ("module", mod)
        s = self.summaries[self.modules[mod]]
        qual = ".".join(rest)
        if qual in s["functions"]:
            return ("func", (mod, qual))
        head = rest[0]
        if head in s["constants"] and len(rest) == 1:
            v = s["constants"][head]
            return ("const", v) if isinstance(v, str) else ("tuple", v)
        target = s["exports"].get(head)
        if target is not None:
            follow = ".".join([target] + rest[1:])
            return self.resolve_qual(follow, _seen)
        return None

    def resolve_axis(self, ref: dict | None, module: str) -> str | None:
        """An axis reference ({"lit"}/{"ref"}) to its string, following
        constants; None when it cannot be proven."""
        if not ref:
            return None
        if "lit" in ref:
            return ref["lit"]
        dotted = ref.get("ref")
        if not dotted:
            return None
        if "." not in dotted:
            rel = self.modules.get(module)
            if rel is not None:
                s = self.summaries[rel]
                v = s["constants"].get(dotted)
                if isinstance(v, str):
                    return v
                target = s["exports"].get(dotted)
                if target:
                    dotted = target
                else:
                    return None
            else:
                return None
        got = self.resolve_qual(dotted)
        if got and got[0] == "const":
            return got[1]
        return None

    # -- call graph --------------------------------------------------------
    def func_targets(self, module: str, target: str) -> list[tuple[str, str]]:
        s = self.summaries.get(self.modules.get(module, ""), None)
        out: list[tuple[str, str]] = []
        if "." not in target:
            if s is not None:
                out = [(module, q) for q in s["names"].get(target, [])]
            return out
        got = self.resolve_qual(target)
        if got and got[0] == "func":
            return [got[1]]
        return []

    def table_targets(self, module: str,
                      dotted: str) -> list[tuple[str, str]]:
        """Members of a dispatch table referenced as ``dotted`` from
        ``module`` — the expansion of an ``@table:`` call target. The
        table may live in this module (bare name) or in another one
        (import-aliased dotted path), and its VALUES were resolved in
        the OWNING module's namespace at summarize time."""
        owner, name = module, dotted
        if "." in dotted:
            head, _, tail = dotted.rpartition(".")
            got = self.resolve_qual(head)
            if got is None or got[0] != "module":
                return []
            owner, name = got[1], tail
        s = self.summaries.get(self.modules.get(owner, ""), None)
        entries = (s or {}).get("tables", {}).get(name, [])
        out: list[tuple[str, str]] = []
        for target in entries:
            out.extend(self.func_targets(owner, target))
        return out

    def edges(self) -> dict[tuple[str, str], set[tuple[str, str]]]:
        if self._edges is not None:
            return self._edges
        edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for (module, qual), f in self.funcs.items():
            out: set[tuple[str, str]] = set()
            for call in f["calls"]:
                if not call["t"]:
                    continue
                if call["t"].startswith("@table:"):
                    # workload dispatch dicts (R9 extension): a
                    # TABLE[key](...) call conservatively reaches every
                    # member of the table
                    out.update(self.table_targets(
                        module, call["t"][len("@table:"):]))
                else:
                    out.update(self.func_targets(module, call["t"]))
            for name in f["methods"]:
                out.update(self.func_targets(module, name))
            out.discard((module, qual))
            edges[(module, qual)] = out
        self._edges = edges
        return edges

    def jit_entry_points(self) -> dict[tuple[str, str], list[dict]]:
        """Functions entering trace, mapped to their REGISTRATION sites:
        ``{"module", "relpath", "line", "symbol"}`` per decoration site /
        jit()/scan() call site. R9 uses the registering modules to
        delimit R1's jurisdiction (a body registered from another module
        is invisible to the per-file pass even when its whole chain stays
        in one file) and prepends a cross-module registration site to the
        reported chain."""
        roots: dict[tuple[str, str], list[dict]] = {}
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            module = s["module"]
            for qual in s["jit_roots"]:
                if (module, qual) in self.funcs:
                    f = self.funcs[(module, qual)]
                    roots.setdefault((module, qual), []).append(
                        {"module": module, "relpath": rel,
                         "line": f["line"], "symbol": qual})
            for ref in s["jit_refs"]:
                got = self.resolve_qual(ref["t"])
                if got and got[0] == "func":
                    roots.setdefault(got[1], []).append(
                        {"module": module, "relpath": rel,
                         "line": ref["line"],
                         "symbol": ref.get("symbol", "<module>")})
        return roots

    def reach_with_parents(self, roots: Iterable[tuple[str, str]],
                           ) -> dict[tuple[str, str],
                                     tuple[str, str] | None]:
        """BFS over the call graph; maps every reachable function to its
        first-discovered caller (None for roots) for chain rebuilding."""
        edges = self.edges()
        parent: dict[tuple[str, str], tuple[str, str] | None] = {}
        frontier: collections.deque = collections.deque()
        for r in sorted(set(roots)):
            parent[r] = None
            frontier.append(r)
        while frontier:
            node = frontier.popleft()
            for nxt in sorted(edges.get(node, ())):
                if nxt not in parent:
                    parent[nxt] = node
                    frontier.append(nxt)
        return parent

    def chain(self, parent: dict, node: tuple[str, str],
              ) -> tuple[tuple[str, int, str], ...]:
        """Root -> ... -> node as Finding.chain hops."""
        hops: list[tuple[str, int, str]] = []
        cur: tuple[str, str] | None = node
        while cur is not None:
            f = self.funcs[cur]
            rel = self.modules[cur[0]]
            hops.append((rel, f["line"], f"{cur[0]}.{cur[1]}"))
            cur = parent.get(cur)
        return tuple(reversed(hops))

    def callers_of(self, target: tuple[str, str]) -> list[tuple[str, str]]:
        return sorted(n for n, outs in self.edges().items()
                      if target in outs)

    # -- import graph ------------------------------------------------------
    def module_deps(self, rel: str) -> set[str]:
        """relpaths this file imports (project-internal only)."""
        out: set[str] = set()
        for dep in self.summaries[rel]["deps"]:
            cands = []
            if dep["m"]:
                cands.append(dep["m"])
            if dep["n"]:
                # `from m import n` may name a submodule, not a symbol
                cands.append(f"{dep['m']}.{dep['n']}" if dep["m"]
                             else dep["n"])
            for cand in cands:
                hit = self.modules.get(cand)
                if hit is not None:
                    out.add(hit)
        out.discard(rel)
        return out

    def reverse_closure(self, seeds: Iterable[str]) -> set[str]:
        """``seeds`` (relpaths) plus every file that transitively imports
        one of them — the set a pre-commit run must re-lint.

        Mesh-constant provenance rides on top of the import graph: a
        module that DEFINES mesh vocabulary (mesh instances or axis
        constants) is consumed by every module with sharding facts even
        when no import edge exists (``parallel/ring_attention.py`` reads
        its axis through a parameter, never importing ``core/mesh.py``) —
        so editing a mesh-defining seed re-lints every sharding consumer,
        and the R10–R12 verdicts can never go stale under
        ``--changed-only``."""
        rdeps: dict[str, set[str]] = {}
        for rel in self.summaries:
            for dep in self.module_deps(rel):
                rdeps.setdefault(dep, set()).add(rel)
        out = {s for s in seeds if s in self.summaries}
        if any(self._defines_mesh(rel) for rel in out):
            out |= {rel for rel in self.summaries
                    if self._consumes_sharding(rel)}
        # Same provenance rule for concurrency vocabulary: a module that
        # DEFINES an execution root or a lock changes the thread topology
        # every raceflow verdict depends on, so editing it re-lints every
        # module with concurrency facts of its own (lock regions, spawns,
        # handoffs — attribute-only modules can't host an R14–R17 finding
        # and stay out).
        if any(self._defines_conc(rel) for rel in out):
            out |= {rel for rel in self.summaries
                    if self._consumes_conc(rel)}
        # Key-provenance rule (ISSUE 20): a module that DEFINES executable
        # identity — the cache-key builders themselves, or any env knob a
        # traced program may read — changes every R18–R21 verdict, so
        # editing one re-lints every module with key sites, build scopes
        # or env reads of its own (the keyed set and the traced reach are
        # both global properties; no import edge need exist between the
        # knob module and the program it retraces).
        if any(self._defines_key(rel) for rel in out):
            out |= {rel for rel in self.summaries
                    if self._consumes_key(rel)}
        frontier = list(out)
        while frontier:
            rel = frontier.pop()
            for dependent in rdeps.get(rel, ()):
                if dependent not in out:
                    out.add(dependent)
                    frontier.append(dependent)
        return out

    def _defines_mesh(self, rel: str) -> bool:
        s = self.summaries[rel]
        return bool(s.get("meshes") or s.get("mesh_axes"))

    def _consumes_sharding(self, rel: str) -> bool:
        s = self.summaries[rel]
        return bool(s.get("specs") or s.get("shard_maps")
                    or s.get("collectives"))

    def _defines_key(self, rel: str) -> bool:
        kf = self.summaries[rel].get("keyflow") or {}
        if kf.get("env") or kf.get("consts"):
            return True
        names = self.summaries[rel].get("names") or {}
        return any(n in names
                   for n in _KEY_BUILDERS + _FP_BUILDERS)

    def _consumes_key(self, rel: str) -> bool:
        kf = self.summaries[rel].get("keyflow") or {}
        return bool(kf.get("keysites") or kf.get("fpsites")
                    or kf.get("builds") or kf.get("env")
                    or kf.get("consts"))

    def _defines_conc(self, rel: str) -> bool:
        conc = self.summaries[rel].get("conc") or {}
        return bool(conc.get("spawns") or conc.get("lockdefs"))

    def _consumes_conc(self, rel: str) -> bool:
        conc = self.summaries[rel].get("conc") or {}
        if conc.get("spawns") or conc.get("lockdefs"):
            return True
        return any(f.get("acq") or f.get("aw") or f.get("bl")
                   or f.get("ho") or f.get("cw")
                   for f in (conc.get("funcs") or {}).values())

    # -- mesh instances (per-mesh-instance universes, R10 extension) -------
    def _mesh_var(self, module: str, var: str,
                  symbol: str | None = None,
                  _seen: frozenset = frozenset()) -> dict | None:
        """A mesh definition record for ``var`` in ``module``: prefer the
        definition inside ``symbol``'s scope, else module scope, else
        follow a top-level re-export of the name."""
        if (module, var) in _seen:
            return None
        _seen = _seen | {(module, var)}
        rel = self.modules.get(module)
        if rel is None:
            return None
        s = self.summaries[rel]
        hits = [m for m in s.get("meshes", ()) if m["var"] == var]
        for want in ([symbol] if symbol else []) + ["<module>"]:
            for m in hits:
                if m["symbol"] == want:
                    return dict(m, module=module, rel=rel)
        target = s["exports"].get(var)
        if target and "." in target:
            head, _, tail = target.rpartition(".")
            got = self.resolve_qual(head)
            if got and got[0] == "module":
                return self._mesh_var(got[1], tail, None, _seen)
        return None

    def resolve_mesh(self, module: str, symbol: str,
                     meshref: dict | None) -> dict | None:
        """Resolve a shard_map site's mesh reference to an instance:
        ``{"axes": set[str], "open": bool, "hop": (rel, line, qual)}``.
        None = unresolvable — callers fall back to the global universe.
        Instances with any unresolvable axis ref resolve to None (a
        partial universe would produce indefensible findings)."""
        if not meshref:
            return None
        rec = None
        owner = module
        if "name" in meshref:
            rec = self._mesh_var(module, meshref["name"], symbol)
        elif "ref" in meshref:
            dotted = meshref["ref"]
            head, _, tail = dotted.rpartition(".")
            got = self.resolve_qual(head) if head else None
            if got and got[0] == "module":
                rec = self._mesh_var(got[1], tail, None)
        elif "axes" in meshref:
            rec = {"axes": meshref["axes"], "open": meshref.get("open", True),
                   "module": module, "rel": self.modules.get(module),
                   "line": meshref.get("line", 0), "var": "<inline>",
                   "symbol": symbol}
        if rec is None:
            return None
        owner = rec["module"]
        axes: set[str] = set()
        for ref in rec["axes"]:
            v = self.resolve_axis(ref, owner)
            if v is None:
                return None
            axes.add(v)
        hop = (rec.get("rel") or self.modules.get(owner, ""),
               rec.get("line", 0), f"{owner}.{rec.get('var', '?')}")
        return {"axes": axes, "open": bool(rec.get("open")), "hop": hop}

    # -- misc --------------------------------------------------------------
    def axis_universe(self) -> dict[str, list[str]]:
        """axis name -> relpaths of the modules whose mesh constructs bind
        it. Empty when the project defines no meshes at all."""
        out: dict[str, list[str]] = {}
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            for ref in s["mesh_axes"]:
                v = self.resolve_axis(ref, s["module"])
                if v is not None and rel not in out.setdefault(v, []):
                    out[v].append(rel)
        return out
