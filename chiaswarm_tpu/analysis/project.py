"""swarmflow: the whole-program index under swarmlint's interprocedural
rules (R9 host-sync reachability, R10 sharding-spec drift).

Every rule through R8 is a single-file AST pass — a jitted function that
calls a helper in another module which does ``.item()`` is invisible to
R1, and nothing checks that ``PartitionSpec``/``shard_map`` axis names
agree across ``parallel/``, ``pipelines/`` and ``serving/``. This module
builds the missing layer, still pure stdlib:

- **module graph** — every linted file becomes a module (dotted name
  derived by climbing ``__init__.py`` packages), with absolute import
  edges (relative imports resolved against the module's package);
- **symbol resolution** — top-level functions, classes' methods, string
  constants and ``from x import y`` re-exports resolve by qualified name
  across modules, following re-export chains (the ``core/compat`` shims);
- **conservative call graph** — per-function call targets keyed by
  qualified name. Conservative means *precise*: an edge exists only when
  the callee resolves statically (bare names through import aliases,
  dotted module paths, ``self.``/``cls.`` methods, ``functools.partial``
  unwrapping). Instance-method calls on arbitrary objects are NOT edges —
  a lint must not invent paths it cannot defend;
- **incremental cache** — per-file summaries (everything the
  interprocedural rules consume) persist to ``.swarmflow-cache.json``
  keyed on content hashes, so a warm whole-repo lint re-summarizes only
  edited files and stays inside the seconds-fast budget, jax never
  imported.

The index deliberately stores *summaries*, not ASTs: a summary is a small
JSON-able dict, which makes the cache format trivial and keeps peak
memory flat across ~100 modules.
"""

from __future__ import annotations

import ast
import collections
import hashlib
import json
import os
from typing import Any, Iterable

from chiaswarm_tpu.analysis.core import FunctionInfo, ModuleContext
from chiaswarm_tpu.analysis.rules import (
    JIT_WRAPPERS, TRACED_WRAPPERS, own_nodes, resolves_to,
)

SCHEMA = 2  # v2: dispatch-table facts ("tables", "@table:" call targets)
DEFAULT_CACHE_NAME = ".swarmflow-cache.json"

#: cross-chip collective primitives and the axis-name argument position
#: they read when it is not passed as ``axis_name=``
_COLLECTIVES: dict[str, int] = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmax": 1,
    "jax.lax.pmin": 1, "jax.lax.ppermute": 1, "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1, "jax.lax.psum_scatter": 1,
    "jax.lax.axis_index": 0, "jax.lax.pshuffle": 1,
    "axis_size": 0,  # core/compat shim (jax.lax.axis_size on modern jax)
}

_SPEC_NAMES = ("jax.sharding.PartitionSpec", "PartitionSpec")
_MESH_NAMES = ("jax.sharding.Mesh", "Mesh")
_MESHSPEC_NAMES = ("MeshSpec",)


# ---------------------------------------------------------------------------
# module naming


def module_name_for_file(abspath: str) -> tuple[str, bool]:
    """(dotted module name, is_package) for a file on disk, climbing the
    ``__init__.py`` chain so the name matches what ``import`` would use
    regardless of where the lint root sits."""
    dirpath, fname = os.path.split(os.path.abspath(abspath))
    stem = fname[:-3] if fname.endswith(".py") else fname
    is_package = stem == "__init__"
    parts = [] if is_package else [stem]
    while os.path.isfile(os.path.join(dirpath, "__init__.py")):
        dirpath, pkg = os.path.split(dirpath)
        parts.insert(0, pkg)
    if not parts:  # a bare __init__.py with no package parent
        parts = [os.path.basename(dirpath) or stem]
    return ".".join(parts), is_package


def module_name_from_relpath(relpath: str) -> tuple[str, bool]:
    """In-memory variant (fixture sources): every path part is assumed a
    package, so ``pkg/mod.py`` -> ``pkg.mod``."""
    parts = [p for p in relpath.replace(os.sep, "/").split("/")
             if p not in (".", "..")]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    is_package = bool(parts) and parts[-1] == "__init__"
    if is_package:
        parts = parts[:-1]
    return ".".join(parts) or relpath, is_package


# ---------------------------------------------------------------------------
# per-module summary extraction


def _axisref(node: ast.AST, resolve) -> list[dict]:
    """Axis-name references inside one spec/collective argument: string
    literals become ``{"lit": s}``, resolvable names ``{"ref": dotted}``.
    Conditional expressions contribute both VALUE branches (never the
    test — its variables are not axis names); ``None`` (the replicated
    dimension) contributes nothing."""
    out: list[dict] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append({"lit": n.value})
        elif isinstance(n, (ast.Name, ast.Attribute)):
            dotted = resolve(n)
            if dotted and not dotted.startswith(("self.", "cls.")):
                out.append({"ref": dotted})
        elif isinstance(n, ast.IfExp):
            visit(n.body)
            visit(n.orelse)
        elif isinstance(n, (ast.Tuple, ast.List)):
            for e in n.elts:
                visit(e)
        elif isinstance(n, ast.Starred):
            visit(n.value)

    visit(node)
    seen: set[str] = set()
    uniq = []
    for a in out:
        key = json.dumps(a, sort_keys=True)
        if key not in seen:
            seen.add(key)
            uniq.append(a)
    return uniq


class _Summarizer:
    """One module -> one JSON-able summary dict."""

    def __init__(self, relpath: str, source: str, tree: ast.Module,
                 module: str, is_package: bool):
        self.ctx = ModuleContext(relpath, source, tree)
        self.module = module
        self.is_package = is_package
        if is_package:
            self.package = module
        else:
            self.package = module.rsplit(".", 1)[0] if "." in module else ""
        self.aliases: dict[str, str] = {}      # whole-tree, absolute
        self.exports: dict[str, str] = {}      # top-level imports only
        self.deps: list[dict] = []
        self._collect_imports(tree)

    # -- imports ----------------------------------------------------------
    def _abs_from(self, node: ast.ImportFrom) -> str:
        mod = node.module or ""
        if not node.level:
            return mod
        parts = self.package.split(".") if self.package else []
        up = node.level - 1
        if up:
            parts = parts[:-up] if up < len(parts) else []
        if mod:
            parts = parts + mod.split(".")
        return ".".join(parts)

    def _collect_imports(self, tree: ast.Module) -> None:
        top = set(tree.body)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".", 1)[0]
                    target = a.name if a.asname else a.name.split(".", 1)[0]
                    self.aliases[local] = target
                    if node in top:
                        self.exports[local] = target
                    self.deps.append({"m": a.name, "n": None})
            elif isinstance(node, ast.ImportFrom):
                abs_mod = self._abs_from(node)
                for a in node.names:
                    if a.name == "*":
                        self.deps.append({"m": abs_mod, "n": None})
                        continue
                    target = f"{abs_mod}.{a.name}" if abs_mod else a.name
                    self.aliases[a.asname or a.name] = target
                    if node in top:
                        self.exports[a.asname or a.name] = target
                    self.deps.append({"m": abs_mod, "n": a.name})

    # -- expression resolution (absolute aliases) -------------------------
    def resolve(self, node: ast.AST) -> str | None:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def callable_target(self, node: ast.AST) -> tuple[str | None, int]:
        """(dotted target, positional args consumed by partial wrapping)."""
        consumed = 0
        while isinstance(node, ast.Call):
            fn = self.resolve(node.func)
            if resolves_to(fn, "functools.partial", "partial") and node.args:
                consumed += len(node.args) - 1
                node = node.args[0]
                continue
            return fn, consumed
        return self.resolve(node), consumed

    # -- summary ----------------------------------------------------------
    def summarize(self) -> dict:
        ctx = self.ctx
        functions: dict[str, dict] = {}
        by_name: dict[str, list[str]] = {}
        for info in ctx.functions:
            functions[info.qualname] = self._func_summary(info)
            name = functions[info.qualname]["name"]
            by_name.setdefault(name, []).append(info.qualname)

        summary = {
            "module": self.module,
            "relpath": ctx.relpath,
            "package": self.is_package,
            "exports": self.exports,
            "deps": self.deps,
            "constants": self._constants(ctx.tree),
            "tables": self._dispatch_tables(ctx.tree),
            "functions": functions,
            "names": by_name,
        }
        summary.update(self._jit_entries(ctx, functions))
        summary.update(self._sharding_facts(ctx))
        return summary

    def _func_summary(self, info: FunctionInfo) -> dict:
        node = info.node
        if isinstance(node, ast.Lambda):
            a = node.args
            name = info.qualname.rsplit(".", 1)[-1]
        else:
            a = node.args
            name = node.name
        npos = len(a.posonlyargs) + len(a.args)
        first = ([arg.arg for arg in a.posonlyargs + a.args] or [""])[0]
        calls, methods = self._calls(info)
        from chiaswarm_tpu.analysis.rules.host_sync import sync_sites

        sync = [{"line": n.lineno, "col": n.col_offset, "what": what}
                for n, what in sync_sites(self.ctx, info)]
        return {
            "name": name,
            "line": getattr(node, "lineno", 0),
            "npos": npos,
            "ndef": len(a.defaults),
            "vararg": a.vararg is not None,
            "pargs": [arg.arg for arg in a.posonlyargs + a.args],
            "kwonly": [arg.arg for arg in a.kwonlyargs],
            "kwreq": [arg.arg for arg, d in zip(a.kwonlyargs, a.kw_defaults)
                      if d is None],
            "meth": first in ("self", "cls"),
            "calls": calls,
            "methods": methods,
            "sync": sync,
        }

    def _calls(self, info: FunctionInfo) -> tuple[list[dict], list[str]]:
        calls: list[dict] = []
        methods: list[str] = []
        # function-local dispatch dicts: ``handlers = {...}`` followed by
        # ``handlers[k](...)`` expands inline to a call per member
        local_tables: dict[str, list[str]] = {}
        for node in own_nodes(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                entries = self._table_entries(node.value)
                if entries:
                    local_tables[node.targets[0].id] = entries
        for node in own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("self", "cls")):
                methods.append(func.attr)
                continue
            if isinstance(func, ast.Subscript) and isinstance(
                    func.value, (ast.Name, ast.Attribute)):
                # a dispatch-table call: TABLE[key](...) — expand local
                # tables inline; module-level (possibly cross-module)
                # tables defer to the index via an "@table:" target
                dotted = self.resolve(func.value)
                if dotted and not dotted.startswith(("self.", "cls.")):
                    if dotted in local_tables:
                        for t in local_tables[dotted]:
                            calls.append({"t": t, "line": node.lineno,
                                          "np": len(node.args), "kw": {},
                                          "poslits": {}})
                    else:
                        calls.append({"t": "@table:" + dotted,
                                      "line": node.lineno,
                                      "np": len(node.args), "kw": {},
                                      "poslits": {}})
                continue
            target, consumed = self.callable_target(node)
            if target is None:
                continue
            kw: dict[str, Any] = {}
            for k in node.keywords:
                if k.arg is None:
                    continue
                refs = _axisref(k.value, self.resolve) \
                    if not isinstance(k.value, (ast.Lambda, ast.Call)) else []
                kw[k.arg] = refs[0] if len(refs) == 1 else None
            poslits = {str(i): arg.value for i, arg in enumerate(node.args)
                       if isinstance(arg, ast.Constant)
                       and isinstance(arg.value, str)}
            # NB: callable_target already unwraps functools.partial, so a
            # `partial(f, ..., axis_name=X)` expression records as a call
            # to `f` with X among its kwargs — exactly what the R10
            # binding check wants, and a conservative call edge (the
            # partial object exists to be invoked)
            calls.append({
                "t": target, "line": node.lineno, "np": len(node.args),
                "kw": kw, "poslits": poslits,
            })
        return calls, sorted(set(methods))

    def _table_entries(self, value: ast.AST) -> list[str] | None:
        """Function references in a dict-literal dispatch table, or None
        when ``value`` is not one. A table is a dict whose VALUES are
        (at least one) resolvable callables — keys are routing strings
        and don't matter for reachability."""
        if not isinstance(value, ast.Dict):
            return None
        targets: list[str] = []
        for v in value.values:
            if isinstance(v, (ast.Name, ast.Attribute)):
                dotted = self.resolve(v)
                if dotted and not dotted.startswith(("self.", "cls.")):
                    targets.append(dotted)
        return sorted(set(targets)) or None

    def _dispatch_tables(self, tree: ast.Module) -> dict:
        """Module-level ``TABLE = {"key": fn, ...}`` dispatch dicts:
        name -> resolved function refs. ``TABLE[key](...)`` calls were
        unresolvable edges before (the ROADMAP lint-extension candidate)
        — R9's call graph now expands them to every member."""
        tables: dict[str, list[str]] = {}
        for node in tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            if target is None:
                continue
            entries = self._table_entries(value)
            if entries:
                tables[target] = entries
        return tables

    def _constants(self, tree: ast.Module) -> dict:
        consts: dict[str, Any] = {}
        for node in tree.body:
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            if target is None:
                continue
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                consts[target] = value.value
            elif isinstance(value, (ast.Tuple, ast.List)):
                refs = []
                ok = True
                for elt in value.elts:
                    r = _axisref(elt, self.resolve)
                    if len(r) == 1:
                        refs.append(r[0])
                    else:
                        ok = False
                        break
                if ok and refs:
                    consts[target] = refs
        return consts

    # -- jit entry points --------------------------------------------------
    def _jit_entries(self, ctx: ModuleContext, functions: dict) -> dict:
        wrappers = JIT_WRAPPERS + TRACED_WRAPPERS
        roots: list[str] = []
        refs: list[dict] = []
        by_name: dict[str, list[str]] = {}
        by_node: dict[ast.AST, str] = {}
        for info in ctx.functions:
            by_node[info.node] = info.qualname
            if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(info.node.name, []).append(info.qualname)
                for dec in info.node.decorator_list:
                    t, _ = self.callable_target(dec)
                    if resolves_to(t, *wrappers):
                        roots.append(info.qualname)
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            t, _ = self.callable_target(call)
            if not resolves_to(t, *wrappers):
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Lambda) and arg in by_node:
                    roots.append(by_node[arg])
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    dotted = self.resolve(arg)
                    if dotted is None:
                        continue
                    if dotted.startswith(("self.", "cls.")):
                        roots.extend(by_name.get(dotted.split(".")[1], []))
                    elif "." in dotted:
                        refs.append({"t": dotted, "line": call.lineno,
                                     "symbol": ctx.symbol_for(call)})
                    else:
                        local = by_name.get(dotted, [])
                        roots.extend(local)
        return {"jit_roots": sorted(set(roots)), "jit_refs": refs}

    # -- sharding facts ----------------------------------------------------
    def _sharding_facts(self, ctx: ModuleContext) -> dict:
        mesh_axes: list[dict] = []
        specs: list[dict] = []
        shard_maps: list[dict] = []
        collectives: list[dict] = []

        for name, value in self._constants(ctx.tree).items():
            if isinstance(value, str) and name.endswith("_AXIS"):
                mesh_axes.append({"lit": value})
            elif isinstance(value, list) and name.endswith("AXES"):
                mesh_axes.extend(value)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t, consumed = self.callable_target(node)
            if t is None:
                continue
            loc = {"line": node.lineno, "col": node.col_offset,
                   "symbol": ctx.symbol_for(node)}
            kwargs = {k.arg: k.value for k in node.keywords if k.arg}

            if resolves_to(t, *_MESH_NAMES):
                ax = kwargs.get("axis_names")
                if ax is None and len(node.args) >= 2:
                    ax = node.args[1]
                if ax is not None:
                    mesh_axes.extend(_axisref(ax, self.resolve))
            elif resolves_to(t, *_MESHSPEC_NAMES):
                shape = kwargs.get("shape")
                if shape is None and node.args:
                    shape = node.args[0]
                if isinstance(shape, ast.Dict):
                    for key in shape.keys:
                        if key is not None:
                            mesh_axes.extend(_axisref(key, self.resolve))
            elif resolves_to(t, *_SPEC_NAMES):
                axes: list[dict] = []
                for arg in node.args:
                    axes.extend(_axisref(arg, self.resolve))
                specs.append({**loc, "arity": len(node.args), "axes": axes})
            elif resolves_to(t, "shard_map"):
                callee = None
                pconsumed = 0
                if node.args:
                    callee, pconsumed = self.callable_target(node.args[0])
                rec: dict[str, Any] = {**loc, "callee": callee,
                                       "pconsumed": pconsumed,
                                       "in_arity": None}
                if node.args and isinstance(node.args[0], ast.Lambda):
                    la = node.args[0].args
                    rec["lam"] = {
                        "npos": len(la.posonlyargs) + len(la.args),
                        "ndef": len(la.defaults),
                        "vararg": la.vararg is not None,
                    }
                in_specs = kwargs.get("in_specs")
                if isinstance(in_specs, (ast.Tuple, ast.List)):
                    rec["in_arity"] = len(in_specs.elts)
                shard_maps.append(rec)
            else:
                resolved_op = None
                for op in _COLLECTIVES:
                    if resolves_to(t, op):
                        resolved_op = op
                        break
                if resolved_op is None:
                    continue
                ax = kwargs.get("axis_name")
                if ax is None:
                    pos = _COLLECTIVES[resolved_op] - consumed
                    if 0 <= pos < len(node.args):
                        ax = node.args[pos]
                axis: dict | None = None
                if ax is not None:
                    # a Name may be a parameter of any ENCLOSING function
                    # (ring_attention's scan body reads the closure's
                    # axis_name): the binding check targets the owner
                    owner = None
                    if isinstance(ax, ast.Name):
                        info = ctx.enclosing_function(node)
                        while info is not None and owner is None:
                            fnode = info.node
                            a_ = fnode.args
                            names = {arg.arg for arg in a_.posonlyargs
                                     + a_.args + a_.kwonlyargs}
                            if ax.id in names:
                                owner = info.qualname
                            info = info.parent
                    if owner is not None:
                        axis = {"param": ax.id, "owner": owner}
                    else:
                        refs = _axisref(ax, self.resolve)
                        axis = refs[0] if len(refs) == 1 else None
                collectives.append({
                    **loc, "op": resolved_op, "axis": axis,
                    "func": ctx.symbol_for(node),
                })
        return {"mesh_axes": mesh_axes, "specs": specs,
                "shard_maps": shard_maps, "collectives": collectives}


def summarize_module(relpath: str, source: str, tree: ast.Module,
                     module: str, is_package: bool) -> dict:
    return _Summarizer(relpath, source, tree, module, is_package).summarize()


# ---------------------------------------------------------------------------
# the index


class ProjectIndex:
    """Whole-program view over per-module summaries.

    ``funcs`` keys are ``(module, qualname)`` pairs; chains reported by the
    interprocedural rules are lists of ``(relpath, line, dotted-qualname)``
    hops suitable for :attr:`Finding.chain`.
    """

    def __init__(self, summaries: dict[str, dict]):
        self.summaries = summaries              # relpath -> summary
        self.modules: dict[str, str] = {}       # module name -> relpath
        for rel in sorted(summaries):
            mod = summaries[rel]["module"]
            self.modules.setdefault(mod, rel)
        self.funcs: dict[tuple[str, str], dict] = {}
        for rel, s in summaries.items():
            for qual, f in s["functions"].items():
                self.funcs[(s["module"], qual)] = f
        self._edges: dict[tuple[str, str], set[tuple[str, str]]] | None = None
        self._redges: dict[str, set[str]] | None = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_sources(cls, entries: Iterable[tuple[str, str, ast.Module]],
                     ) -> "ProjectIndex":
        summaries = {}
        for relpath, source, tree in entries:
            rel = relpath.replace(os.sep, "/")
            module, is_pkg = module_name_from_relpath(rel)
            summaries[rel] = summarize_module(rel, source, tree, module,
                                              is_pkg)
        return cls(summaries)

    @classmethod
    def build(cls, files: Iterable[tuple[str, str]],
              cache_path: str | None = None) -> "ProjectIndex":
        """Index (abspath, relpath) files, reusing cached summaries for
        files whose content hash is unchanged. Unparseable files are
        skipped here — the per-file driver already reports them."""
        cache: dict[str, Any] = {}
        if cache_path:
            try:
                with open(cache_path, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
                if isinstance(doc, dict) and doc.get("schema") == SCHEMA:
                    cache = doc.get("files", {})
            except (OSError, ValueError):
                cache = {}
        summaries: dict[str, dict] = {}
        fresh: dict[str, Any] = {}
        dirty = False
        for abspath, rel in files:
            rel = rel.replace(os.sep, "/")
            try:
                with open(abspath, "rb") as fh:
                    raw = fh.read()
            except OSError:
                continue
            digest = hashlib.sha256(raw).hexdigest()
            entry = cache.get(rel)
            if entry and entry.get("hash") == digest:
                summaries[rel] = entry["summary"]
                fresh[rel] = entry
                continue
            try:
                source = raw.decode("utf-8")
                tree = ast.parse(source, filename=rel)
            except (SyntaxError, UnicodeDecodeError, ValueError):
                dirty = True
                continue
            module, is_pkg = module_name_for_file(abspath)
            summary = summarize_module(rel, source, tree, module, is_pkg)
            summaries[rel] = summary
            fresh[rel] = {"hash": digest, "summary": summary}
            dirty = True
        if cache_path and dirty:
            # MERGE into the existing cache — a path-subset run must not
            # evict the rest of the repo's warm entries — and drop
            # entries whose files vanished so the cache cannot grow
            # without bound across renames/deletions
            merged = dict(cache)
            merged.update(fresh)
            base = os.path.dirname(os.path.abspath(cache_path))
            merged = {rel: e for rel, e in merged.items()
                      if rel in fresh
                      or os.path.exists(os.path.join(base, rel))}
            try:
                tmp = cache_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"schema": SCHEMA, "files": merged}, fh)
                os.replace(tmp, cache_path)
            except OSError:
                pass  # read-only checkout (CI): the cache is an optimization
        return cls(summaries)

    # -- symbol resolution -------------------------------------------------
    def resolve_qual(self, dotted: str,
                     _seen: frozenset = frozenset()) -> tuple[str, Any] | None:
        """Resolve a dotted name to ("func", (module, qualname)),
        ("const", value), ("tuple", [...]) or ("module", name), following
        top-level re-exports across modules."""
        if dotted in _seen:
            return None
        _seen = _seen | {dotted}
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                rest = parts[i:]
                break
        else:
            return None
        if not rest:
            return ("module", mod)
        s = self.summaries[self.modules[mod]]
        qual = ".".join(rest)
        if qual in s["functions"]:
            return ("func", (mod, qual))
        head = rest[0]
        if head in s["constants"] and len(rest) == 1:
            v = s["constants"][head]
            return ("const", v) if isinstance(v, str) else ("tuple", v)
        target = s["exports"].get(head)
        if target is not None:
            follow = ".".join([target] + rest[1:])
            return self.resolve_qual(follow, _seen)
        return None

    def resolve_axis(self, ref: dict | None, module: str) -> str | None:
        """An axis reference ({"lit"}/{"ref"}) to its string, following
        constants; None when it cannot be proven."""
        if not ref:
            return None
        if "lit" in ref:
            return ref["lit"]
        dotted = ref.get("ref")
        if not dotted:
            return None
        if "." not in dotted:
            rel = self.modules.get(module)
            if rel is not None:
                s = self.summaries[rel]
                v = s["constants"].get(dotted)
                if isinstance(v, str):
                    return v
                target = s["exports"].get(dotted)
                if target:
                    dotted = target
                else:
                    return None
            else:
                return None
        got = self.resolve_qual(dotted)
        if got and got[0] == "const":
            return got[1]
        return None

    # -- call graph --------------------------------------------------------
    def func_targets(self, module: str, target: str) -> list[tuple[str, str]]:
        s = self.summaries.get(self.modules.get(module, ""), None)
        out: list[tuple[str, str]] = []
        if "." not in target:
            if s is not None:
                out = [(module, q) for q in s["names"].get(target, [])]
            return out
        got = self.resolve_qual(target)
        if got and got[0] == "func":
            return [got[1]]
        return []

    def table_targets(self, module: str,
                      dotted: str) -> list[tuple[str, str]]:
        """Members of a dispatch table referenced as ``dotted`` from
        ``module`` — the expansion of an ``@table:`` call target. The
        table may live in this module (bare name) or in another one
        (import-aliased dotted path), and its VALUES were resolved in
        the OWNING module's namespace at summarize time."""
        owner, name = module, dotted
        if "." in dotted:
            head, _, tail = dotted.rpartition(".")
            got = self.resolve_qual(head)
            if got is None or got[0] != "module":
                return []
            owner, name = got[1], tail
        s = self.summaries.get(self.modules.get(owner, ""), None)
        entries = (s or {}).get("tables", {}).get(name, [])
        out: list[tuple[str, str]] = []
        for target in entries:
            out.extend(self.func_targets(owner, target))
        return out

    def edges(self) -> dict[tuple[str, str], set[tuple[str, str]]]:
        if self._edges is not None:
            return self._edges
        edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for (module, qual), f in self.funcs.items():
            out: set[tuple[str, str]] = set()
            for call in f["calls"]:
                if not call["t"]:
                    continue
                if call["t"].startswith("@table:"):
                    # workload dispatch dicts (R9 extension): a
                    # TABLE[key](...) call conservatively reaches every
                    # member of the table
                    out.update(self.table_targets(
                        module, call["t"][len("@table:"):]))
                else:
                    out.update(self.func_targets(module, call["t"]))
            for name in f["methods"]:
                out.update(self.func_targets(module, name))
            out.discard((module, qual))
            edges[(module, qual)] = out
        self._edges = edges
        return edges

    def jit_entry_points(self) -> dict[tuple[str, str], list[dict]]:
        """Functions entering trace, mapped to their REGISTRATION sites:
        ``{"module", "relpath", "line", "symbol"}`` per decoration site /
        jit()/scan() call site. R9 uses the registering modules to
        delimit R1's jurisdiction (a body registered from another module
        is invisible to the per-file pass even when its whole chain stays
        in one file) and prepends a cross-module registration site to the
        reported chain."""
        roots: dict[tuple[str, str], list[dict]] = {}
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            module = s["module"]
            for qual in s["jit_roots"]:
                if (module, qual) in self.funcs:
                    f = self.funcs[(module, qual)]
                    roots.setdefault((module, qual), []).append(
                        {"module": module, "relpath": rel,
                         "line": f["line"], "symbol": qual})
            for ref in s["jit_refs"]:
                got = self.resolve_qual(ref["t"])
                if got and got[0] == "func":
                    roots.setdefault(got[1], []).append(
                        {"module": module, "relpath": rel,
                         "line": ref["line"],
                         "symbol": ref.get("symbol", "<module>")})
        return roots

    def reach_with_parents(self, roots: Iterable[tuple[str, str]],
                           ) -> dict[tuple[str, str],
                                     tuple[str, str] | None]:
        """BFS over the call graph; maps every reachable function to its
        first-discovered caller (None for roots) for chain rebuilding."""
        edges = self.edges()
        parent: dict[tuple[str, str], tuple[str, str] | None] = {}
        frontier: collections.deque = collections.deque()
        for r in sorted(set(roots)):
            parent[r] = None
            frontier.append(r)
        while frontier:
            node = frontier.popleft()
            for nxt in sorted(edges.get(node, ())):
                if nxt not in parent:
                    parent[nxt] = node
                    frontier.append(nxt)
        return parent

    def chain(self, parent: dict, node: tuple[str, str],
              ) -> tuple[tuple[str, int, str], ...]:
        """Root -> ... -> node as Finding.chain hops."""
        hops: list[tuple[str, int, str]] = []
        cur: tuple[str, str] | None = node
        while cur is not None:
            f = self.funcs[cur]
            rel = self.modules[cur[0]]
            hops.append((rel, f["line"], f"{cur[0]}.{cur[1]}"))
            cur = parent.get(cur)
        return tuple(reversed(hops))

    def callers_of(self, target: tuple[str, str]) -> list[tuple[str, str]]:
        return sorted(n for n, outs in self.edges().items()
                      if target in outs)

    # -- import graph ------------------------------------------------------
    def module_deps(self, rel: str) -> set[str]:
        """relpaths this file imports (project-internal only)."""
        out: set[str] = set()
        for dep in self.summaries[rel]["deps"]:
            cands = []
            if dep["m"]:
                cands.append(dep["m"])
            if dep["n"]:
                # `from m import n` may name a submodule, not a symbol
                cands.append(f"{dep['m']}.{dep['n']}" if dep["m"]
                             else dep["n"])
            for cand in cands:
                hit = self.modules.get(cand)
                if hit is not None:
                    out.add(hit)
        out.discard(rel)
        return out

    def reverse_closure(self, seeds: Iterable[str]) -> set[str]:
        """``seeds`` (relpaths) plus every file that transitively imports
        one of them — the set a pre-commit run must re-lint."""
        rdeps: dict[str, set[str]] = {}
        for rel in self.summaries:
            for dep in self.module_deps(rel):
                rdeps.setdefault(dep, set()).add(rel)
        out = {s for s in seeds if s in self.summaries}
        frontier = list(out)
        while frontier:
            rel = frontier.pop()
            for dependent in rdeps.get(rel, ()):
                if dependent not in out:
                    out.add(dependent)
                    frontier.append(dependent)
        return out

    # -- misc --------------------------------------------------------------
    def axis_universe(self) -> dict[str, list[str]]:
        """axis name -> relpaths of the modules whose mesh constructs bind
        it. Empty when the project defines no meshes at all."""
        out: dict[str, list[str]] = {}
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            for ref in s["mesh_axes"]:
                v = self.resolve_axis(ref, s["module"])
                if v is not None and rel not in out.setdefault(v, []):
                    out[v].append(rel)
        return out
