"""Baseline file: grandfathered findings that may only ever shrink.

The baseline is a JSON list of finding identities (rule, path, symbol,
message — deliberately line-number-free so it survives unrelated edits)
plus a ``count`` for identical findings repeated in one function.

Lifecycle:

- adopt a rule: run with ``--write-baseline`` to grandfather what exists
- new code: any finding whose identity is not baselined FAILS the run
- fix a baselined finding: its entry goes *stale*; stale entries FAIL
  under ``--strict`` (the CI mode) until the entry is deleted — so the
  file ratchets monotonically toward empty and can never hide new debt.
"""

from __future__ import annotations

import collections
import dataclasses
import json
from typing import Callable, Iterable

from chiaswarm_tpu.analysis.core import Finding

DEFAULT_BASELINE_NAME = ".swarmlint-baseline.json"
_SCHEMA = 1


@dataclasses.dataclass
class Baseline:
    """Suppression set with multiplicity-aware matching."""

    entries: dict[str, int] = dataclasses.field(default_factory=dict)

    def split(self, findings: Iterable[Finding],
              in_scope: Callable[[str], bool] | None = None,
              ) -> tuple[list[Finding], list[Finding], list[str]]:
        """Partition into (new, suppressed, stale_keys).

        A key suppresses at most ``count`` identical findings; the excess
        surface as new. A key matching FEWER findings than its count is
        stale — including a partial fix of a multi-count entry, otherwise
        the leftover headroom would silently suppress a reintroduced
        violation later. Staleness is only reported when ``in_scope``
        says this run actually looked for the entry (a --select or
        path-subset run must not misreport entries it never re-checked).
        """
        counts: collections.Counter[str] = collections.Counter()
        new: list[Finding] = []
        suppressed: list[Finding] = []
        for f in findings:
            key = f.baseline_key
            counts[key] += 1
            if counts[key] <= self.entries.get(key, 0):
                suppressed.append(f)
            else:
                new.append(f)
        stale = [k for k, n in self.entries.items()
                 if counts[k] < n and (in_scope is None or in_scope(k))]
        return new, suppressed, sorted(stale)


def _key_fields(key: str) -> dict[str, str]:
    rule, path, symbol, message = key.split("::", 3)
    return {"rule": rule, "path": path, "symbol": symbol, "message": message}


def load_baseline(path: str) -> Baseline:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return Baseline()
    if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
        raise ValueError(f"{path}: not a swarmlint baseline (schema "
                         f"{_SCHEMA} expected)")
    entries: dict[str, int] = {}
    for e in doc.get("findings", []):
        key = "::".join((e["rule"], e["path"], e["symbol"], e["message"]))
        entries[key] = int(e.get("count", 1))
    return Baseline(entries)


def write_baseline(path: str, findings: Iterable[Finding],
                   keep: dict[str, int] | None = None) -> int:
    """Serialize current findings as the new baseline; returns entry count.

    ``keep`` carries existing entries that this run did NOT re-check
    (out-of-scope paths on a partial run) — they are preserved verbatim
    so a path-subset ``--write-baseline`` cannot erase them."""
    counts: collections.Counter[str] = collections.Counter(
        f.baseline_key for f in findings)
    for key, n in (keep or {}).items():
        counts.setdefault(key, n)
    doc = {
        "schema": _SCHEMA,
        "comment": "grandfathered swarmlint findings — may only shrink; "
                   "regenerate with python -m chiaswarm_tpu.analysis "
                   "--write-baseline after FIXING findings, never to "
                   "suppress new ones",
        "findings": [
            {**_key_fields(key), "count": n}
            for key, n in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(counts)
