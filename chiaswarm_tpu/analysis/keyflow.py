"""keyflow — trace-input provenance proofs over the swarmflow index.

The worker's serving model rests on one invariant: an executable-cache
slot is keyed by *everything that changes the traced program*. PRs 11,
12 and 18 each re-enforced it by hand ("fold into ``static_cache_key``
only when enabled", with byte-identical-key gates) — keyflow makes the
bug class statically checkable, the FOURTH interpreter over the
swarmflow project index (swarmflow builds the call graph, shardflow
replays value sharding, raceflow replays thread topology, keyflow
replays *which inputs the trace consumed and whether the key knows*).
Pure stdlib, no jax import.

Three passes, four rules (plus R6's interprocedural face):

**Keyed set.** The cache-key builders (``static_cache_key``,
``cache_fingerprint``, ``artifact_cache_key`` — matched by name, so a
fixture-local builder works) seed a BFS over the call graph; every
env-var name mentioned in that closure — a SCREAMING_SNAKE string
literal, a resolved env read, or a string/tuple constant of a builder's
module (``_TRACE_ENV_KNOBS``) — is *folded into the key*. Conservative
in the safe direction: over-approximating the keyed set can only silence
a finding, never invent one.

**Traced reach.** Functions reachable from the jit entry points
(decorated roots + ``toplevel_jit``/``jax.jit``/``scan`` registration
sites) run at trace time: an env read there is baked into the
executable. Build scopes — factory closures handed to
``cached_executable``/``get_or_create``, and the jit roots themselves —
are the lexical subset where the read provably happens at most once per
slot.

Rules (all conservative: dynamic env names and unresolvable targets are
silent):

- **R18 unkeyed-trace-input** — a trace-affecting env read (direct, or
  an import-time read frozen into a module constant that a traced
  function loads) whose var is NOT in the keyed set: a knob flip
  silently serves the stale executable from a warm slot. The live
  ``CHIASWARM_ATTENTION`` bug that motivated this pass.
- **R19 frozen-env-reread** — an env read lexically inside a build/
  traced scope, written as if live-per-call but executed once per cache
  slot; hoist to dispatch or fold into the key.
- **R20 unstable-key-component** — ``id()``/``hash()``/``repr()``
  flowing into the PERSISTENT key surface (``cache_fingerprint``/
  ``artifact_cache_key``): stable within a process, different across
  processes, so a shipped AOT artifact keyed by one can never hit.
  In-process ``static_cache_key`` owners may keep ``id(self.c)`` — that
  is the point of having two surfaces.
- **R21 cache-tag-collision** — two distinct build callables sharing an
  (owner, tag, statics-vocabulary) triple: their programs land in one
  slot and the second build silently serves the first's executable.

Findings carry full entry→sink chains (jit registration site → call
path → env read / key site) rendered in text/JSON/SARIF exactly like
R9–R17, and key into the shrink-only baseline. Suppressions:
``# swarmlens: allow-<kind>`` markers (``allow-unkeyed-trace-input``,
``allow-frozen-env-reread``, ``allow-unstable-key``,
``allow-tag-collision``) on the finding line or the comment line above,
each stating the invariant that makes the freeze safe.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable

from chiaswarm_tpu.analysis.core import Finding

if TYPE_CHECKING:  # pragma: no cover
    from chiaswarm_tpu.analysis.project import ProjectIndex

R18 = "unkeyed-trace-input"
R19 = "frozen-env-reread"
R20 = "unstable-key-component"
R21 = "cache-tag-collision"
R6 = "recompile-hazard"  # the interprocedural face rides this name

_BUILDER_NAMES = frozenset(
    {"static_cache_key", "cache_fingerprint", "artifact_cache_key"})


def _enc_names(enc) -> Iterable[str]:
    """Bare names referenced by a flow-IR expression tree."""
    if not isinstance(enc, dict):
        return
    if "n" in enc:
        yield enc["n"]
    for sub in enc.get("u") or ():
        yield from _enc_names(sub)
    for sub in enc.get("x") or ():
        yield from _enc_names(sub)
    for sub in (enc.get("kwx") or {}).values():
        yield from _enc_names(sub)


class KeyflowAnalysis:
    """Run the keyed-set + traced-reach passes and evaluate R18–R21
    (and R6's interprocedural face).

    Build once per index via :func:`results`; ``findings`` holds every
    violation, tagged with the rule name, sorted by location.
    """

    def __init__(self, index: "ProjectIndex"):
        self.index = index
        self.findings: list[Finding] = []
        self._collect()
        self._keyed_set()
        self._traced_reach()
        self._build_scopes()
        self._r18()
        self._r19()
        self._r20()
        self._r21()
        self._r6_interproc()
        seen: set[tuple] = set()
        uniq: list[Finding] = []
        for f in self.findings:
            k = (f.rule, f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                uniq.append(f)
        self.findings = sorted(
            uniq, key=lambda f: (f.path, f.line, f.rule, f.message))

    # -- facts -------------------------------------------------------------
    def _collect(self) -> None:
        idx = self.index
        self.kf: dict[str, dict] = {}                # rel -> keyflow facts
        self.allow: dict[str, dict[str, set[int]]] = {}
        self.lits: dict[tuple[str, str], list[str]] = {}
        for rel in sorted(idx.summaries):
            s = idx.summaries[rel]
            facts = s.get("keyflow") or {}
            self.kf[rel] = facts
            self.allow[rel] = {k: set(v) for k, v in
                               (facts.get("allow") or {}).items()}
            for qual, names in (facts.get("lits") or {}).items():
                self.lits[(s["module"], qual)] = names

    def _allowed(self, rel: str, kind: str, *lines: int) -> bool:
        lns = self.allow.get(rel, {}).get(kind, set())
        return any(ln in lns for ln in lines)

    def _var_of(self, rec: dict, module: str) -> str | None:
        """The literal env-var name a read site targets, following
        constant references across modules; None = dynamic (silent)."""
        if "var" in rec:
            return rec["var"]
        ref = rec.get("ref")
        if not ref:
            return None
        return self.index.resolve_axis({"ref": ref}, module)

    # -- keyed set ---------------------------------------------------------
    def _keyed_set(self) -> None:
        idx = self.index
        builders = sorted(
            (m, q) for (m, q) in idx.funcs
            if q.rsplit(".", 1)[-1] in _BUILDER_NAMES)
        self.builder_mods = sorted({m for m, _ in builders})
        breach = idx.reach_with_parents(builders)
        keyed: set[str] = set()
        for node in breach:
            keyed.update(self.lits.get(node, ()))
        # env reads executed while BUILDING the key are folded by
        # construction (numerics.fingerprint, quantize.activations_format)
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            for rec in self.kf[rel].get("env") or ():
                if (s["module"], rec.get("fn")) in breach:
                    v = self._var_of(rec, s["module"])
                    if v:
                        keyed.add(v)
        # a builder module's own constant vocabulary: the
        # _TRACE_ENV_KNOBS tuple and single-name constants
        from chiaswarm_tpu.analysis.project import _ENV_NAME_RE

        for m in self.builder_mods:
            rel = idx.modules.get(m)
            if rel is None:
                continue
            for v in (idx.summaries[rel].get("constants") or {}).values():
                names = ([v] if isinstance(v, str)
                         else [r.get("lit") for r in v
                               if isinstance(r, dict)])
                keyed.update(n for n in names
                             if n and _ENV_NAME_RE.match(n))
        self.keyed = keyed

    # -- traced reach ------------------------------------------------------
    def _traced_reach(self) -> None:
        self.roots = self.index.jit_entry_points()
        self.tparent = self.index.reach_with_parents(self.roots)

    def _entry_chain(self, func: tuple[str, str],
                     sink: tuple[str, int, str],
                     ) -> tuple[tuple[str, int, str], ...]:
        """jit registration site -> call path -> sink."""
        hops = list(self.index.chain(self.tparent, func))
        cur = func
        while self.tparent.get(cur) is not None:
            cur = self.tparent[cur]
        regs = self.roots.get(cur) or []
        if regs and hops:
            r = regs[0]
            if (r["relpath"], r["line"]) != (hops[0][0], hops[0][1]):
                hops.insert(0, (r["relpath"], r["line"], r["symbol"]))
        if not hops or (hops[-1][0], hops[-1][1]) != (sink[0], sink[1]):
            hops.append(sink)
        return tuple(hops)

    # -- build scopes ------------------------------------------------------
    def _build_scopes(self) -> None:
        """Function -> registration hop for every build closure (factory
        arguments of cached_executable/get_or_create) and every jit
        root: the scopes where an env read runs once per cache slot."""
        idx = self.index
        scopes: dict[tuple[str, str], tuple[str, int, str]] = {}
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            for b in self.kf[rel].get("builds") or ():
                hop = (rel, b["ln"], f"{m}.{b['fn']}")
                target = b["b"]
                if target.startswith("<lambda>:"):
                    qual = target[len("<lambda>:"):]
                    if (m, qual) in idx.funcs:
                        scopes.setdefault((m, qual), hop)
                    continue
                if target.startswith(("self.", "cls.")):
                    name = target.split(".")[1]
                    for qual in (s.get("names") or {}).get(name, ()):
                        scopes.setdefault((m, qual), hop)
                    continue
                for node in idx.func_targets(m, target):
                    scopes.setdefault(node, hop)
        for node, regs in self.roots.items():
            if regs:
                r = regs[0]
                scopes.setdefault(
                    node, (r["relpath"], r["line"], r["symbol"]))
        self.scopes = scopes

    # -- R18 unkeyed-trace-input -------------------------------------------
    def _r18(self) -> None:
        idx = self.index
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            # direct reads on the traced path
            for rec in self.kf[rel].get("env") or ():
                fn = rec.get("fn", "<module>")
                node = (m, fn)
                if fn == "<module>" or node not in self.tparent:
                    continue
                if node in self.scopes:
                    continue  # lexical build scope: R19's jurisdiction
                var = self._var_of(rec, m)
                if var is None or var in self.keyed:
                    continue
                if self._allowed(rel, "unkeyed", rec["ln"]):
                    continue
                sink = (rel, rec["ln"], f"{m}.{fn}")
                self.findings.append(Finding(
                    rule=R18, path=rel, line=rec["ln"], col=0,
                    message=(
                        f"trace-affecting env knob {var} is read at "
                        f"trace time but never folded into the "
                        f"executable-cache key — a warm cache hit "
                        f"serves the stale program after a knob flip; "
                        f"fold it into static_cache_key only-when-set, "
                        f"or mark the deliberate freeze"),
                    symbol=fn,
                    chain=self._entry_chain(node, sink)))
            # import-time reads frozen into module constants that a
            # traced function loads
            for name, cons in (self.kf[rel].get("consts") or {}).items():
                users = self._const_users(m, name)
                if not users:
                    continue
                for var in cons["vars"]:
                    if var in self.keyed:
                        continue
                    if self._allowed(rel, "unkeyed", cons["ln"]):
                        continue
                    user = users[0]
                    sink = (rel, cons["ln"], f"{m}.{name}")
                    self.findings.append(Finding(
                        rule=R18, path=rel, line=cons["ln"], col=0,
                        message=(
                            f"env knob {var} is frozen into module "
                            f"constant {name} at import and traced "
                            f"through {user[1]} — neither a knob flip "
                            f"nor a restartless reload can reach a "
                            f"warm slot; fold it into the cache key "
                            f"only-when-set, or mark the deliberate "
                            f"freeze"),
                        symbol="<module>",
                        chain=self._entry_chain(user, sink)))

    def _const_users(self, module: str, name: str,
                     ) -> list[tuple[str, str]]:
        """Traced-reach functions of ``module`` that load the bare
        module-global ``name`` (params and locally assigned names
        excluded — those shadow the global)."""
        out: list[tuple[str, str]] = []
        for node in sorted(self.tparent):
            if node[0] != module:
                continue
            f = self.index.funcs.get(node)
            if f is None or name in f["pargs"] or name in f["kwonly"]:
                continue
            assigned = {t for step in f["flow"]
                        for t in step.get("a") or ()}
            if name in assigned:
                continue
            for step in f["flow"]:
                found = False
                for key in ("e", "r"):
                    if key in step and name in _enc_names(step[key]):
                        out.append(node)
                        found = True
                        break
                if found:
                    break
        return out

    # -- R19 frozen-env-reread ---------------------------------------------
    def _r19(self) -> None:
        idx = self.index
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            for rec in self.kf[rel].get("env") or ():
                fn = rec.get("fn", "<module>")
                node = (m, fn)
                hop = self.scopes.get(node)
                if hop is None:
                    continue
                var = self._var_of(rec, m)
                if var is None or var in self.keyed:
                    continue
                if self._allowed(rel, "frozen", rec["ln"]):
                    continue
                sink = (rel, rec["ln"], f"{m}.{fn}")
                chain = (hop, sink) if (hop[0], hop[1]) != (rel, rec["ln"]) \
                    else (sink,)
                self.findings.append(Finding(
                    rule=R19, path=rel, line=rec["ln"], col=0,
                    message=(
                        f"env knob {var} is read inside a build/traced "
                        f"scope — it executes once per cache slot, so a "
                        f"warm hit freezes the value the code treats as "
                        f"live-per-call; hoist the read to dispatch or "
                        f"fold it into the cache key"),
                    symbol=fn, chain=chain))

    # -- R20 unstable-key-component ----------------------------------------
    def _r20(self) -> None:
        idx = self.index
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            for site in self.kf[rel].get("fpsites") or ():
                for part in site.get("unstable") or ():
                    ln = part.get("ln", site["ln"])
                    if self._allowed(rel, "unstable", ln, site["ln"]):
                        continue
                    what = part["op"] + "(" + (part.get("arg") or "…") + ")"
                    fn = site.get("fn", "<module>")
                    self.findings.append(Finding(
                        rule=R20, path=rel, line=ln, col=0,
                        message=(
                            f"process-unstable component {what} flows "
                            f"into the persistent key surface "
                            f"({site.get('b', 'cache_fingerprint')}) — "
                            f"id()/hash()/repr() differ across "
                            f"processes, so a shipped artifact keyed by "
                            f"it can never hit; use stable content "
                            f"(model name, dtype, config tuple). "
                            f"In-process static_cache_key owners may "
                            f"keep id()"),
                        symbol=fn,
                        chain=((rel, site["ln"], f"{m}.{fn}"),)))

    # -- R21 cache-tag-collision -------------------------------------------
    def _r21(self) -> None:
        idx = self.index
        groups: dict[tuple, list[tuple[str, str, dict]]] = {}
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            for site in self.kf[rel].get("keysites") or ():
                tag = site.get("tag")
                skeys = site.get("skeys")
                if tag is None or skeys is None:
                    continue
                canon = self._owner_key(site, m)
                if canon is None:
                    continue
                groups.setdefault(
                    (canon, tag, tuple(skeys)), []).append((rel, m, site))
        for (canon, tag, skeys), sites in sorted(groups.items()):
            quals = {(m, site["fn"]) for _, m, site in sites}
            if len(quals) < 2:
                continue
            sites = sorted(sites, key=lambda t: (t[0], t[2]["ln"]))
            first_rel, first_m, first = sites[0]
            fhop = (first_rel, first["ln"],
                    f"{first_m}.{first['fn']}")
            for rel, m, site in sites[1:]:
                if site["fn"] == first["fn"] and m == first_m:
                    continue
                if self._allowed(rel, "collision", site["ln"]) \
                        or self._allowed(first_rel, "collision",
                                         first["ln"]):
                    continue
                self.findings.append(Finding(
                    rule=R21, path=rel, line=site["ln"], col=0,
                    message=(
                        f"distinct build callables share the "
                        f"executable-cache vocabulary (owner {canon[1]}, "
                        f"tag {tag!r}, statics {sorted(skeys)}) with "
                        f"{first_m}.{first['fn']} — their programs "
                        f"collide in one slot and the second build "
                        f"silently serves the first's executable; give "
                        f"each program a distinct tag"),
                    symbol=site["fn"],
                    chain=(fhop,
                           (rel, site["ln"], f"{m}.{site['fn']}"))))

    @staticmethod
    def _owner_key(site: dict, module: str) -> tuple | None:
        o = site.get("owner") or {}
        k = o.get("k")
        fn = site.get("fn", "<module>")
        if k == "lit":
            return ("lit", o["v"])
        if k == "ref":
            v = o["v"]
            return ("ref", v if "." in v else f"{module}.{v}")
        if k in ("self", "selfcall"):
            if "." not in fn:
                return None
            return ("self", f"{module}.{fn.split('.')[0]}.{o['v']}")
        return None

    # -- R6 interprocedural face -------------------------------------------
    def _r6_interproc(self) -> None:
        idx = self.index
        for rel in sorted(self.kf):
            s = idx.summaries[rel]
            m = s["module"]
            for site in self.kf[rel].get("keysites") or ():
                fn = site.get("fn", "<module>")
                for ent in site.get("svals") or ():
                    if ent.get("t") == "display" and not ent.get("allc"):
                        kind = ("non-hashable container"
                                if not ent.get("h") else "container")
                        self.findings.append(Finding(
                            rule=R6, path=rel, line=site["ln"], col=0,
                            message=(
                                f"unbounded-cardinality {kind} built "
                                f"from varying values fills static key "
                                f"{ent.get('k')!r} — every distinct "
                                f"content is a fresh executable slot "
                                f"and a fresh XLA compile; bucket the "
                                f"values or key on a bounded enum"),
                            symbol=fn))
                    elif ent.get("t") == "param":
                        self._r6_param(rel, m, site, ent)

    def _r6_param(self, rel: str, module: str, site: dict,
                  ent: dict) -> None:
        """A key-site parameter fed straight into the static dict: walk
        one caller hop — a caller passing a raw request attribute
        without bucketing reopens the compile-per-job failure mode."""
        idx = self.index
        fn = site.get("fn", "<module>")
        func = (module, fn)
        f = idx.funcs.get(func)
        if f is None or ent["p"] not in f["pargs"]:
            return
        pidx = f["pargs"].index(ent["p"])
        for caller in idx.callers_of(func):
            cf = idx.funcs[caller]
            if (cf.get("r6") or {}).get("b"):
                continue  # the caller buckets; cardinality is bounded
            crel = idx.modules[caller[0]]
            for call in cf["calls"]:
                t = call.get("t")
                if not t or t.startswith("@table:"):
                    continue
                if func not in idx.func_targets(caller[0], t):
                    continue
                attr = (call.get("rattr") or {}).get(str(pidx))
                if attr is None:
                    attr = (call.get("rattrk") or {}).get(ent["p"])
                if attr is None:
                    continue
                self.findings.append(Finding(
                    rule=R6, path=crel, line=call["line"], col=0,
                    message=(
                        f"raw request attribute .{attr} flows through "
                        f"{fn}'s parameter {ent['p']!r} into the static "
                        f"cache-key vocabulary — every distinct value "
                        f"is a fresh XLA compile; snap through "
                        f"compile_cache.bucket_image_size/bucket_batch "
                        f"at the call site"),
                    symbol=caller[1],
                    chain=(
                        (crel, call["line"],
                         f"{caller[0]}.{caller[1]}"),
                        (idx.modules[module], f["line"],
                         f"{module}.{fn}"),
                        (rel, site["ln"], f"{module}.{fn}"))))


def results(index: "ProjectIndex") -> KeyflowAnalysis:
    """The keyflow analysis for ``index``, computed once and cached on
    the index (R18–R21 plus R6's interprocedural face each filter the
    same findings list)."""
    cached = getattr(index, "_keyflow", None)
    if cached is None:
        cached = KeyflowAnalysis(index)
        index._keyflow = cached
    return cached
