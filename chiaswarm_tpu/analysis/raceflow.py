"""raceflow — whole-program concurrency proofs over the swarmflow index.

The reference worker is one asyncio process; this reproduction runs six
concurrent execution roots (event loop, executor job threads, watchdog
monitor, lane decode threads, residency prefetch daemon, loadgen probe).
Every concurrency bug the repo has shipped-and-fixed — PR 3's live-numpy
/ in-flight-array container hazards, PR 10's fired-vs-condemn race — was
found dynamically. raceflow encodes those disciplines statically, the
third interpreter over the swarmflow project index (swarmflow builds the
call graph, shardflow replays value sharding, raceflow replays *who runs
where holding what*). Pure stdlib, no jax import.

Two passes, four rules:

**Thread topology.** Every statically resolvable execution-root site —
``threading.Thread(target=...)``, ``loop.run_in_executor(...)``,
``io_callback``/``pure_callback`` bodies, ``weakref.finalize`` callbacks
— becomes a distinct root; all ``async def`` functions (plus
``create_task`` targets) seed the shared *event loop* root. A BFS from
each root's seeds over the call graph yields, per function, the set of
roots that may execute it. Two accesses race only when their root sets
contain two *different* roots; a single-rooted program is silent by
construction.

**Lock discipline.** ``with self._lock:`` regions (locks resolved
through instance attributes, module globals, imports and — for the
lock-order pass — parameters), with ``Condition(self._lock)`` aliasing
folded to the underlying lock. Every summarized event carries the
held-lock stack at that program point.

Rules (all conservative: unresolvable targets/locks are silent):

- **R14 cross-thread-device-handoff** — a value produced by a jit/lane
  dispatch is published into a shared container/attribute without
  ``block_until_ready``/``.copy()``/``np.asarray`` while another root
  consumes that state: PR 3's two container hazards as lint findings.
  The fix is producer-side (ROADMAP: sync at admission; resolve futures
  only once outputs are resident).
- **R15 unguarded-shared-mutation** — RacerD-style mostly-locked
  inference: state written under a lock on some path but mutated
  lock-free on a concurrent root's path (``__init__`` writes exempt —
  the object is not yet shared).
- **R16 lock-order-inversion** — ABBA: lock A held while taking B in
  one root, B held while taking A in another (interprocedural, with
  one-level substitution of locks passed as parameters).
- **R17 await-or-blocking-under-lock** — ``await`` (or blocking I/O)
  while holding a ``threading`` lock parks the event loop with the lock
  held; plus ``time.sleep``/socket I/O lexically inside a coroutine or
  in a sync function a coroutine calls directly.

Findings carry full root→site chains (the spawn site, then the call
path) rendered in text/JSON/SARIF exactly like R9–R13, and key into the
shrink-only baseline. Suppressions: ``# swarmlens: allow-<kind>``
markers (``allow-cross-thread-handoff``, ``allow-unguarded-mutation``,
``allow-lock-order``, ``allow-blocking-under-lock``) on the finding line
or the comment line above, each stating the invariant that makes the
site safe.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

from chiaswarm_tpu.analysis.core import Finding

if TYPE_CHECKING:  # pragma: no cover
    from chiaswarm_tpu.analysis.project import ProjectIndex

R14 = "cross-thread-device-handoff"
R15 = "unguarded-shared-mutation"
R16 = "lock-order-inversion"
R17 = "await-or-blocking-under-lock"

_ROOT_NOUN = {"thread": "thread root", "exec": "executor root",
              "cb": "host-callback root", "fin": "finalizer root"}
#: lock kinds an OS thread can park on (asyncio primitives excluded)
_THREADING_KINDS = frozenset({"lock", "rlock", "cond", "sem"})


@dataclasses.dataclass(frozen=True)
class Root:
    rid: str
    label: str
    kind: str
    #: spawn-site chain hop (relpath, line, qualname); None for the loop
    hop: tuple[str, int, str] | None


class RaceflowAnalysis:
    """Run the topology + lock passes and evaluate R14–R17.

    Build once per index via :func:`results`; ``findings`` holds every
    violation, tagged with the rule name, sorted by location.
    """

    def __init__(self, index: "ProjectIndex"):
        self.index = index
        self.findings: list[Finding] = []
        self._collect()
        self._topology()
        self._entry_held()
        self._shared()
        self._r14()
        self._r15()
        self._r16()
        self._r17()
        self.findings.sort(
            key=lambda f: (f.path, f.line, f.rule, f.message))

    # -- facts -------------------------------------------------------------
    def _collect(self) -> None:
        idx = self.index
        self.conc: dict[str, dict] = {}          # module -> conc summary
        self.lockkind: dict[str, str] = {}       # canonical token -> kind
        self.lockalias: dict[str, str] = {}      # Condition(sibling) folds
        self.allow: dict[str, dict[str, set[int]]] = {}
        for rel in sorted(idx.summaries):
            s = idx.summaries[rel]
            m = s["module"]
            conc = s.get("conc") or {}
            self.conc[m] = conc
            for d in conc.get("lockdefs", ()):
                tok = (f"{m}.{d['cls']}.{d['attr']}" if d["cls"]
                       else f"{m}.{d['attr']}")
                self.lockkind[tok] = d["kind"]
                if d.get("alias"):
                    self.lockalias[tok] = (
                        f"{m}.{d['cls']}.{d['alias']}" if d["cls"]
                        else f"{m}.{d['alias']}")
            al = conc.get("allow") or {}
            if al:
                self.allow[rel] = {k: set(v) for k, v in al.items()}

    def _allowed(self, rel: str, kind: str, *lines: int) -> bool:
        have = self.allow.get(rel, {}).get(kind, ())
        return any(ln in have for ln in lines)

    def canon(self, tok: str, module: str) -> str | None:
        """Canonical lock identity for a summarizer token (alias-chased);
        None for parameter locks and unresolvable expressions. A
        canonical token is *known* iff it appears in ``lockkind`` —
        unknown tokens still suppress "unguarded" verdicts (holding
        *something* is not lock-free) but never serve as evidence."""
        if tok.startswith(("s:", "g:")):
            out = f"{module}.{tok[2:]}"
        elif tok.startswith("d:"):
            out = tok[2:]
        else:  # p: parameter — meaningful only via call-site substitution
            return None
        for _ in range(4):
            nxt = self.lockalias.get(out)
            if nxt is None:
                break
            out = nxt
        return out

    def _known_held(self, held: list[str], module: str,
                    kinds: frozenset | None = None) -> list[str]:
        out = []
        for h in held:
            c = self.canon(h, module)
            if c and c in self.lockkind and (
                    kinds is None or self.lockkind[c] in kinds):
                out.append(c)
        return out

    # -- thread topology ---------------------------------------------------
    def _topology(self) -> None:
        idx = self.index
        self.roots: dict[str, Root] = {}
        self.parent: dict[str, dict] = {}
        seeds: dict[str, set] = {}
        loop_seeds = {key for key, f in idx.funcs.items()
                      if f.get("isasync")}
        for m in sorted(self.conc):
            rel = idx.modules.get(m)
            for sp in self.conc[m].get("spawns", ()):
                targets = self._spawn_targets(m, sp["t"])
                if not targets:
                    continue
                if sp["k"] == "task":
                    # coroutines scheduled on the one event loop — same
                    # root as every other coroutine
                    loop_seeds.update(targets)
                    continue
                rid = f"{sp['k']}:{m}.{sp['symbol']}:{sp['t']}"
                if rid not in self.roots:
                    self.roots[rid] = Root(
                        rid=rid, kind=sp["k"],
                        label=(f"the {_ROOT_NOUN[sp['k']]} spawned in "
                               f"{m}.{sp['symbol']}"),
                        hop=(rel, sp["ln"], f"{m}.{sp['symbol']}"))
                seeds.setdefault(rid, set()).update(targets)
        if loop_seeds:
            self.roots["loop"] = Root("loop", "the event loop", "loop",
                                      None)
            seeds["loop"] = loop_seeds
        self.rootfns: dict[tuple[str, str], set[str]] = {}
        self.nonloop_seeds: set[tuple[str, str]] = set()
        for rid in sorted(seeds):
            if rid != "loop":
                self.nonloop_seeds |= seeds[rid]
            par = idx.reach_with_parents(seeds[rid])
            self.parent[rid] = par
            for key in par:
                self.rootfns.setdefault(key, set()).add(rid)

    def _spawn_targets(self, module: str,
                       t: str) -> list[tuple[str, str]]:
        idx = self.index
        if t.startswith(("self.", "cls.")):
            name = t.split(".", 1)[1]
            if "." in name:
                return []
            rel = idx.modules.get(module)
            if rel is None:
                return []
            quals = idx.summaries[rel]["names"].get(name, [])
            return [(module, q) for q in quals]
        return list(idx.func_targets(module, t))

    def _concurrent(self, ra: set[str],
                    rb: set[str]) -> tuple[str, str] | None:
        for x in sorted(ra):
            for y in sorted(rb):
                if x != y:
                    return x, y
        return None

    def _chain(self, rid: str | None, key: tuple[str, str],
               sink: tuple[str, int, str]) -> tuple:
        hops: list[tuple[str, int, str]] = []
        if rid is not None:
            root = self.roots[rid]
            if root.hop is not None:
                hops.append(root.hop)
            par = self.parent.get(rid, {})
            if key in par:
                hops.extend(self.index.chain(par, key))
        if not hops or hops[-1][:2] != sink[:2]:
            hops.append(sink)
        return tuple(hops)

    # -- caller-held lock context --------------------------------------------
    def _entry_held(self) -> None:
        """Locks provably held at ENTRY to each function: the
        intersection, over every recorded call site, of the caller's
        lexical held stack plus the caller's own entry set (fixpoint).
        This is how ``_evict_locked``-style helpers — lock taken by the
        caller, never lexically in the helper — get guard credit instead
        of a false R15. Over-approximates guarding only (a caller the
        summarizer could not resolve contributes nothing to the
        intersection-breaking side), so it can hide a racy helper whose
        unguarded caller is invisible — never invent a race."""
        callers: dict[tuple[str, str],
                      list[tuple[tuple[str, str], frozenset]]] = {}
        for m in sorted(self.conc):
            for q, f in (self.conc[m].get("funcs") or {}).items():
                key = (m, q)
                for cw in f.get("cw", ()):
                    helds = frozenset(self._known_held(cw["held"], m))
                    for g in self._call_targets(m, cw["t"]):
                        if g != key:
                            callers.setdefault(g, []).append((key, helds))
        entry: dict[tuple[str, str], set[str] | None] = {
            g: None for g in callers}  # None = top (not yet constrained)
        for _ in range(32):
            changed = False
            for g, recs in callers.items():
                acc: set[str] | None = None
                for ck, helds in recs:
                    ev = entry.get(ck)  # callers outside the map: empty
                    base = set() if ck not in entry else ev
                    if base is None:
                        continue  # still top: identity for intersection
                    contrib = helds | base
                    acc = set(contrib) if acc is None else acc & contrib
                if acc != entry[g] and acc is not None:
                    entry[g] = acc
                    changed = True
            if not changed:
                break
        self.entry: dict[tuple[str, str], set[str]] = {
            g: v for g, v in entry.items() if v}

    def _entry_locks(self, key: tuple[str, str],
                     kinds: frozenset | None = None) -> list[str]:
        out = self.entry.get(key, ())
        if kinds is None:
            return sorted(out)
        return sorted(c for c in out if self.lockkind.get(c) in kinds)

    # -- shared-state table --------------------------------------------------
    def _shared(self) -> None:
        idx = self.index
        self.acc: dict[str, list[dict]] = {}
        self.hand: dict[str, list[dict]] = {}
        for m in sorted(self.conc):
            rel = idx.modules.get(m)
            funcs = self.conc[m].get("funcs") or {}
            for q in sorted(funcs):
                f = funcs[q]
                key = (m, q)
                roots = self.rootfns.get(key, set())
                sym = f"{m}.{q}"
                entry = self._entry_locks(key)
                for at in f.get("at", ()):
                    tok = f"{m}.{at['n'][2:]}"
                    self.acc.setdefault(tok, []).append({
                        "key": key, "rel": rel, "q": q, "sym": sym,
                        "w": at["w"], "ln": at["ln"], "roots": roots,
                        "held_any": bool(at["held"]) or bool(entry),
                        "heldc": sorted(set(
                            self._known_held(at["held"], m)) | set(entry)),
                    })
                for ho in f.get("ho", ()):
                    tok = f"{m}.{ho['n'][2:]}"
                    self.hand.setdefault(tok, []).append({
                        "key": key, "rel": rel, "q": q, "sym": sym,
                        "ln": ho["ln"], "via": ho["via"], "roots": roots,
                    })

    # -- R14 -----------------------------------------------------------------
    def _r14(self) -> None:
        for tok in sorted(self.hand):
            consumers = self.acc.get(tok, [])
            attr = tok.rsplit(".", 1)[-1]
            for ho in self.hand[tok]:
                if not ho["roots"]:
                    continue
                if self._allowed(ho["rel"], "handoff", ho["ln"]):
                    continue
                hit = None
                for a in consumers:
                    if (a["rel"], a["ln"]) == (ho["rel"], ho["ln"]):
                        continue
                    pair = self._concurrent(ho["roots"], a["roots"])
                    if pair:
                        hit = (a, pair)
                        break
                if hit is None:
                    continue
                a, (rp, rc) = hit
                msg = (f"in-flight device value from {ho['via']}(...) is "
                       f"published to shared '{attr}' without "
                       f"block_until_ready/.copy()/np.asarray — "
                       f"{self.roots[rc].label} consumes it in {a['sym']} "
                       f"while the dispatch may still be running; sync "
                       f"before publishing (producer-side, the PR-3 "
                       f"container discipline)")
                self.findings.append(Finding(
                    rule=R14, path=ho["rel"], line=ho["ln"], col=0,
                    message=msg, symbol=ho["q"],
                    chain=self._chain(rp, ho["key"],
                                      (ho["rel"], ho["ln"], ho["sym"]))))

    # -- R15 -----------------------------------------------------------------
    def _r15(self) -> None:
        for tok in sorted(self.acc):
            accs = self.acc[tok]
            locked_writes = [a for a in accs if a["w"] and a["heldc"]]
            if not locked_writes:
                continue
            attr = tok.rsplit(".", 1)[-1]
            lw = locked_writes[0]
            lock = lw["heldc"][0]
            for w in accs:
                if not w["w"] or w["held_any"] or not w["roots"]:
                    continue
                if w["q"].rsplit(".", 1)[-1] in ("__init__", "__new__",
                                                 "__del__"):
                    continue  # not yet / no longer shared
                if self._allowed(w["rel"], "unguarded", w["ln"]):
                    continue
                hit = None
                for o in accs:
                    if (o["rel"], o["ln"]) == (w["rel"], w["ln"]):
                        continue
                    pair = self._concurrent(w["roots"], o["roots"])
                    if pair:
                        hit = pair
                        break
                if hit is None:
                    continue
                rw, ro = hit
                msg = (f"'{attr}' is written under {lock} in "
                       f"{lw['sym']} but mutated lock-free here on "
                       f"{self.roots[rw].label} while "
                       f"{self.roots[ro].label} also touches it — "
                       f"mostly-locked discipline violated; take the "
                       f"lock or state the invariant with "
                       f"'swarmlens: allow-unguarded-mutation'")
                self.findings.append(Finding(
                    rule=R15, path=w["rel"], line=w["ln"], col=0,
                    message=msg, symbol=w["q"],
                    chain=self._chain(sorted(w["roots"])[0], w["key"],
                                      (w["rel"], w["ln"], w["sym"]))))

    # -- R16 -----------------------------------------------------------------
    def _acquire_closure(self) -> dict[tuple[str, str], set[str]]:
        """Canonical locks each function may acquire, transitively over
        the call graph (parameter locks excluded — substituted only at
        direct call sites)."""
        own: dict[tuple[str, str], set[str]] = {}
        for m in self.conc:
            for q, f in (self.conc[m].get("funcs") or {}).items():
                toks = {c for a in f.get("acq", ())
                        for c in self._known_held([a["l"]], m)}
                if toks:
                    own[(m, q)] = toks
        clos = {k: set(v) for k, v in own.items()}
        edges = self.index.edges()
        for _ in range(32):  # fixpoint; depth-bounded for safety
            changed = False
            for key, outs in edges.items():
                acc = clos.get(key, set())
                for o in outs:
                    extra = clos.get(o)
                    if extra and not extra <= acc:
                        clos[key] = acc = acc | extra
                        changed = True
            if not changed:
                break
        return clos

    def _r16(self) -> None:
        idx = self.index
        clos = self._acquire_closure()
        edges_out: dict[tuple[str, str], list[dict]] = {}

        def add(a: str, b: str, site: dict) -> None:
            if a != b:
                edges_out.setdefault((a, b), []).append(site)

        for m in sorted(self.conc):
            rel = idx.modules.get(m)
            for q, f in sorted((self.conc[m].get("funcs") or {}).items()):
                key = (m, q)
                roots = self.rootfns.get(key, set())
                base = {"rel": rel, "key": key, "sym": f"{m}.{q}",
                        "roots": roots}
                entry = self._entry_locks(key)
                for a in f.get("acq", ()):
                    inner = self._known_held([a["l"]], m)
                    if not inner:
                        continue
                    for h in set(self._known_held(a["held"], m)) | set(
                            entry):
                        add(h, inner[0], {**base, "ln": a["ln"]})
                for cw in f.get("cw", ()):
                    helds = sorted(set(self._known_held(cw["held"], m))
                                   | set(entry))
                    if not helds:
                        continue
                    for g in self._call_targets(m, cw["t"]):
                        acquired = set(clos.get(g, ()))
                        acquired |= self._substituted(m, cw, g)
                        for c in acquired:
                            for h in helds:
                                add(h, c, {**base, "ln": cw["ln"]})
        seen_pairs: set[tuple[str, str]] = set()
        for (a, b) in sorted(edges_out):
            if a > b or (b, a) not in edges_out:
                continue
            if (a, b) in seen_pairs:
                continue
            seen_pairs.add((a, b))
            hit = None
            for s1 in edges_out[(a, b)]:
                for s2 in edges_out[(b, a)]:
                    if (s1["rel"], s1["ln"]) == (s2["rel"], s2["ln"]):
                        continue
                    if self._allowed(s1["rel"], "lockorder", s1["ln"]) \
                            or self._allowed(s2["rel"], "lockorder",
                                             s2["ln"]):
                        continue
                    pair = self._concurrent(s1["roots"], s2["roots"])
                    if pair:
                        hit = (s1, s2, pair)
                        break
                if hit:
                    break
            if hit is None:
                continue
            s1, s2, (r1, r2) = hit
            msg = (f"lock-order inversion: {a} is held while taking {b} "
                   f"here, but {s2['sym']} takes {b} then {a} — "
                   f"{self.roots[r1].label} and {self.roots[r2].label} "
                   f"can deadlock (ABBA); pick one global order")
            chain = self._chain(r1, s1["key"],
                                (s1["rel"], s1["ln"], s1["sym"]))
            chain = chain + ((s2["rel"], s2["ln"], s2["sym"]),)
            self.findings.append(Finding(
                rule=R16, path=s1["rel"], line=s1["ln"], col=0,
                message=msg, symbol=s1["key"][1], chain=chain))

    def _call_targets(self, module: str, t: str) -> list[tuple[str, str]]:
        if t.startswith(("self.", "cls.")):
            return self._spawn_targets(module, t)
        return list(self.index.func_targets(module, t))

    def _substituted(self, module: str, cw: dict,
                     g: tuple[str, str]) -> set[str]:
        """Locks a callee acquires through a parameter, resolved with the
        caller's argument tokens (one level)."""
        la = cw.get("la") or {}
        if not la:
            return set()
        gf = self.index.funcs.get(g)
        gconc = self.conc.get(g[0], {}).get("funcs", {}).get(g[1])
        if gf is None or gconc is None:
            return set()
        offset = 1 if (cw["t"].startswith(("self.", "cls."))
                       and gf.get("meth")) else 0
        out: set[str] = set()
        for a in gconc.get("acq", ()):
            if not a["l"].startswith("p:"):
                continue
            pname = a["l"][2:]
            if pname not in gf["pargs"]:
                continue
            pos = gf["pargs"].index(pname) - offset
            tok = la.get(str(pos))
            if tok is None:
                continue
            out.update(self._known_held([tok], module))
        return out

    # -- R17 -----------------------------------------------------------------
    def _r17(self) -> None:
        idx = self.index
        edges = idx.edges()
        async_caller: dict[tuple[str, str], tuple[str, str]] = {}
        for key, f in idx.funcs.items():
            if f.get("isasync"):
                for o in sorted(edges.get(key, ())):
                    async_caller.setdefault(o, key)
        for m in sorted(self.conc):
            rel = idx.modules.get(m)
            for q, f in sorted((self.conc[m].get("funcs") or {}).items()):
                key = (m, q)
                fn = idx.funcs.get(key)
                isasync = bool(fn and fn.get("isasync"))
                sym = f"{m}.{q}"
                roots = self.rootfns.get(key, set())
                rid = ("loop" if "loop" in roots
                       else sorted(roots)[0] if roots else None)
                entry = self._entry_locks(key, _THREADING_KINDS)
                for aw in f.get("aw", ()):
                    locks = (self._known_held(aw["held"], m,
                                              _THREADING_KINDS)
                             + entry)
                    if not locks:
                        continue
                    if self._allowed(rel, "blocking", aw["ln"]):
                        continue
                    msg = (f"'await' while holding threading lock "
                           f"{locks[0]} — the coroutine parks with the "
                           f"lock held and every root contending for it "
                           f"deadlocks against the event loop; release "
                           f"before awaiting")
                    self.findings.append(Finding(
                        rule=R17, path=rel, line=aw["ln"], col=0,
                        message=msg, symbol=q,
                        chain=self._chain(rid, key,
                                          (rel, aw["ln"], sym))))
                for bl in f.get("bl", ()):
                    if self._allowed(rel, "blocking", bl["ln"]):
                        continue
                    locks = (self._known_held(bl["held"], m,
                                              _THREADING_KINDS)
                             + entry)
                    if locks and (roots or isasync):
                        msg = (f"blocking call {bl['t']} while holding "
                               f"{locks[0]} — every other root "
                               f"contending for the lock waits out the "
                               f"sleep/IO; move it outside the region")
                    elif isasync:
                        msg = (f"blocking call {bl['t']} inside "
                               f"coroutine {sym} stalls the event loop "
                               f"(and every lane poll behind it) — use "
                               f"the asyncio equivalent or "
                               f"run_in_executor")
                    elif key in async_caller and "loop" in roots \
                            and key not in self.nonloop_seeds:
                        # a function some site explicitly dispatches to
                        # a thread/executor is exempt: the "direct call"
                        # edge is usually that registration site itself
                        ac = async_caller[key]
                        msg = (f"blocking call {bl['t']} in {sym}, "
                               f"called directly from coroutine "
                               f"{ac[0]}.{ac[1]} — stalls the event "
                               f"loop; use the asyncio equivalent or "
                               f"run_in_executor")
                    else:
                        continue
                    self.findings.append(Finding(
                        rule=R17, path=rel, line=bl["ln"], col=0,
                        message=msg, symbol=q,
                        chain=self._chain(rid, key,
                                          (rel, bl["ln"], sym))))


def results(index: "ProjectIndex") -> RaceflowAnalysis:
    """Analysis for ``index``, computed once and cached on it — R14–R17
    share one topology/lock-discipline run per lint invocation."""
    cached = getattr(index, "_raceflow", None)
    if cached is None:
        cached = RaceflowAnalysis(index)
        index._raceflow = cached
    return cached
