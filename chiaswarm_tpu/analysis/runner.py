"""Shared driver behind the CLI and the tier-1 ``tests/test_lint.py`` gate."""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

from chiaswarm_tpu.analysis import baseline as baseline_mod
from chiaswarm_tpu.analysis.core import Finding, all_rules, analyze_paths, get_rule


#: the repo surfaces the lint gate covers — single source of truth for
#: the CLI default paths, tests/test_lint.py, and the CI job
DEFAULT_LINT_PATHS = ("chiaswarm_tpu", "tests", "tools",
                      "bench.py", "__graft_entry__.py")


@dataclasses.dataclass
class RunResult:
    exit_code: int
    new: list[Finding]
    suppressed: list[Finding]
    stale: list[str]
    errors: list[str]
    report: str


def repo_root() -> str:
    """The directory findings are reported relative to (and where the
    default baseline lives): the repo checkout containing this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _scope_checker(paths: list[str], root: str,
                   rules) -> Callable[[str], bool]:
    """Predicate: did THIS run (its paths + selected rules) re-check the
    file/rule a baseline key refers to? Out-of-scope entries are neither
    stale nor erasable."""
    rule_names = {r.name for r in rules}
    prefixes: list[str] = []
    exact: set[str] = set()
    for p in paths:
        rel = os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        if rel == ".":
            prefixes.append("")  # whole repo
        elif os.path.isdir(p):
            prefixes.append(rel.rstrip("/") + "/")
        else:
            exact.add(rel)

    def in_scope(key: str) -> bool:
        rule, path, _, _ = key.split("::", 3)
        return rule in rule_names and (
            path in exact or any(path.startswith(px) for px in prefixes))

    return in_scope


def run(paths: list[str],
        *,
        baseline_path: str | None = None,
        strict: bool = False,
        select: list[str] | None = None,
        write_baseline: bool = False,
        root: str | None = None) -> RunResult:
    """Lint ``paths``; returns exit code 0 when clean.

    - new (non-baselined) findings -> exit 1
    - stale baseline entries -> exit 1 under ``strict``, warning otherwise
    - unparseable files -> exit 2
    """
    root = root or repo_root()
    if baseline_path is None:
        baseline_path = os.path.join(
            root, baseline_mod.DEFAULT_BASELINE_NAME)
    try:
        rules = [get_rule(s) for s in select] if select else all_rules()
    except KeyError as exc:
        # typo'd --select is bad input (exit 2), not lint findings
        return RunResult(2, [], [], [], [str(exc)],
                         f"swarmlint: {exc.args[0]}")

    errors: list[str] = []
    error_paths: set[str] = set()

    def record_error(rel: str, exc: Exception) -> None:
        errors.append(f"{rel}: {exc}")
        error_paths.add(rel)

    findings = analyze_paths(paths, rules, root=root, on_error=record_error)
    scope = _scope_checker(paths, root, rules)

    def in_scope(key: str) -> bool:
        # a file that failed to parse was NOT re-checked: its baseline
        # entries are neither stale nor safe to drop on a rewrite
        return scope(key) and key.split("::", 3)[1] not in error_paths

    if write_baseline:
        if select:
            return RunResult(
                2, [], [], [], ["--write-baseline with --select would "
                                "erase other rules' entries"],
                "swarmlint: refusing --write-baseline with --select — a "
                "partial rule run cannot regenerate the full baseline")
        if errors:
            # refuse to write a silently incomplete baseline
            report = "\n".join(
                [f"error: {e}" for e in errors]
                + ["swarmlint: baseline NOT written — fix unparseable "
                   "files first"])
            return RunResult(2, [], [], [], errors, report)
        # preserve entries this run never re-checked (out-of-scope paths)
        try:
            existing = baseline_mod.load_baseline(baseline_path).entries
        except Exception as exc:
            return RunResult(
                2, [], [], [], [f"{baseline_path}: {exc}"],
                f"swarmlint: cannot read existing baseline "
                f"{baseline_path}: {exc}")
        keep = {k: n for k, n in existing.items() if not in_scope(k)}
        n = baseline_mod.write_baseline(baseline_path, findings, keep)
        report = (f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} "
                  f"({len(findings)} findings, {len(keep)} out-of-scope "
                  f"kept) to {baseline_path}")
        return RunResult(0, [], findings, [], errors, report)

    try:
        bl = baseline_mod.load_baseline(baseline_path)
    except Exception as exc:
        # truncated / merge-conflicted / wrong-schema baseline: bad
        # input (exit 2), not a lint failure
        return RunResult(
            2, [], [], [], [f"{baseline_path}: {exc}"],
            f"swarmlint: unreadable baseline {baseline_path}: {exc}")
    new, suppressed, stale = bl.split(findings, in_scope=in_scope)

    lines: list[str] = [f.render() for f in new]
    for key in stale:
        lines.append(
            f"stale baseline entry (finding no longer present — delete it "
            f"from {os.path.basename(baseline_path)}): {key}")
    for e in errors:
        lines.append(f"error: {e}")
    lines.append(
        f"swarmlint: {len(new)} finding{'s' if len(new) != 1 else ''}, "
        f"{len(suppressed)} baselined, {len(stale)} stale baseline "
        f"entr{'y' if len(stale) == 1 else 'ies'}")

    exit_code = 0
    if errors:
        exit_code = 2
    elif new or (strict and stale):
        exit_code = 1
    return RunResult(exit_code, new, suppressed, stale, errors,
                     "\n".join(lines))
